#!/usr/bin/env python
"""Per-phase timing smoke test of the JaxScorer on the current device."""
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

t0 = time.perf_counter()
from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.utils.example_gen import generate_test

print(f"import {time.perf_counter()-t0:.1f}s", flush=True)

truth, reads = generate_test(4, 200, 16, 0.01, seed=0)
cfg = CdwfaConfigBuilder().min_count(4).build()
t0 = time.perf_counter()
sc = JaxScorer(reads, cfg)
h = sc.root(np.ones(16, dtype=bool))
print(f"init+root {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter()
s = sc.push(h, truth[:1])
print(f"first push {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter()
s = sc.push(h, truth[:2])
print(f"second push {time.perf_counter()-t0:.3f}s", flush=True)
t0 = time.perf_counter()
steps, code, app, _stats, _recs = sc.run_extend(
    h, truth[:2], 10**9, 2**31 - 1, 0, 4, False, 100
)
print(
    f"first run_extend (compile) {time.perf_counter()-t0:.1f}s "
    f"steps={steps} code={code}",
    flush=True,
)
cons = truth[:2] + app
t0 = time.perf_counter()
steps, code, app, _stats, _recs = sc.run_extend(
    h, cons, 10**9, 2**31 - 1, 0, 4, False, 100
)
print(
    f"second run_extend {time.perf_counter()-t0:.3f}s steps={steps} "
    f"code={code}",
    flush=True,
)
t0 = time.perf_counter()
eds = sc.finalized_eds(h, cons + app)
print(f"finalize {time.perf_counter()-t0:.3f}s", flush=True)
