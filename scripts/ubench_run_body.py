"""Microbenchmark the run-kernel while-loop on the live device.

Measures, at north-star shapes (R=256, band E=216 -> W=434, A=5):
  1. an EMPTY while loop (pure loop-control floor),
  2. col-step only,
  3. col-step + stats/vote fold (the real body shape),
  4. the same with a K-chunked body (K col+stats per iteration)
to locate the per-iteration overhead and the win from chunking.
"""
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from waffle_con_tpu.ops.jax_scorer import (
    _col_step_u, _stats_core_u, _init_col, INF,
)

R, E, A = 256, 216, 5
W = 2 * E + 2
L = 10_000
STEPS = 2_000

rng = np.random.default_rng(0)
reads = rng.integers(0, 4, size=(R, L)).astype(np.int32)
reads_pad = jnp.asarray(
    np.concatenate([np.zeros((R, W), np.int32), reads], axis=1)
)
rlen = jnp.full((R,), L, jnp.int32)
off = jnp.zeros((R,), jnp.int32)
act = jnp.ones((R,), bool)
wc = jnp.int32(-2)
et = jnp.asarray(False)
off0 = jnp.int32(0)

D0, e0, rmin0, er0 = _init_col(off, act, rlen, jnp.int32(E), W)


def timeit(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    best = 1e9
    for _ in range(3):
        t = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t)
    return best, out


@jax.jit
def empty_loop(x):
    def body(c):
        i, x = c
        return i + 1, x + 1
    return lax.while_loop(lambda c: c[0] < STEPS, body, (jnp.int32(0), x))


@jax.jit
def col_only(D, e, rmin, er):
    def body(c):
        j, D, e, rmin, er = c
        D, e, rmin, er = _col_step_u(
            D, e, rmin, er, off, act, rlen, reads_pad, j + 1, off0,
            jnp.int32(1), wc, et, jnp.int32(E),
        )
        return j + 1, D, e, rmin, er
    return lax.while_loop(
        lambda c: c[0] < STEPS, body, (jnp.int32(0), D, e, rmin, er)
    )


@jax.jit
def col_stats(D, e, rmin, er):
    def body(c):
        j, D, e, rmin, er, acc = c
        eds, occ, split, reached = _stats_core_u(
            D, e, rmin, er, off, act, rlen, reads_pad, j, off0, A,
            jnp.int32(E),
        )
        sym = jnp.argmax(occ.sum(axis=0)).astype(jnp.int32)
        D, e, rmin, er = _col_step_u(
            D, e, rmin, er, off, act, rlen, reads_pad, j + 1, off0, sym,
            wc, et, jnp.int32(E),
        )
        return j + 1, D, e, rmin, er, acc + eds.sum()
    return lax.while_loop(
        lambda c: c[0] < STEPS, body, (jnp.int32(0), D, e, rmin, er,
                                       jnp.int32(0))
    )


def chunked(K):
    @jax.jit
    def fn(D, e, rmin, er):
        def one(c):
            j, D, e, rmin, er, acc = c
            eds, occ, split, reached = _stats_core_u(
                D, e, rmin, er, off, act, rlen, reads_pad, j, off0, A,
                jnp.int32(E),
            )
            sym = jnp.argmax(occ.sum(axis=0)).astype(jnp.int32)
            D, e, rmin, er = _col_step_u(
                D, e, rmin, er, off, act, rlen, reads_pad, j + 1, off0,
                sym, wc, et, jnp.int32(E),
            )
            return j + 1, D, e, rmin, er, acc + eds.sum()

        def body(c):
            for _ in range(K):
                c = one(c)
            return c
        return lax.while_loop(
            lambda c: c[0] < STEPS, body,
            (jnp.int32(0), D, e, rmin, er, jnp.int32(0)),
        )
    return fn


def report(name, t):
    print(f"{name:28s} {t*1e3:8.1f} ms  {t/STEPS*1e6:7.2f} us/step")


t, _ = timeit(empty_loop, jnp.int32(0))
report("empty while_loop", t)
t, _ = timeit(col_only, D0, e0, rmin0, er0)
report("col_step only", t)
t, _ = timeit(col_stats, D0, e0, rmin0, er0)
report("col_step + stats/vote", t)
for K in (2, 4, 8, 16):
    t, _ = timeit(chunked(K), D0, e0, rmin0, er0)
    report(f"chunked body K={K}", t)
