#!/usr/bin/env python
"""Run the reference criterion grid one config per subprocess, appending
JSONL lines as they complete (survives individual config timeouts and
tunnel flaps; re-running SKIPS configs already measured in OUT.jsonl,
so interrupted device runs resume where they left off).

Usage: python scripts/grid_runner.py OUT.jsonl [timeout_s] [platform]
``platform``: cpu (default) pins jax to host CPU; device uses the
session default backend (the tunneled TPU when attached).
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, sys, time
import jax
if {cpu!r} == "cpu":
    jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {root!r})
from waffle_con_tpu.utils.cache import enable_compilation_cache
enable_compilation_cache()
import bench
out = bench.bench_single({ns}, {sl}, {er})
out["metric"] = "consensus_4x{sl}x{ns}_{er}"
out["device_platform"] = jax.devices()[0].platform
print("GRIDLINE " + json.dumps(out))
"""


def main():
    out_path = sys.argv[1]
    timeout_s = int(sys.argv[2]) if len(sys.argv) > 2 else 1800
    platform = sys.argv[3] if len(sys.argv) > 3 else "cpu"
    done = set()
    if os.path.exists(out_path):
        with open(out_path) as f:
            for ln in f:
                try:
                    d = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if "value" in d:  # only successful lines count as done
                    done.add(d["metric"])
    for sl in (1000, 10_000):
        for ns in (8, 30):
            for er in (0.0, 0.01, 0.02):
                metric = f"consensus_4x{sl}x{ns}_{er}"
                if metric in done:
                    print(metric, "already measured; skipping", flush=True)
                    continue
                code = CHILD.format(
                    root=ROOT, ns=ns, sl=sl, er=er, cpu=platform
                )
                t0 = time.time()
                try:
                    proc = subprocess.run(
                        [sys.executable, "-c", code],
                        capture_output=True,
                        text=True,
                        timeout=timeout_s,
                    )
                    line = None
                    for ln in (proc.stdout or "").splitlines():
                        if ln.startswith("GRIDLINE "):
                            line = json.loads(ln[len("GRIDLINE "):])
                    if line is None:
                        line = {
                            "metric": metric,
                            "error": f"rc={proc.returncode}: "
                            + (proc.stderr or "")[-300:],
                        }
                except subprocess.TimeoutExpired:
                    line = {
                        "metric": metric,
                        "error": f"timeout after {timeout_s}s",
                    }
                line["runner_wall_s"] = round(time.time() - t0, 1)
                with open(out_path, "a") as f:
                    f.write(json.dumps(line) + "\n")
                print(line.get("metric"), line.get("value", line.get("error")),
                      flush=True)


if __name__ == "__main__":
    main()
