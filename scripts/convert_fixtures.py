"""One-time conversion of the reference's CSV golden fixtures
(``/root/reference/tests/*.csv``, header ``consensus,edits,sequence`` with
``;``-joined chains) into this repo's JSON fixture schema
(``tests/data/*.json``).  The fixtures are *data* (input reads plus
expected consensus assignments), reused as golden tests per SURVEY.md §4.

Run from the repo root:  python scripts/convert_fixtures.py
"""

import csv
import json
import pathlib

SRC = pathlib.Path("/root/reference/tests")
DST = pathlib.Path(__file__).resolve().parent.parent / "tests" / "data"


def main() -> None:
    DST.mkdir(parents=True, exist_ok=True)
    for path in sorted(SRC.glob("*.csv")):
        records = []
        with open(path, newline="") as fh:
            for row in csv.DictReader(fh):
                records.append(
                    {
                        "consensus": int(row["consensus"]),
                        "edits": int(row["edits"]),
                        "chain": row["sequence"].split(";"),
                    }
                )
        out = DST / (path.stem + ".json")
        with open(out, "w") as fh:
            json.dump({"source": path.name, "records": records}, fh, indent=1)
        print(f"wrote {out} ({len(records)} records)")


if __name__ == "__main__":
    main()
