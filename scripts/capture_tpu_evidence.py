#!/usr/bin/env python
"""Tunnel watchdog: wait for the TPU to come back, then capture the
round-5 device evidence in priority order — north-star self-run first
(the headline number), then the 12-config criterion grid (resumable).

Each stage runs in a subprocess with a timeout so a tunnel flap mid-way
never wedges the watchdog; stages re-probe and retry until the overall
deadline.  Safe to re-run: the self-run keeps the BEST line and the
grid runner skips already-measured configs.

Usage: python scripts/capture_tpu_evidence.py [deadline_minutes]
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EVID = os.path.join(ROOT, "evidence")
PROBE = "import jax; print(jax.devices()[0].platform)"


def probe(timeout_s=90):
    try:
        p = subprocess.run(
            [sys.executable, "-c", PROBE],
            capture_output=True, text=True, timeout=timeout_s,
        )
        out = (p.stdout or "").strip().splitlines()
        return bool(out) and out[-1] not in ("cpu", "")
    except Exception:
        return False


def run_selfrun(reps=2):
    """North-star self-run; keep the best (lowest value) parity-true
    line in evidence/BENCH_r05_selfrun_tpu.json."""
    path = os.path.join(EVID, "BENCH_r05_selfrun_tpu.json")
    best = None
    if os.path.exists(path):
        with open(path) as f:
            best = json.load(f)
    for _ in range(reps):
        try:
            p = subprocess.run(
                [sys.executable, os.path.join(ROOT, "bench.py"), "--_run",
                 "--reads", "256", "--len", "10000", "--platform",
                 "device"],
                capture_output=True, text=True, timeout=900, cwd=ROOT,
            )
        except subprocess.TimeoutExpired:
            return False
        line = None
        for ln in (p.stdout or "").splitlines():
            try:
                d = json.loads(ln)
                if "metric" in d:
                    line = d
            except json.JSONDecodeError:
                continue
        if line is None or not line.get("parity"):
            return False
        if best is None or line["value"] < best.get("value", 1e9):
            best = line
            with open(path, "w") as f:
                json.dump(best, f, indent=1)
        print("selfrun:", line["value"], "s  vs_baseline",
              line["vs_baseline"], flush=True)
    return True


def run_grid(timeout_s):
    out = os.path.join(EVID, "GRID_r05_tpu.jsonl")
    try:
        subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts/grid_runner.py"),
             out, "900", "device"],
            timeout=timeout_s, cwd=ROOT,
        )
    except subprocess.TimeoutExpired:
        pass
    # done when all 12 configs have successful lines
    done = set()
    if os.path.exists(out):
        with open(out) as f:
            for ln in f:
                try:
                    d = json.loads(ln)
                    if "value" in d:
                        done.add(d["metric"])
                except json.JSONDecodeError:
                    continue
    print(f"grid: {len(done)}/12 configs measured", flush=True)
    return len(done) >= 12


def run_dual_priority(timeout_s):
    """TPU-wall versions of the dual/priority evidence workloads (the
    r5 dispatch counts were recorded on jax-CPU; the device wall makes
    the dispatches x ~80 ms model concrete)."""
    out = os.path.join(EVID, "DUAL_PRIORITY_r05_tpu.jsonl")
    try:
        p = subprocess.run(
            [sys.executable, "scripts/dispatch_evidence.py", "--dual",
             "16", "1500", "--priority", "32", "2000", "--platform",
             "device"],
            capture_output=True, text=True, timeout=timeout_s, cwd=ROOT,
        )
    except subprocess.TimeoutExpired:
        return False
    lines = [
        ln for ln in (p.stdout or "").splitlines()
        if ln.startswith("{")
    ]
    if not lines:
        return False
    with open(out, "a") as f:
        for ln in lines:
            f.write(ln + "\n")
    print(f"dual/priority: {len(lines)} lines", flush=True)
    return True


def main():
    deadline = time.time() + 60 * (
        int(sys.argv[1]) if len(sys.argv) > 1 else 360
    )
    selfrun_done = False
    selfrun_tries = 0
    grid_done = False
    dp_done = False
    while time.time() < deadline and not (
        selfrun_done and grid_done and dp_done
    ):
        if not probe():
            print("tunnel down; sleeping 120s", flush=True)
            time.sleep(120)
            continue
        print("tunnel UP", flush=True)
        # the box has ONE cpu core: any background measurement would
        # contend with the bench children and distort both the C++
        # baseline and the host-side timings — clear the deck first
        subprocess.run(["pkill", "-f", "grid_heavy_config"],
                       capture_output=True)
        subprocess.run(["pkill", "-f", "test_slow_scale"],
                       capture_output=True)
        if not selfrun_done and selfrun_tries < 6:
            selfrun_tries += 1
            selfrun_done = run_selfrun()
            continue  # re-probe between stages
        if not grid_done:
            grid_done = run_grid(min(3600, deadline - time.time()))
            continue
        if not dp_done:
            dp_done = run_dual_priority(
                min(1800, deadline - time.time())
            )
            if not dp_done:
                time.sleep(60)
    print("watchdog exit: selfrun", selfrun_done, "grid", grid_done,
          "dual/priority", dp_done, flush=True)


if __name__ == "__main__":
    main()
