#!/usr/bin/env python
"""A/B the run-kernel implementations on the live device with one
command: XLA while-loop vs fused pallas (int32 tile) vs fused pallas
(int16 tile), each in its own subprocess (the pallas mode is resolved
once per process).

Usage: python scripts/ubench_ab.py [steps] [band]
Writes one summary line per variant; ~3 x (compile + run) total.
"""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STEPS = sys.argv[1] if len(sys.argv) > 1 else "4000"
BAND = sys.argv[2] if len(sys.argv) > 2 else "216"

VARIANTS = [
    ("xla", {"WAFFLE_PALLAS": "0"}),
    ("pallas-i32", {"WAFFLE_PALLAS": "auto", "WAFFLE_PALLAS_I16": "0"}),
    ("pallas-i16", {"WAFFLE_PALLAS": "auto", "WAFFLE_PALLAS_I16": "1"}),
]

for name, env in VARIANTS:
    e = dict(os.environ, **env)
    try:
        p = subprocess.run(
            [sys.executable, "scripts/ubench_jrun.py", STEPS, BAND],
            capture_output=True, text=True, timeout=900, cwd=ROOT, env=e,
        )
        runs = [
            ln for ln in (p.stdout or "").splitlines()
            if ln.startswith("run ")
        ]
        best = None
        for ln in runs:
            us = float(ln.split()[-2])
            best = us if best is None else min(best, us)
        print(json.dumps({
            "variant": name,
            "best_us_per_step": best,
            "runs": runs,
            "rc": p.returncode,
            "err": (p.stderr or "")[-200:] if p.returncode else "",
        }), flush=True)
    except subprocess.TimeoutExpired:
        print(json.dumps({"variant": name, "error": "timeout"}),
              flush=True)
