#!/usr/bin/env python3
"""Invariant lint driver (rules WL001-WL005) + env-table generator.

Usage::

    python scripts/waffle_lint.py [paths...] [--strict]
    python scripts/waffle_lint.py --env-table [--write-readme]

With no paths, lints the whole tree (``waffle_con_tpu/``, ``scripts/``,
``bench.py``, ``conftest.py``; ``tests/`` excluded) plus the WL001
README doc-sync check.  ``--strict`` exits 1 on any violation — the
blocking CI entry point (see ``scripts/ci.sh``).

``--env-table`` prints the markdown ``WAFFLE_*`` reference table from
the ``utils/envspec.py`` registry; ``--write-readme`` splices it into
README.md between the ``<!-- envspec:begin -->`` / ``<!-- envspec:end
-->`` markers.

The rule engine and the registry are loaded *standalone* (by file
path, not package import), so this script never imports the package —
and therefore never imports jax.  Full-tree runtime is a fraction of
the 10 s CI budget.
"""

from __future__ import annotations

import argparse
import importlib.util
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
ENV_BEGIN = "<!-- envspec:begin -->"
ENV_END = "<!-- envspec:end -->"


def _load(module_name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        module_name, REPO / relpath
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[module_name] = module  # dataclasses need the entry
    spec.loader.exec_module(module)
    return module


lint = _load("_waffle_lint_rules", "waffle_con_tpu/analysis/lint.py")
envspec = _load("_waffle_envspec", "waffle_con_tpu/utils/envspec.py")


def _splice_readme(readme: Path, table: str) -> bool:
    text = readme.read_text()
    try:
        head, rest = text.split(ENV_BEGIN, 1)
        _old, tail = rest.split(ENV_END, 1)
    except ValueError:
        print(f"error: {readme} lacks {ENV_BEGIN}/{ENV_END} markers",
              file=sys.stderr)
        return False
    new = f"{head}{ENV_BEGIN}\n{table}\n{ENV_END}{tail}"
    if new != text:
        readme.write_text(new)
        print(f"updated {readme}")
    else:
        print(f"{readme} already up to date")
    return True


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="waffle_con_tpu invariant lint (WL001-WL005)"
    )
    parser.add_argument("paths", nargs="*", help="files to lint "
                        "(default: the whole tree + doc-sync)")
    parser.add_argument("--strict", action="store_true",
                        help="exit nonzero on any violation")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset, e.g. "
                        "WL001,WL005")
    parser.add_argument("--env-table", action="store_true",
                        help="print the WAFFLE_* env reference table")
    parser.add_argument("--write-readme", action="store_true",
                        help="with --env-table: splice the table into "
                        "README.md between the envspec markers")
    args = parser.parse_args(argv)

    if args.env_table:
        table = envspec.env_table_markdown()
        if args.write_readme:
            return 0 if _splice_readme(REPO / "README.md", table) else 1
        print(table)
        return 0

    rules = args.rules.split(",") if args.rules else None
    started = time.monotonic()
    violations = []
    if args.paths:
        for raw in args.paths:
            path = Path(raw).resolve()
            root = REPO if REPO in path.parents else None
            violations.extend(lint.lint_path(path, root=root,
                                             rules=rules))
    else:
        violations.extend(lint.lint_tree(REPO, rules=rules))
        if rules is None or "WL001" in rules:
            readme = REPO / "README.md"
            if readme.exists():
                violations.extend(lint.check_env_docs(
                    readme.read_text(), envspec.KNOBS, "README.md"
                ))
    elapsed = time.monotonic() - started

    for violation in violations:
        print(violation.render())
    count = len(violations)
    status = "FAIL" if (violations and args.strict) else "ok"
    print(f"waffle-lint: {count} violation(s), "
          f"{len(lint.iter_python_files(REPO)) if not args.paths else len(args.paths)} "
          f"file(s), {elapsed:.2f}s [{status}]")
    return 1 if (violations and args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
