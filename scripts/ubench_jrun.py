"""Time the REAL ``_j_run`` kernel through the scorer at north-star
shapes, isolating device per-step cost from engine/host overhead."""
import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.utils.example_gen import generate_test

STEPS = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
BAND = int(sys.argv[2]) if len(sys.argv) > 2 else 216

truth, reads = generate_test(4, 10_000, 256, 0.01, seed=0)
cfg = (
    CdwfaConfigBuilder().min_count(64).backend("jax").initial_band(BAND)
    .build()
)
sc = JaxScorer(reads, cfg)
h = sc.root(np.ones(len(reads), dtype=bool))
print(f"band E={sc.bucket_e} W={sc._W} R={len(reads)}")


def one():
    t = time.perf_counter()
    steps, code, appended, stats, records = sc.run_extend(
        h, b"", me_budget=2**31 - 1, other_cost=2**31 - 1, other_len=0,
        min_count=64, l2=False, max_steps=STEPS,
    )
    dt = time.perf_counter() - t
    return dt, steps, code


dt, steps, code = one()  # compile + run
print(f"warm-up: {dt:.2f}s steps={steps} code={code}")
# fresh branch each time (run mutates the branch)
for i in range(3):
    sc.free(h)
    h = sc.root(np.ones(len(reads), dtype=bool))
    dt, steps, code = one()
    print(
        f"run {i}: {dt*1e3:8.1f} ms  steps={steps} code={code} "
        f"{dt/max(steps,1)*1e6:7.2f} us/step"
    )
