"""Time the REAL ``_j_run`` kernel through the scorer at north-star
shapes, isolating device per-step cost from engine/host overhead.

Two modes:

  python scripts/ubench_jrun.py [STEPS] [BAND]
      Single timing pass at the configured ``WAFFLE_RUN_COLS``.

  python scripts/ubench_jrun.py --sweep [STEPS] [BAND]
      Sweep the speculative block size K over {1, 2, 4, 8, 16},
      checking byte parity of the appended consensus against K=1 and
      emitting a JSON table of steps/s + commit rate per K.  This is
      how the per-platform ``_RUN_COLS_DEFAULT`` values were chosen:
      on a 1-core CPU host throughput plateaus from K=4 (~12% over
      K=1; K=8/16 measure the same within noise while compile time
      doubles per octave), and the TPU/GPU default of 4 is a
      conservative carry-over pending on-device sweeps.

  python scripts/ubench_jrun.py --sweep-m [STEPS] [BAND]
      Sweep the frontier-gang width M over {1, 2, 4, 8}: M identical
      root branches advance through one FrontierGang dispatch, every
      deposit is consumed by its matching ``run_extend`` call, and the
      appended consensus of every member must equal the M=1 solo run
      byte-for-byte (exit 1 on any break).  Emits a JSON table of
      ganged steps/s, per-member wall, deposit/commit counts, and the
      gang kernel's compile time per pow2 row-prefix.
"""
import json
import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np

from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.utils.example_gen import generate_test

argv = [a for a in sys.argv[1:] if a not in ("--sweep", "--sweep-m")]
SWEEP = "--sweep" in sys.argv[1:]
SWEEP_M = "--sweep-m" in sys.argv[1:]
STEPS = int(argv[0]) if len(argv) > 0 else 2000
BAND = int(argv[1]) if len(argv) > 1 else 216

truth, reads = generate_test(4, 10_000, 256, 0.01, seed=0)
cfg = (
    CdwfaConfigBuilder().min_count(64).backend("jax").initial_band(BAND)
    .build()
)
sc = JaxScorer(reads, cfg)
h = sc.root(np.ones(len(reads), dtype=bool))
print(f"band E={sc.bucket_e} W={sc._W} R={len(reads)}", file=sys.stderr)


def one():
    t = time.perf_counter()
    steps, code, appended, stats, records = sc.run_extend(
        h, b"", me_budget=2**31 - 1, other_cost=2**31 - 1, other_len=0,
        min_count=64, l2=False, max_steps=STEPS,
    )
    stats.eds  # force the deferred-sync fetch into the timed window
    dt = time.perf_counter() - t
    return dt, steps, code, appended


def timed_runs(n=3):
    """Best-of-n fresh-branch engagements (run mutates the branch)."""
    global h
    best = None
    for _ in range(n):
        sc.free(h)
        h = sc.root(np.ones(len(reads), dtype=bool))
        dt, steps, code, appended = one()
        if best is None or dt < best[0]:
            best = (dt, steps, code, appended)
    return best


if SWEEP_M:
    from waffle_con_tpu.ops import ragged as _ragged
    from waffle_con_tpu.ops.jax_scorer import _run_cols

    BIG = 2**31 - 1
    MC = 64

    def gang_pass(m):
        """One gang-of-m engagement: returns (wall_s, gang_s, total
        steps, appended list, injected delta)."""
        hs = [sc.root(np.ones(len(reads), dtype=bool)) for _ in range(m)]
        inj0 = sc.counters.get("run_gang_injected", 0)
        t0 = time.perf_counter()
        gang_s = 0.0
        if m > 1:
            gang = _ragged.frontier_gang_for(sc)
            members = [
                GangMember(hh, b"", BIG, BIG, 0, STEPS) for hh in hs
            ]
            gang.run(members, MC, False, cols=_run_cols())
            gang_s = time.perf_counter() - t0
        total_steps = 0
        appended = []
        for hh in hs:
            steps, code, app, stats, _recs = sc.run_extend(
                hh, b"", BIG, BIG, 0, MC, False, STEPS
            )
            stats.eds  # force the deferred-sync fetch into the window
            total_steps += steps
            appended.append(app)
        wall = time.perf_counter() - t0
        for hh in hs:
            sc.free(hh)
        inj = sc.counters.get("run_gang_injected", 0) - inj0
        return wall, gang_s, total_steps, appended, inj

    from waffle_con_tpu.ops.ragged import GangMember

    sc.free(h)
    rows = []
    baseline = None
    ok = True
    for m in (1, 2, 4, 8):
        compile_s, _, _, _, _ = gang_pass(m)  # warm-up compiles this P
        wall, gang_s, steps, appended, inj = gang_pass(m)
        if baseline is None:
            baseline = appended[0]
        parity = all(a == baseline for a in appended)
        ok = ok and parity and (m == 1 or inj == m)
        rows.append({
            "m": m,
            "steps_per_s": round(steps / max(wall, 1e-9), 1),
            "wall_s": round(wall, 4),
            "gang_dispatch_s": round(gang_s, 4),
            "steps_total": steps,
            "deposits_committed": inj,
            "compile_s": round(compile_s, 2),
            "parity_vs_m1": parity,
        })
        print(f"M={m}: {rows[-1]}", file=sys.stderr)
    print(json.dumps({"sweep_m": rows, "steps": STEPS, "band": BAND}))
    if not ok:
        sys.exit(1)
elif SWEEP:
    rows = []
    baseline = None
    for k in (1, 2, 4, 8, 16):
        os.environ["WAFFLE_RUN_COLS"] = str(k)
        sc.free(h)
        h = sc.root(np.ones(len(reads), dtype=bool))
        wdt, _, _, _ = one()  # warm-up compiles this K
        it0, sp0, st0 = (
            sc.counters["run_iters"], sc.counters["run_spec_cols"],
            sc.counters["run_steps"],
        )
        dt, steps, code, appended = timed_runs()
        if baseline is None:
            baseline = appended
        spec = sc.counters["run_spec_cols"] - sp0
        rows.append({
            "k": k,
            "steps_per_s": round(steps / max(dt, 1e-9), 1),
            "us_per_step": round(dt / max(steps, 1) * 1e6, 2),
            "commit_rate": round(
                (sc.counters["run_steps"] - st0) / spec, 4
            ) if spec else 1.0,
            "iters": sc.counters["run_iters"] - it0,
            "compile_s": round(wdt, 2),
            "parity_vs_k1": appended == baseline,
            "stop_code": code,
        })
        print(f"K={k:2d}: {rows[-1]}", file=sys.stderr)
    os.environ.pop("WAFFLE_RUN_COLS", None)
    print(json.dumps({"sweep": rows, "steps": STEPS, "band": BAND}))
    if not all(r["parity_vs_k1"] for r in rows):
        sys.exit(1)
else:
    dt, steps, code, _ = one()  # compile + run
    print(f"warm-up: {dt:.2f}s steps={steps} code={code}")
    for i in range(3):
        sc.free(h)
        h = sc.root(np.ones(len(reads), dtype=bool))
        dt, steps, code, _ = one()
        print(
            f"run {i}: {dt*1e3:8.1f} ms  steps={steps} code={code} "
            f"{dt/max(steps,1)*1e6:7.2f} us/step"
        )
