#!/usr/bin/env bash
# CI entry point: tier-1 test suite (per-file sharded) plus an
# observability-enabled bench smoke whose evidence JSON and Chrome trace
# are asserted to be well-formed.
#
# Usage: scripts/ci.sh [extra pytest args...]
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

export JAX_PLATFORMS=cpu

echo "== invariant lint (waffle_lint --strict) =="
# blocking: all five WL rules over the whole tree, plus the README
# env-table doc-sync check. Budget is ~1s; the gate is <10s.
python scripts/waffle_lint.py --strict

echo "== tier-1 suite (sharded) =="
python scripts/run_suite.py "$@"

echo "== search audit drill (lockstep shadow + seeded divergence triage) =="
# clean lockstep shadow over golden fixtures must report zero
# divergences; then a deterministic flip_vote fault must be localized to
# its exact pop by the shadow, the offline differ, and a minimized
# checkpoint-resume repro (scripts/waffle_diverge.py --drill).
WAFFLE_AUDIT=1 python scripts/waffle_diverge.py --drill

echo "== bench smoke (metrics + trace) =="
SMOKE_OUT="$(mktemp /tmp/waffle_ci_bench.XXXXXX.json)"
TRACE_OUT="$(mktemp /tmp/waffle_ci_trace.XXXXXX.json)"
trap 'rm -f "$SMOKE_OUT" "$TRACE_OUT"' EXIT

WAFFLE_METRICS=1 BENCH_SMOKE=1 \
  BENCH_TOTAL_BUDGET="${BENCH_TOTAL_BUDGET:-600}" \
  python bench.py --iters 5 --platform cpu --trace-out "$TRACE_OUT" \
  > "$SMOKE_OUT"

python - "$SMOKE_OUT" "$TRACE_OUT" <<'PY'
import json
import sys

smoke_path, trace_path = sys.argv[1], sys.argv[2]

with open(smoke_path) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert "metric" in evidence, f"no metric in evidence: {sorted(evidence)}"
assert "search_report" in evidence, (
    f"no search_report in evidence: {sorted(evidence)}"
)
report = evidence["search_report"]
for key in ("engine", "backend", "nodes_explored", "dispatch_total"):
    assert key in report, f"search_report missing {key!r}: {sorted(report)}"
assert "metrics" in evidence, f"no metrics snapshot: {sorted(evidence)}"
latency = evidence["metrics"].get("waffle_dispatch_latency_seconds", {})
assert latency.get("series"), "empty dispatch latency histograms"

with open(trace_path) as fh:
    trace = json.load(fh)
events = trace.get("traceEvents", [])
assert events, "empty Chrome trace"
cats = {e.get("cat") for e in events}
assert "search" in cats and "dispatch" in cats, f"missing span cats: {cats}"
print(
    f"ci bench smoke ok: {evidence['metric']}={evidence['value']}s, "
    f"{len(events)} trace events, "
    f"{len(latency['series'])} latency series"
)
PY

echo "== hot-loop microbench (steps/s regression gate, mega on+off) =="
# Raw run_extend throughput at the north-star geometry (256 reads x
# 10 kb, 1% error) at the configured speculative block size
# (WAFFLE_RUN_COLS, default 4). The floor is set from the round-7
# measurement (~1063 steps/s at K=4; K=1 measures ~930-950), so it
# both catches hot-loop regressions AND "speculation silently
# disabled".
# The mode also cross-checks the appended bytes against ground truth
# at K=1 and at the configured K, so a parity break fails the gate
# even when throughput holds.
#
# The same invocation times the MEGASTEP path (run_extend mega=True:
# M x K device-resident blocks, deferred stats off, one bundled
# control+stats fetch) against the plain path and asserts BOTH a mega
# steps/s floor and strictly fewer blocking host round trips with
# mega on.  Honest calibration: the issue aspired to a mega floor
# >= 1.5x the 900 plain floor (1350); measured on this 1-core CPU
# host the mega path does 905 steps/s vs 1027 plain — the bundled
# fetch costs slightly more per engagement than the deferred-stats
# plain path, and the megastep's real win here is round trips
# (3 -> 2 per engagement; the per-pop win at engine level is pinned
# in tests/test_megastep.py).  Floor = 770 keeps the same ~15%
# margin vs measurement the plain 900 floor has.
MICRO_FLOOR="${WAFFLE_MICROBENCH_FLOOR:-900}"
MEGA_FLOOR="${WAFFLE_MEGA_FLOOR:-770}"
python bench.py --microbench --platform cpu --iters 3 \
  --assert-steps-floor "$MICRO_FLOOR" \
  --assert-mega-floor "$MEGA_FLOOR"

echo "== perfdb (persistent perf history + rolling-baseline gate) =="
# The microbench above appended its record to the perf database — a
# retained artifact (evidence/perfdb.jsonl in the repo), not a
# tmpfile.  The gate compares that latest record against the rolling
# median of the prior same-platform runs with a tolerance band; the
# absolute MICRO_FLOOR stays as the backstop for a drifted baseline.
# Knobs:
#   WAFFLE_PERFDB             database path (default evidence/perfdb.jsonl)
#   WAFFLE_MICROBENCH_FLOOR   absolute steps/s backstop (default 900)
#   WAFFLE_PERFDB_TOLERANCE   allowed fractional drop vs the rolling
#                             baseline (default 0.05)
#   WAFFLE_PERFDB_WINDOW      rolling-baseline window (default 10)
# The microbench-mega kind rides the same gate (absolute floor applies
# to 'microbench' only; mega's absolute floor is the bench-side
# --assert-mega-floor above).  Until three same-platform records
# accumulate, perf_report prints an explicit "no-baseline (n=<k>)"
# line for the kind instead of silently passing.
python scripts/perf_report.py --check \
  --kinds microbench,microbench-mega \
  --tolerance "${WAFFLE_PERFDB_TOLERANCE:-0.05}" \
  --window "${WAFFLE_PERFDB_WINDOW:-10}" \
  --floor "$MICRO_FLOOR"
python scripts/perf_report.py

echo "== speculative K-sweep smoke (golden-fixture parity at K>1) =="
# The speculative K-column device loop must be byte-identical to K=1
# at every K. The fuzz suite pins the adversarial cases; this smoke
# re-runs the golden-fixture jax-backend scenarios (dual_001,
# priority_001, multi_err_001) across a small K sweep so a masking
# bug that only shows on real fixture workloads fails CI outright.
for K in 2 5 8; do
  echo "-- WAFFLE_RUN_COLS=$K --"
  WAFFLE_RUN_COLS="$K" python -m pytest -q -p no:cacheprovider \
    -p no:randomly tests/test_jax_scorer.py \
    -k "fixture or multi_err_recovery"
done

echo "== frontier gang M-sweep smoke (deposit parity per pow2 width) =="
# The frontier gang advances M branches through one ragged dispatch
# and deposits consume-once injections; every member's appended bytes
# must equal the M=1 solo run at every pow2 width (the sweep exits 1
# on any break or unconsumed deposit).
python scripts/ubench_jrun.py --sweep-m 200 > /dev/null

echo "== tie-heavy bench smoke (frontier speculation wall gate) =="
# The tie-heavy worst case (2% error: cost ties force the engine onto
# forced single-step pops) is the geometry frontier-parallel
# speculation exists for.  Smoke geometry under BENCH_SMOKE; the gate
# asserts parity plus a generous absolute wall ceiling (timed wall
# ~10s single + ~3s dual on a quiet 1-core host), and the emitted
# tie_heavy records feed the rolling perfdb trend gate below.
BENCH_SMOKE=1 python bench.py --tie-heavy --platform cpu \
  --assert-wall-ceiling "${WAFFLE_TIE_HEAVY_CEILING_S:-120}"

echo "== serve bench smoke (cross-job batching) =="
SERVE_OUT="$(mktemp /tmp/waffle_ci_serve.XXXXXX.json)"
trap 'rm -f "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT"' EXIT

# WAFFLE_LOCKCHECK=1 arms the runtime lock-order checker on every lock
# the serve stack creates (see waffle_con_tpu/analysis/lockcheck.py): an
# acquisition-order inversion raises + flight-records instead of being a
# latent deadlock. Same for the serve-mix and storm smokes below.
WAFFLE_METRICS=1 BENCH_SMOKE=1 WAFFLE_LOCKCHECK=1 \
  python bench.py --serve 4 --platform cpu > "$SERVE_OUT"

python - "$SERVE_OUT" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "serve", f"not a serve line: {sorted(evidence)}"
assert evidence["jobs"] == 4, evidence["jobs"]
assert evidence["jobs_per_s"] > 0, evidence["jobs_per_s"]
assert evidence["parity"] is True, "served result diverged from serial"
assert 0 <= evidence["p50_job_latency_s"] <= evidence["p95_job_latency_s"], (
    evidence["p50_job_latency_s"], evidence["p95_job_latency_s"],
)
dispatch = evidence["serve_stats"]["dispatch"]
assert dispatch["coalesced_batches"] >= 1, dispatch
assert evidence["mean_batch_occupancy"] > 1.0, evidence["mean_batch_occupancy"]
jobs = evidence["serve_stats"]["jobs"]
assert jobs["done"] == 4 and jobs["failed"] == 0, jobs
serve_metrics = [
    k for k in evidence.get("metrics", {}) if k.startswith("waffle_serve")
]
assert "waffle_serve_batch_occupancy" in serve_metrics, serve_metrics
assert "waffle_serve_jobs_total" in serve_metrics, serve_metrics
print(
    f"ci serve smoke ok: {evidence['jobs_per_s']} jobs/s, "
    f"occupancy={evidence['mean_batch_occupancy']}, "
    f"p95={evidence['p95_job_latency_s']}s, "
    f"{len(serve_metrics)} serve metric families"
)
PY

echo "== flight-recorder smoke (fault-injected serve) =="
FLIGHT_DIR="$(mktemp -d /tmp/waffle_ci_flight.XXXXXX)"
FLIGHT_OUT="$(mktemp /tmp/waffle_ci_flight_out.XXXXXX.json)"
trap 'rm -rf "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT" "$FLIGHT_DIR" "$FLIGHT_OUT"' EXIT

# two injected jax timeouts against breaker_threshold=2 force one served
# job to demote mid-search; the always-on flight recorder must dump a
# self-contained incident without any tracing/metrics pipeline enabled
WAFFLE_FAULTS="timeout:jax:*:*:2" WAFFLE_FLIGHT_DIR="$FLIGHT_DIR" \
  BENCH_SMOKE=1 WAFFLE_LOCKCHECK=1 \
  python bench.py --serve 4 --serve-supervised --platform cpu \
  > "$FLIGHT_OUT"

python - "$FLIGHT_OUT" "$FLIGHT_DIR" <<'PY'
import glob
import json
import sys

out_path, flight_dir = sys.argv[1], sys.argv[2]

with open(out_path) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("supervised") is True, sorted(evidence)
assert evidence["parity"] is True, "demoted job diverged from serial"
slo = evidence.get("slo", {})
for window in ("dispatch", "job"):
    for q in ("p50_s", "p95_s", "p99_s"):
        assert slo.get(window, {}).get(q) is not None, (window, q, slo)
assert evidence.get("incidents"), "no incidents in serve evidence"

dumps = sorted(glob.glob(f"{flight_dir}/incident-*.json"))
assert dumps, f"no incident dump in {flight_dir}"
with open(dumps[0]) as fh:
    incident = json.load(fh)
assert incident["schema"] == "waffle-flight-incident/1", incident["schema"]
assert incident["reason"] == "backend_demoted", incident["reason"]
assert incident["trace_id"], incident
assert incident["detail"]["from_backend"] == "jax", incident["detail"]
assert any(r["kind"] == "job_start" for r in incident["trace"]), (
    [r["kind"] for r in incident["trace"]]
)
assert "job" in incident["slo"], sorted(incident["slo"])
print(
    f"ci flight smoke ok: {len(dumps)} incident dump(s), "
    f"reason={incident['reason']}, trace={incident['trace_id']}, "
    f"rolling job p95={slo['job']['p95_s']:.3f}s"
)
PY

echo "== serve-mix smoke (ragged cross-job batching) =="
MIX_OUT="$(mktemp /tmp/waffle_ci_mix.XXXXXX.json)"
trap 'rm -rf "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT" "$FLIGHT_DIR" "$FLIGHT_OUT" "$MIX_OUT"' EXIT

# heterogeneous job geometries: the ragged arena must gang jobs across
# shape buckets (occupancy), keep results byte-identical to serial, and
# compile a CONSTANT number of kernels regardless of job shapes (the
# pool geometry + pow2 row-prefix ladder bound the keys, not the
# number of distinct job shapes)
WAFFLE_METRICS=1 BENCH_SMOKE=1 WAFFLE_LOCKCHECK=1 \
  python bench.py --serve-mix 6 --platform cpu > "$MIX_OUT"

python - "$MIX_OUT" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "serve-mix", sorted(evidence)
assert evidence["parity"] is True, "ragged/bucketed diverged from serial"
occ = evidence["ragged_occupancy"]
assert occ > 1.5, f"ragged occupancy {occ} <= 1.5"
# constant-compile bound: the ragged phase may compile the gang kernel
# (pow2 row-prefix ladder), slot-put stores, and the shared pool-floored
# solo kernels -- a fixed envelope, independent of the job-shape count
assert evidence["compiles_ragged"] <= 24, evidence["compiles_ragged"]
ragged = evidence["ragged_stats"]
assert ragged["groups"] >= 1, ragged
assert ragged["pages_used"] == 0, ragged  # completion released all pages
assert ragged["member_store_failures"] == 0, ragged
# mixed-W traffic class: members at three distinct band widths must
# still gang (width-agnostic pages), byte-identical, and the per-row
# stride is traced data -- no new compile geometries beyond the same
# fixed envelope
mixed = evidence["mixed_w"]
assert mixed["parity"] is True, "mixed-W ragged diverged from serial"
m_occ = mixed["ragged_occupancy"]
assert m_occ > 1.5, f"mixed-W ragged occupancy {m_occ} <= 1.5"
assert mixed["mixed_w_groups"] >= 1, mixed
assert mixed["compiles_ragged"] <= 24, mixed["compiles_ragged"]
assert mixed["ragged_stats"]["pages_used"] == 0, mixed["ragged_stats"]
print(
    f"ci serve-mix smoke ok: occupancy={occ} "
    f"(bucketed {evidence['bucketed_run_occupancy']}), "
    f"compiles={evidence['compiles_ragged']}, "
    f"{evidence['jobs_per_s_ragged']} jobs/s ragged; "
    f"mixed-W occupancy={m_occ}, "
    f"mixed gangs={mixed['mixed_w_groups']}/{mixed['groups']}, "
    f"compiles={mixed['compiles_ragged']}"
)
PY

echo "== storm smoke (replicated front door + mesh placement) =="
STORM_OUT="$(mktemp /tmp/waffle_ci_storm.XXXXXX.json)"
SHED_OUT="$(mktemp /tmp/waffle_ci_shed.XXXXXX.json)"
trap 'rm -rf "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT" "$FLIGHT_DIR" "$FLIGHT_OUT" "$MIX_OUT" "$STORM_OUT" "$SHED_OUT"' EXIT

# heavy-tailed bursty mix through the replicated front door: 8 jobs
# (one mesh-large, promoted by the placement policy onto the sharded
# scorer), 4 replicas on forced-multidevice CPU.  Gates (env-knobbed):
#   WAFFLE_STORM_JOBS_FLOOR   multi-replica jobs/s floor (default 3.0)
#   WAFFLE_STORM_P95_CEIL     p95 job-latency ceiling (default 3.0)
#   WAFFLE_STORM_SPEEDUP      multi/single jobs/s sanity floor
#                             (default 0.8).  The CI container has ONE
#                             core: replicas can't compute in parallel,
#                             AND splitting the mix across 4 dispatchers
#                             forfeits cross-job arena ganging the
#                             single service gets for free — measured
#                             multi/single lands anywhere in ~0.9-1.5x
#                             depending on scheduler luck.  The floor
#                             only catches a front door that collapses
#                             throughput; raise to 1.5 on hosts with
#                             real parallel devices, where per-replica
#                             device slices turn replication into
#                             actual concurrency.
WAFFLE_METRICS=1 WAFFLE_LOCKCHECK=1 \
  python bench.py --storm 8 --replicas 4 --platform cpu > "$STORM_OUT"

python - "$STORM_OUT" <<'PY'
import json
import os
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "storm", sorted(evidence)
assert evidence["jobs"] == 8, evidence["jobs"]
assert evidence["replicas"] == 4, evidence["replicas"]
assert evidence["parity"] is True, "storm results diverged from serial"
assert evidence["mesh_placed"] >= 1, evidence["mesh_placed"]

floor = float(os.environ.get("WAFFLE_STORM_JOBS_FLOOR", "3.0"))
ceil = float(os.environ.get("WAFFLE_STORM_P95_CEIL", "3.0"))
speedup_floor = float(os.environ.get("WAFFLE_STORM_SPEEDUP", "0.8"))
assert evidence["jobs_per_s"] >= floor, (
    f"storm jobs/s {evidence['jobs_per_s']} < floor {floor}"
)
assert evidence["p95_job_latency_s"] <= ceil, (
    f"storm p95 {evidence['p95_job_latency_s']}s > ceiling {ceil}s"
)
assert evidence["p95_job_latency_s"] <= evidence["p99_job_latency_s"], (
    evidence["p95_job_latency_s"], evidence["p99_job_latency_s"],
)
assert evidence["speedup_vs_single"] >= speedup_floor, (
    f"multi-replica speedup {evidence['speedup_vs_single']} < "
    f"{speedup_floor} vs single replica "
    f"({evidence['jobs_per_s_single']} jobs/s)"
)
reps = evidence["per_replica"]
assert len(reps) == 4, [r["replica"] for r in reps]
assert sum(r["routed"] for r in reps) == evidence["jobs"], reps
assert sum(1 for r in reps if r["routed"] > 0) >= 2, (
    "front door routed everything to one replica"
)
print(
    f"ci storm smoke ok: {evidence['jobs_per_s']} jobs/s "
    f"({evidence['speedup_vs_single']}x vs single replica), "
    f"p95={evidence['p95_job_latency_s']}s, "
    f"mesh_placed={evidence['mesh_placed']}, "
    f"routed={[r['routed'] for r in reps]}"
)
PY

echo "== storm shedding demo (fault-injected replica drain + reroute) =="
# two injected jax timeouts demote one replica's backend mid-storm
# (armed for the timed multi-replica pass only); the front door must
# mark it draining, reroute admissions, keep every result byte-
# identical, and still meet the (shed-specific) latency ceiling:
#   WAFFLE_STORM_SHED_P95   p95 ceiling with one demoted replica
#                           (default 12.0 — the demoted job finishes
#                           on the python fallback backend)
WAFFLE_FAULTS="timeout:jax:*:*:2" WAFFLE_LOCKCHECK=1 \
  python bench.py --storm 8 --replicas 4 --serve-supervised \
  --platform cpu > "$SHED_OUT"

python - "$SHED_OUT" <<'PY'
import json
import os
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("supervised") is True, sorted(evidence)
assert evidence["parity"] is True, "shed storm diverged from serial"
shed = evidence["shed"]
assert shed["demotions"] >= 1, shed
shed_ceil = float(os.environ.get("WAFFLE_STORM_SHED_P95", "12.0"))
assert evidence["p95_job_latency_s"] <= shed_ceil, (
    f"shed-storm p95 {evidence['p95_job_latency_s']}s > {shed_ceil}s"
)
reps = evidence["per_replica"]
demoted = [r for r in reps if r["demotions"] >= 1]
assert demoted, reps
healthy_routed = sum(
    r["routed"] for r in reps if r["demotions"] == 0
)
assert healthy_routed >= 1, "no rerouting to healthy replicas"
incidents = [i for i in evidence.get("incidents", [])
             if i.get("reason") == "backend_demoted"]
assert incidents, "no backend_demoted incident recorded"
print(
    f"ci storm shed ok: {demoted[0]['replica']} "
    f"state={demoted[0]['state']} after {shed['demotions']} "
    f"demotion(s), healthy replicas routed {healthy_routed} job(s), "
    f"p95={evidence['p95_job_latency_s']}s"
)
PY

echo "== storm-procs smoke (out-of-process workers behind the RPC door) =="
PROCS_OUT="$(mktemp /tmp/waffle_ci_procs.XXXXXX.json)"
KILL_OUT="$(mktemp /tmp/waffle_ci_kill.XXXXXX.json)"
trap 'rm -rf "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT" "$FLIGHT_DIR" "$FLIGHT_OUT" "$MIX_OUT" "$STORM_OUT" "$SHED_OUT" "$PROCS_OUT" "$KILL_OUT"' EXIT

# the same heavy-tailed storm mix, but through process-parallel worker
# replicas (each its own interpreter, dispatcher, and arena) behind the
# length-prefixed socket protocol.  Gates: byte-parity vs serial, both
# workers actually routed jobs, and a jobs/s sanity floor:
#   WAFFLE_STORM_PROCS_SPEEDUP   multi-worker/single-process jobs/s
#                                floor.  Default 0.25 is the documented
#                                1-core sanity value: two jax processes
#                                time-slice one core AND forfeit
#                                cross-job arena ganging (measured
#                                0.34-0.42 here).  Raise toward 1.5 on
#                                hosts with real cores, where process
#                                isolation buys actual parallelism
#                                (the ISSUE target is >1.5 multi-core).
WAFFLE_LOCKCHECK=1 \
  python bench.py --storm 8 --procs 2 --platform cpu > "$PROCS_OUT"

python - "$PROCS_OUT" <<'PY'
import json
import os
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "storm-procs", sorted(evidence)
assert evidence["procs"] == 2, evidence["procs"]
assert evidence["parity"] is True, "storm-procs diverged from serial"
assert evidence["workers_participating"] >= 2, (
    "front door routed everything to one worker process"
)
assert evidence["worker_lost_incidents"] == 0, evidence
assert evidence["requeues"] == 0, evidence
floor = float(os.environ.get("WAFFLE_STORM_PROCS_SPEEDUP", "0.25"))
assert evidence["speedup_vs_single"] >= floor, (
    f"storm-procs speedup {evidence['speedup_vs_single']} < {floor} "
    f"vs single process ({evidence['jobs_per_s_single']} jobs/s)"
)
print(
    f"ci storm-procs smoke ok: {evidence['jobs_per_s']} jobs/s "
    f"({evidence['speedup_vs_single']}x vs single process), "
    f"workers={[ (w['worker'], w['routed']) for w in evidence['per_worker'] ]}"
)
PY

echo "== storm-procs crash drill (SIGKILL a worker mid-storm) =="
# kill a checkpointed worker mid-storm: the door must detect the dead
# socket, migrate its started jobs to a healthy worker from their last
# CHECKPOINT frames (no full re-search of a started job), keep every
# byte identical to serial, and record exactly one worker_lost flight
# incident.  The kill run writes a storm-procs-ckpt perfdb record so
# migration walls never pollute the storm-procs trend baseline.
WAFFLE_LOCKCHECK=1 \
  python bench.py --storm 8 --procs 2 --kill-worker --platform cpu \
  > "$KILL_OUT"

python - "$KILL_OUT" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "storm-procs-ckpt", sorted(evidence)
assert evidence.get("kill_worker"), sorted(evidence)  # victim info dict
assert evidence["parity"] is True, "post-crash results diverged from serial"
assert evidence["requeues"] >= 1, (
    f"no requeue observed after SIGKILL: {evidence['per_worker']}"
)
assert evidence["worker_lost_incidents"] == 1, (
    f"expected exactly one worker_lost incident, got "
    f"{evidence['worker_lost_incidents']}"
)
lost = [w for w in evidence["per_worker"] if w["state"] == "lost"]
assert len(lost) == 1, evidence["per_worker"]
survivors = [w for w in evidence["per_worker"] if w["state"] != "lost"]
assert sum(w["routed"] for w in survivors) >= 1, evidence["per_worker"]
assert evidence["migrated"] >= 1, (
    f"SIGKILL produced no checkpoint migration: {evidence['checkpoints']}"
)
assert evidence["restarted_started"] == 0, (
    f"{evidence['restarted_started']} started job(s) lost their "
    f"checkpoints and re-searched from scratch"
)
mig = evidence["migration_jobs"]
assert mig and any(
    m["post_kill_wall_s"] < m["scratch_wall_s"] for m in mig
), f"no migrated job beat its from-scratch served wall: {mig}"
assert evidence["checkpoints"]["frames"] >= 1, evidence["checkpoints"]
print(
    f"ci storm-procs crash drill ok: lost={lost[0]['worker']}, "
    f"requeues={evidence['requeues']}, "
    f"migrated={evidence['migrated']} "
    f"(wasted {evidence['wasted_work_s']}s), parity held"
)
PY

echo "== storm-procs fleet observability smoke (traced + fault-injected) =="
FLEET_OUT="$(mktemp /tmp/waffle_ci_fleet.XXXXXX.json)"
FLEET_TRACE="$(mktemp /tmp/waffle_ci_fleet_trace.XXXXXX.json)"
FLEET_FLIGHT="$(mktemp -d /tmp/waffle_ci_fleet_flight.XXXXXX)"
trap 'rm -rf "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT" "$FLIGHT_DIR" "$FLIGHT_OUT" "$MIX_OUT" "$STORM_OUT" "$SHED_OUT" "$PROCS_OUT" "$KILL_OUT" "$FLEET_OUT" "$FLEET_TRACE" "$FLEET_FLIGHT"' EXIT

# the full fleet observability plane, armed: --trace-out turns on
# tracing + metrics in the door AND (via the worker spec) in every
# spawned worker; a dense STATS cadence federates worker metric
# snapshots during the short run; the injected jax timeouts fire
# inside the *workers* (bench pops WAFFLE_FAULTS before the serial
# refs and re-exports it only for the multi-worker phase), so the
# incident files below prove the worker->door INCIDENT path, not a
# door-local recorder.  Fault runs write no perfdb record, so this
# smoke can never move the storm-procs trend baseline.
WAFFLE_LOCKCHECK=1 WAFFLE_PROC_STATS_S=0.3 \
  WAFFLE_FAULTS="timeout:jax:*:*:2" WAFFLE_FLIGHT_DIR="$FLEET_FLIGHT" \
  python bench.py --storm 8 --procs 2 --serve-supervised \
  --trace-out "$FLEET_TRACE" --platform cpu > "$FLEET_OUT"

python - "$FLEET_OUT" "$FLEET_TRACE" "$FLEET_FLIGHT" <<'PY'
import glob
import json
import sys

out_path, trace_path, flight_dir = sys.argv[1], sys.argv[2], sys.argv[3]

with open(out_path) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "storm-procs", sorted(evidence)
assert evidence.get("supervised") is True, sorted(evidence)
assert evidence.get("faults"), "fault spec missing from evidence"
assert evidence["parity"] is True, (
    "fleet-observability storm diverged from serial"
)

# federated metrics: the door's exposition must carry each worker's
# snapshot as worker=-labelled series
fleet = evidence["fleet"]
assert fleet["stats_frames"] >= 1, fleet
assert fleet["span_events"] >= 1, fleet
series_labels = [
    label
    for family in evidence["metrics"].values()
    for label in family.get("series", {})
]
for wname in ("storm:w0", "storm:w1"):
    assert any(f'worker="{wname}"' in lbl for lbl in series_labels), (
        f"no federated series for {wname} in the merged registry"
    )

# distributed tracing: one job's spans must come from BOTH sides of
# the socket (door spans have no args.worker; ingested worker spans
# do), stitched onto the same per-job chrome pid, with flow arrows
with open(trace_path) as fh:
    events = json.load(fh)["traceEvents"]
spans = [e for e in events if e.get("ph") == "X"]
assert spans, "trace file has no spans"
door_pids = {e["pid"] for e in spans
             if not (e.get("args") or {}).get("worker")}
worker_pids = {e["pid"] for e in spans
               if (e.get("args") or {}).get("worker")}
stitched = door_pids & worker_pids
assert stitched, (
    f"no job pid with spans from 2 processes "
    f"(door-only={sorted(door_pids)[:4]}, "
    f"worker-only={sorted(worker_pids)[:4]})"
)
names = {e["name"] for e in spans if e["pid"] in stitched}
assert "door:job" in names and "serve:job" in names, sorted(names)
flow_starts = {e["id"] for e in events if e.get("ph") == "s"}
flow_ends = {e["id"] for e in events if e.get("ph") == "f"}
assert flow_starts & flow_ends, "no matched flow arrow pair"

# incident aggregation: a worker-side flight trigger must surface as
# exactly one door-side dump per forwarded incident, attributed to
# the worker that hit it (workers are spawned without
# WAFFLE_FLIGHT_DIR, so every file here came from the door's
# re-ingest)
assert fleet["incidents_forwarded"] >= 1, fleet
dumps = sorted(glob.glob(f"{flight_dir}/incident-*.json"))
assert dumps, f"no door-side incident dump in {flight_dir}"
keys = []
for path in dumps:
    with open(path) as fh:
        incident = json.load(fh)
    assert incident["origin"] == "remote", incident
    assert str(incident.get("worker", "")).startswith("storm:w"), incident
    keys.append((incident["reason"], incident["trace_id"],
                 incident["worker"]))
assert len(keys) == len(set(keys)), f"duplicate incident dumps: {keys}"
assert len(dumps) == fleet["incidents_forwarded"], (
    f"{len(dumps)} dump(s) for {fleet['incidents_forwarded']} "
    f"forwarded incident(s)"
)
print(
    f"ci fleet observability smoke ok: "
    f"{fleet['stats_frames']} STATS frame(s), "
    f"{fleet['span_events']} ingested span event(s), "
    f"{len(stitched)} stitched job(s), "
    f"{len(dumps)} attributed incident dump(s), parity held"
)
PY

echo "== storm-cache smoke (duplicate-heavy consensus cache) =="
CACHE_OUT="$(mktemp /tmp/waffle_ci_cache.XXXXXX.json)"
trap 'rm -rf "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT" "$FLIGHT_DIR" "$FLIGHT_OUT" "$MIX_OUT" "$STORM_OUT" "$SHED_OUT" "$PROCS_OUT" "$KILL_OUT" "$FLEET_OUT" "$FLEET_TRACE" "$FLEET_FLIGHT" "$CACHE_OUT"' EXIT

# duplicate-heavy + superset-heavy traffic through the content-addressed
# cache: exact duplicates (permuted read order) must be served CACHED
# without ever reaching a worker, cached-consensus supersets certify by
# one oracle pass, and checkpoint supersets resume from a deposited
# bound-free frontier.  bench exits 1 itself unless parity holds, every
# exact hit is dispatch-free, and hit_rate > 0; the assertions below
# re-check those fields from the evidence JSON and pin the tier split.
WAFFLE_METRICS=1 WAFFLE_LOCKCHECK=1 \
  python bench.py --storm 8 --cache --platform cpu > "$CACHE_OUT"

python - "$CACHE_OUT" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "storm-cache", sorted(evidence)
assert evidence["parity"] is True, (
    "cache-served result diverged from serial reference"
)
assert evidence["hit_rate"] > 0, evidence["hit_rate"]
assert evidence["exact_hits_dispatch_free"] is True, (
    "an exact duplicate was dispatched to a worker"
)
cache = evidence["cache"]
assert cache["exact"] >= 1, cache
assert cache["deposits"] >= 1, cache
assert evidence["checkpoint_hits_all_iters"] >= 1, (
    f"no superset job resumed from a cached checkpoint: {cache}"
)
ckpt_jobs = evidence["checkpoint_jobs"]
assert ckpt_jobs and all(
    j["resumed_wall_s"] < j["scratch_wall_s"] for j in ckpt_jobs
), f"a resumed superset job did not beat its from-scratch wall: {ckpt_jobs}"
hits = [
    k for k in evidence.get("metrics", {})
    if k.startswith("waffle_cache")
]
assert "waffle_cache_hits_total" in hits, hits
print(
    f"ci storm-cache smoke ok: hit_rate={evidence['hit_rate']}, "
    f"tiers exact={cache['exact']} certified={cache['certified']} "
    f"checkpoint={cache['checkpoint']}, "
    f"resumed {evidence['resumed_wall_total_s']}s vs "
    f"scratch {evidence['scratch_wall_total_s']}s, parity held"
)
PY

echo "== perfdb serving trend gate (serve-mix + storm jobs/s) =="
# the serving smokes above appended their records; gate each kind's
# latest against its own same-platform, same-metric rolling baseline.
# The microbench re-check (already floor-gated earlier) keeps one
# combined trend verdict in the log at the tight tolerance; the
# serving kinds get a wider band (WAFFLE_PERFDB_SERVE_TOLERANCE,
# default 15%): their walls are single ~1-2s serving passes on a
# shared 1-core host with ~±10% run-to-run jitter, where 15% still
# catches any structural regression (batching off, a dead replica, or
# placement gone wrong all cost far more than 15%).
python scripts/perf_report.py --check \
  --kinds microbench,microbench-mega \
  --tolerance "${WAFFLE_PERFDB_TOLERANCE:-0.05}" \
  --window "${WAFFLE_PERFDB_WINDOW:-10}" \
  --floor "$MICRO_FLOOR"
python scripts/perf_report.py --check \
  --kinds serve-mix,serve-mix-mixed-w,storm,storm-procs,tie_heavy \
  --tolerance "${WAFFLE_PERFDB_SERVE_TOLERANCE:-0.15}" \
  --window "${WAFFLE_PERFDB_WINDOW:-10}" \
  --floor "$MICRO_FLOOR"
# storm-cache gets its own wider band (WAFFLE_PERFDB_CACHE_TOLERANCE,
# default 30%): its timed wall is dominated by the checkpoint-tier
# resume searches (whole seconds each), which jitter ~20% run-to-run
# on the shared 1-core host.  A real cache regression — exact hits
# dispatching, the checkpoint tier dead — costs far more than 30%,
# and the hit-rate/parity/dispatch-free gates above catch structural
# breaks independent of wall time.
python scripts/perf_report.py --check \
  --kinds storm-cache \
  --tolerance "${WAFFLE_PERFDB_CACHE_TOLERANCE:-0.30}" \
  --window "${WAFFLE_PERFDB_WINDOW:-10}" \
  --floor "$MICRO_FLOOR"
python scripts/perf_report.py

echo "== ci.sh: all green =="
