#!/usr/bin/env bash
# CI entry point: tier-1 test suite (per-file sharded) plus an
# observability-enabled bench smoke whose evidence JSON and Chrome trace
# are asserted to be well-formed.
#
# Usage: scripts/ci.sh [extra pytest args...]
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

export JAX_PLATFORMS=cpu

echo "== tier-1 suite (sharded) =="
python scripts/run_suite.py "$@"

echo "== bench smoke (metrics + trace) =="
SMOKE_OUT="$(mktemp /tmp/waffle_ci_bench.XXXXXX.json)"
TRACE_OUT="$(mktemp /tmp/waffle_ci_trace.XXXXXX.json)"
trap 'rm -f "$SMOKE_OUT" "$TRACE_OUT"' EXIT

WAFFLE_METRICS=1 BENCH_SMOKE=1 \
  BENCH_TOTAL_BUDGET="${BENCH_TOTAL_BUDGET:-600}" \
  python bench.py --iters 5 --platform cpu --trace-out "$TRACE_OUT" \
  > "$SMOKE_OUT"

python - "$SMOKE_OUT" "$TRACE_OUT" <<'PY'
import json
import sys

smoke_path, trace_path = sys.argv[1], sys.argv[2]

with open(smoke_path) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert "metric" in evidence, f"no metric in evidence: {sorted(evidence)}"
assert "search_report" in evidence, (
    f"no search_report in evidence: {sorted(evidence)}"
)
report = evidence["search_report"]
for key in ("engine", "backend", "nodes_explored", "dispatch_total"):
    assert key in report, f"search_report missing {key!r}: {sorted(report)}"
assert "metrics" in evidence, f"no metrics snapshot: {sorted(evidence)}"
latency = evidence["metrics"].get("waffle_dispatch_latency_seconds", {})
assert latency.get("series"), "empty dispatch latency histograms"

with open(trace_path) as fh:
    trace = json.load(fh)
events = trace.get("traceEvents", [])
assert events, "empty Chrome trace"
cats = {e.get("cat") for e in events}
assert "search" in cats and "dispatch" in cats, f"missing span cats: {cats}"
print(
    f"ci bench smoke ok: {evidence['metric']}={evidence['value']}s, "
    f"{len(events)} trace events, "
    f"{len(latency['series'])} latency series"
)
PY

echo "== hot-loop microbench (steps/s regression gate) =="
# Raw run_extend throughput at the north-star geometry (256 reads x
# 10 kb, 1% error): the floor is 1.5x the r05 baseline (413 steps/s);
# the mode also cross-checks the appended bytes against ground truth,
# so a parity break fails the gate even when throughput holds.
MICRO_FLOOR="${WAFFLE_MICROBENCH_FLOOR:-620}"
python bench.py --microbench --platform cpu --iters 3 \
  --assert-steps-floor "$MICRO_FLOOR"

echo "== serve bench smoke (cross-job batching) =="
SERVE_OUT="$(mktemp /tmp/waffle_ci_serve.XXXXXX.json)"
trap 'rm -f "$SMOKE_OUT" "$TRACE_OUT" "$SERVE_OUT"' EXIT

WAFFLE_METRICS=1 BENCH_SMOKE=1 \
  python bench.py --serve 4 --platform cpu > "$SERVE_OUT"

python - "$SERVE_OUT" <<'PY'
import json
import sys

with open(sys.argv[1]) as fh:
    evidence = json.loads(fh.read().strip().splitlines()[-1])
assert evidence.get("mode") == "serve", f"not a serve line: {sorted(evidence)}"
assert evidence["jobs"] == 4, evidence["jobs"]
assert evidence["jobs_per_s"] > 0, evidence["jobs_per_s"]
assert evidence["parity"] is True, "served result diverged from serial"
assert 0 <= evidence["p50_job_latency_s"] <= evidence["p95_job_latency_s"], (
    evidence["p50_job_latency_s"], evidence["p95_job_latency_s"],
)
dispatch = evidence["serve_stats"]["dispatch"]
assert dispatch["coalesced_batches"] >= 1, dispatch
assert evidence["mean_batch_occupancy"] > 1.0, evidence["mean_batch_occupancy"]
jobs = evidence["serve_stats"]["jobs"]
assert jobs["done"] == 4 and jobs["failed"] == 0, jobs
serve_metrics = [
    k for k in evidence.get("metrics", {}) if k.startswith("waffle_serve")
]
assert "waffle_serve_batch_occupancy" in serve_metrics, serve_metrics
assert "waffle_serve_jobs_total" in serve_metrics, serve_metrics
print(
    f"ci serve smoke ok: {evidence['jobs_per_s']} jobs/s, "
    f"occupancy={evidence['mean_batch_occupancy']}, "
    f"p95={evidence['p95_job_latency_s']}s, "
    f"{len(serve_metrics)} serve metric families"
)
PY

echo "== ci.sh: all green =="
