#!/usr/bin/env python
"""Count blocking device dispatches for the dual/priority evidence
workloads on the jax-CPU backend (dispatch count is platform-invariant;
wall time on the tunneled TPU ~= dispatches x ~80 ms + exec — see
evidence/DUAL_DISPATCH_r04.json).

Usage: python scripts/dispatch_evidence.py [--dual R L] [--priority R L]
Prints one JSON line per requested workload.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--dual", nargs=2, type=int, default=None)
    parser.add_argument("--priority", nargs=2, type=int, default=None)
    parser.add_argument("--platform", default="cpu", choices=["cpu", "device"])
    return parser.parse_args(argv)


# Parse BEFORE anything imports jax: the platform pin must be decided by
# real argparse semantics (``--platform=cpu`` is ONE argv token — the
# old substring sniff missed it and let jax grab the device), and
# setting JAX_PLATFORMS in the env ahead of the import pins it however
# late the backend initializes.
if __name__ == "__main__":
    _ARGS = _parse_args()
    if _ARGS.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np

from waffle_con_tpu.ops.scorer import DISPATCH_COUNTER_KEYS as DISPATCH_KEYS


def _plat():
    import jax

    return "jax" + jax.devices()[0].platform


def _cfg(backend, min_count, band):
    from waffle_con_tpu import CdwfaConfigBuilder

    return (
        CdwfaConfigBuilder()
        .min_count(min_count)
        .backend(backend)
        .initial_band(band)
        .build()
    )


def dual_workload(num_reads, seq_len, error_rate=0.01):
    from waffle_con_tpu.utils.example_gen import generate_test, corrupt

    rng = np.random.default_rng(1)
    truth, reads1 = generate_test(4, seq_len, num_reads // 2, error_rate, seed=1)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=3, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    h2 = bytes(h2)
    reads2 = [
        corrupt(h2, error_rate, np.random.default_rng(100 + i))
        for i in range(num_reads // 2)
    ]
    return list(reads1) + reads2


def run_dual(num_reads, seq_len):
    from waffle_con_tpu import DualConsensusDWFA
    from waffle_con_tpu.native import native_dual_consensus

    band = 16 + int(2 * 0.01 * seq_len)
    min_count = max(2, num_reads // 4)
    reads = dual_workload(num_reads, seq_len)
    cpp_start = time.perf_counter()
    cpp = native_dual_consensus(reads, config=_cfg("native", min_count, band))
    cpp_wall = time.perf_counter() - cpp_start

    def once():
        eng = DualConsensusDWFA(_cfg("jax", min_count, band))
        for r in reads:
            eng.add_sequence(r)
        return eng, eng.consensus()

    eng, res = once()  # warm-up/compile
    t0 = time.perf_counter()
    eng, res = once()
    wall = time.perf_counter() - t0
    c = eng.last_search_stats["scorer_counters"]
    return {
        "metric": f"dual_{num_reads}x{seq_len}_{_plat()}",
        "parity": bool(res == cpp),
        "jax_wall_s": round(wall, 3),
        "cpp_wall_s": round(cpp_wall, 4),
        "blocking_dispatches": sum(c.get(k, 0) for k in DISPATCH_KEYS),
        "counters": {
            k: v
            for k, v in sorted(c.items())
            if v and (k in DISPATCH_KEYS or k.startswith("arena"))
        },
    }


def run_priority(num_reads, seq_len):
    from waffle_con_tpu import PriorityConsensusDWFA
    from waffle_con_tpu.native import native_priority_consensus
    from waffle_con_tpu.utils.example_gen import generate_test, corrupt

    band = 16 + int(2 * 0.01 * seq_len)
    min_count = max(2, num_reads // 4)
    truth, level0 = generate_test(4, seq_len // 2, num_reads, 0.01, seed=3)
    t1a, _ = generate_test(4, seq_len, 1, 0.0, seed=4)
    t1b = bytearray(t1a)
    t1b[seq_len // 3] = (t1b[seq_len // 3] + 1) % 4
    t1b[2 * seq_len // 3] = (t1b[2 * seq_len // 3] + 2) % 4
    t1b = bytes(t1b)
    chains = []
    for i in range(num_reads):
        level1_truth = t1a if i < num_reads // 2 else t1b
        lvl1 = corrupt(level1_truth, 0.01, np.random.default_rng(200 + i))
        chains.append([level0[i], lvl1])

    cpp_start = time.perf_counter()
    cpp = native_priority_consensus(chains, config=_cfg("native", min_count, band))
    cpp_wall = time.perf_counter() - cpp_start

    def once():
        eng = PriorityConsensusDWFA(_cfg("jax", min_count, band))
        for ch in chains:
            eng.add_sequence_chain(ch)
        return eng, eng.consensus()

    eng, res = once()
    t0 = time.perf_counter()
    eng, res = once()
    wall = time.perf_counter() - t0
    c = eng.last_search_stats["scorer_counters"]
    return {
        "metric": f"priority_{num_reads}x{seq_len}_{_plat()}",
        "parity": bool(res == cpp),
        "jax_wall_s": round(wall, 3),
        "cpp_wall_s": round(cpp_wall, 4),
        "blocking_dispatches": sum(c.get(k, 0) for k in DISPATCH_KEYS),
        "counters": {
            k: v
            for k, v in sorted(c.items())
            if v and (k in DISPATCH_KEYS or k.startswith("arena"))
        },
    }


def main():
    args = _ARGS if __name__ == "__main__" else _parse_args()

    from waffle_con_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()
    if args.dual:
        print(json.dumps(run_dual(*args.dual)), flush=True)
    if args.priority:
        print(json.dumps(run_priority(*args.priority)), flush=True)


if __name__ == "__main__":
    main()
