"""Dump optimized TPU HLO for ``_j_run`` at north-star shapes and print
an opcode histogram of the while-loop body (launch count ~= per-step
kernel count, the latency driver)."""
import re
import sys
from collections import Counter

sys.path.insert(0, ".")

import numpy as np

from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.ops.jax_scorer import JaxScorer, _j_run
from waffle_con_tpu.utils.example_gen import generate_test

truth, reads = generate_test(4, 2_000, 256, 0.01, seed=0)
cfg = (
    CdwfaConfigBuilder().min_count(64).backend("jax").initial_band(216)
    .build()
)
sc = JaxScorer(reads, cfg)
h = sc.root(np.ones(len(reads), dtype=bool))
slot = sc._slot_of[h]
params = np.asarray(
    [slot, 2**31 - 1, 2**31 - 1, 0, 64, 0, 1000, 0, -1, 1], dtype=np.int32
)
lowered = _j_run.lower(
    sc._state, sc._reads, sc._reads_pad, sc._rlen, params, sc._wc, sc._et,
    sc._A, True,
)
txt = lowered.compile().as_text()
out = "/tmp/jrun_hlo.txt"
with open(out, "w") as f:
    f.write(txt)
print(f"wrote {len(txt)} bytes to {out}")

# find the while body computation: the largest computation mentioning
# "body" in its name
bodies = {}
cur = None
for line in txt.splitlines():
    m = re.match(r"%?([\w.\-]*body[\w.\-]*) (?:\([^)]*\) -> .*{)", line)
    if line.startswith("}"):  # computation end
        cur = None
    if m:
        cur = m.group(1)
        bodies[cur] = []
    elif cur is not None:
        bodies[cur].append(line)

for name, lines in bodies.items():
    ops = Counter()
    for ln in lines:
        m = re.match(r"\s*(?:ROOT )?%?[\w.\-]+ = \S+ ([\w\-]+)\(", ln)
        if m:
            ops[m.group(1)] += 1
    total = sum(ops.values())
    if total < 10:
        continue
    print(f"\n== {name}: {total} HLO ops")
    for op, n in ops.most_common(20):
        print(f"  {op:30s} {n}")
