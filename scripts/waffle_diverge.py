#!/usr/bin/env python
"""waffle_diverge: first-divergence triage for the search audit plane.

Companion CLI to ``waffle_con_tpu/obs/audit.py`` — the three triage
verbs, plus the seeded-divergence CI drill:

``diff A.jsonl B.jsonl``
    Align two decision audit logs (jax-vs-python, mega-on-vs-off,
    K=4-vs-K=1, resumed-vs-scratch, ...) as order-independent decision
    maps and print the first conflicting decision: exact pop index on
    both sides, both records, and the node identity at that point.
    Exit 0 when the logs agree on every shared decision, 3 when they
    diverge.

``minimize`` (drill-internal; see ``--drill``)
    Shrink a diverging run to its last few pops: snapshot the search
    through the checkpoint seam a few pops before the first divergence
    and emit a self-contained repro JSON (checkpoint wire form + the
    fault spec + the expected divergence).

``replay REPRO.json``
    Resume the repro's checkpoint through the ``resume`` seam with the
    recorded fault armed and the python lockstep shadow engaged; exit 0
    when the recorded divergence reproduces at the same decision within
    the pop budget, 3 otherwise.

``--drill``
    The CI self-test (``scripts/ci.sh``): clean lockstep shadow over
    golden fixtures must report zero divergences; then a deterministic
    ``flip_vote`` fault (``runtime/faults.py``) flips one committed
    vote on the jax engine and the drill asserts the shadow aborts with
    exactly one ``parity_divergence`` flight incident, the offline
    differ localizes the same pop, and the minimized repro replays to
    the same divergence in <= 10 pops.

Everything runs in-process without mutating the environment (the audit
``capture``/``shadow_override`` seams), so the drill composes with any
ambient WAFFLE_* configuration CI sets.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fail(msg: str) -> "SystemExit":
    print(f"waffle_diverge: FAIL: {msg}")
    return SystemExit(2)


def cmd_diff(path_a: str, path_b: str) -> int:
    from waffle_con_tpu.obs import audit as obs_audit

    detail = obs_audit.diff_logs(
        obs_audit.load_log(path_a), obs_audit.load_log(path_b)
    )
    if detail is None:
        print(json.dumps({"divergence": None}))
        return 0
    print(json.dumps({"divergence": detail}, indent=2, default=repr))
    return 3


def _arm_fault(fault: dict):
    from waffle_con_tpu.runtime import faults as faults_mod

    plan = faults_mod.install(faults_mod.FaultPlan())
    plan.add(
        fault["kind"],
        backend=fault.get("backend", "*"),
        op=fault.get("op", "*"),
        at=fault.get("at"),
        count=fault.get("count", 1),
    )
    return plan


def _replay_repro(repro: dict) -> dict:
    """Resume the repro checkpoint with its fault armed under the python
    lockstep shadow; returns the observed divergence detail (raises
    SystemExit(2) when nothing diverges)."""
    from waffle_con_tpu.models import checkpoint as ckpt_mod
    from waffle_con_tpu.obs import audit as obs_audit
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.runtime import faults as faults_mod

    checkpoint = ckpt_mod.SearchCheckpoint.from_wire(repro["checkpoint"])
    engine = ckpt_mod.resume_engine(checkpoint)
    _arm_fault(repro["fault"])
    obs_flight.reset()
    try:
        with obs_audit.shadow_override("python"):
            engine.consensus()
    except obs_audit.ParityDivergence as exc:
        return exc.detail
    finally:
        faults_mod.clear()
    raise _fail("repro replayed without any divergence")


def cmd_replay(path: str) -> int:
    with open(path) as fh:
        repro = json.load(fh)
    detail = _replay_repro(repro)
    expect = repro.get("expect", {})
    ok_key = list(detail.get("key", [])) == list(expect.get("key", []))
    budget = repro.get("budget_pops", 10)
    resumed_pops = detail.get("pop_a", 0) - repro.get("ckpt_pops", 0)
    ok_budget = resumed_pops <= budget
    print(json.dumps({
        "divergence": detail, "expected_key": expect.get("key"),
        "key_match": ok_key, "resumed_pops": resumed_pops,
        "budget_pops": budget,
    }, indent=2, default=repr))
    return 0 if (ok_key and ok_budget) else 3


# -- the seeded-divergence CI drill ------------------------------------

#: single-engine drill reads: a clean 3-vs-3 fork at position 2, then a
#: long unambiguous tail — plain branch pops through the fork, device
#: runs down the tail (so the fault lands mid-run territory)
DRILL_READS = [
    b"ACGTTGCAACGTTGCAACGT",
    b"ACGTTGCAACGTTGCAACGT",
    b"ACGTTGCAACGTTGCAACGT",
    b"ACCTTGCAACGTTGCAACGT",
    b"ACCTTGCAACGTTGCAACGT",
    b"ACCTTGCAACGTTGCAACGT",
]


def _single_engine(backend: str):
    from waffle_con_tpu import ConsensusDWFA
    from waffle_con_tpu.config import CdwfaConfig

    engine = ConsensusDWFA(CdwfaConfig(backend=backend))
    for read in DRILL_READS:
        engine.add_sequence(read)
    return engine


def _drill_clean_shadow() -> None:
    from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
    from waffle_con_tpu.models.dual_consensus import DualConsensusDWFA
    from waffle_con_tpu.obs import audit as obs_audit
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.utils.fixtures import load_dual_fixture

    obs_flight.reset()
    obs_audit.reset_stats()
    with obs_audit.shadow_override("python"):
        _single_engine("jax").consensus()
        seqs, _expected = load_dual_fixture(
            "dual_001", True, ConsensusCost.L1_DISTANCE
        )
        dual = DualConsensusDWFA(CdwfaConfig(backend="jax"))
        for s in seqs:
            dual.add_sequence(s)
        dual.consensus()
    snap = obs_audit.stats_snapshot()
    if snap["divergences"] != 0:
        raise _fail(f"clean shadow reported divergences: {snap}")
    if snap["shadow_pops"] <= 0:
        raise _fail("clean shadow compared zero pops")
    incidents = [
        i for i in obs_flight.incidents()
        if i.get("reason") == "parity_divergence"
    ]
    if incidents:
        raise _fail(f"clean shadow fired {len(incidents)} incidents")
    print(
        f"waffle_diverge: clean shadow OK "
        f"({snap['shadow_pops']} pops compared, 0 divergences)"
    )


def _drill_find_target() -> int:
    """Baseline jax capture: the consensus length of the first device
    run (a pop where exactly one symbol passes) — where ``flip_vote``
    will deterministically land."""
    from waffle_con_tpu.obs import audit as obs_audit

    with obs_audit.capture(strict_align=True) as sinks:
        _single_engine("jax").consensus()
    runs = [
        r for r in sinks[0].records
        if r["kind"] == "run" and r.get("forced")
    ]
    if not runs:
        raise _fail("baseline jax run produced no forced run records")
    preferred = [r for r in runs if r["pop"] >= 3]
    target = (preferred or runs)[0]
    print(
        f"waffle_diverge: fault target: consensus length {target['len']} "
        f"(baseline pop {target['pop']})"
    )
    return int(target["len"])


def _drill_seeded_shadow(length: int) -> dict:
    from waffle_con_tpu.obs import audit as obs_audit
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.runtime import faults as faults_mod

    obs_flight.reset()
    obs_audit.reset_stats()
    _arm_fault({"kind": "flip_vote", "backend": "jax", "op": "vote",
                "at": length, "count": 1})
    try:
        with obs_audit.shadow_override("python"):
            _single_engine("jax").consensus()
        raise _fail("seeded shadow did not abort on the flipped vote")
    except obs_audit.ParityDivergence as exc:
        detail = exc.detail
    finally:
        faults_mod.clear()
    incidents = [
        i for i in obs_flight.incidents()
        if i.get("reason") == "parity_divergence"
    ]
    if len(incidents) != 1:
        raise _fail(
            f"expected exactly one parity_divergence incident, "
            f"got {len(incidents)}"
        )
    key = detail.get("key") or []
    if not key or key[0] != "s" or key[1] != length:
        raise _fail(f"divergence key {key} is not at length {length}")
    print(
        f"waffle_diverge: shadow aborted at pop {detail['pop_a']} "
        f"(key={key}, one incident) — streaming parity works"
    )
    return detail


def _drill_offline_diff(length: int, shadow_detail: dict) -> None:
    from waffle_con_tpu.obs import audit as obs_audit
    from waffle_con_tpu.runtime import faults as faults_mod

    _arm_fault({"kind": "flip_vote", "backend": "jax", "op": "vote",
                "at": length, "count": 1})
    try:
        with obs_audit.capture(strict_align=True) as sinks:
            _single_engine("jax").consensus()
    finally:
        faults_mod.clear()
    jax_records = sinks[0].records
    with obs_audit.capture(strict_align=True) as sinks:
        _single_engine("python").consensus()
    detail = obs_audit.diff_logs(jax_records, sinks[0].records)
    if detail is None:
        raise _fail("offline differ missed the seeded divergence")
    if detail["pop_a"] != shadow_detail["pop_a"]:
        raise _fail(
            f"differ pop {detail['pop_a']} != shadow pop "
            f"{shadow_detail['pop_a']}"
        )
    if list(detail["key"]) != list(shadow_detail["key"]):
        raise _fail(
            f"differ key {detail['key']} != shadow key "
            f"{shadow_detail['key']}"
        )
    print(
        f"waffle_diverge: offline differ localized the same divergence "
        f"(pop {detail['pop_a']})"
    )


def _drill_minimize(length: int, detail: dict) -> str:
    """Snapshot the seeded run a few pops before the divergence and
    write the self-contained repro JSON; returns its path."""
    from waffle_con_tpu.models import checkpoint as ckpt_mod
    from waffle_con_tpu.obs import audit as obs_audit
    from waffle_con_tpu.runtime import faults as faults_mod

    # poll ordinals are completed-pop counts; record pops are 1-based,
    # so the divergent iteration is poll D-1 — snapshot 3 polls earlier
    ckpt_pops = max(0, int(detail["pop_a"]) - 4)
    ctrl = ckpt_mod.CheckpointController(
        snapshot_at_pops={ckpt_pops}, preempt=True, label="diverge-min"
    )
    _arm_fault({"kind": "flip_vote", "backend": "jax", "op": "vote",
                "at": length, "count": 1})
    checkpoint = None
    try:
        with ckpt_mod.installed(ctrl):
            with obs_audit.capture(strict_align=True):
                try:
                    _single_engine("jax").consensus()
                except ckpt_mod.SearchPreempted as exc:
                    checkpoint = exc.checkpoint
    finally:
        faults_mod.clear()
    if checkpoint is None:
        raise _fail(
            f"minimizer run was not preempted at pop {ckpt_pops}"
        )
    repro = {
        "schema": "waffle-diverge-repro/1",
        "checkpoint": checkpoint.to_wire(),
        "ckpt_pops": ckpt_pops,
        "fault": {"kind": "flip_vote", "backend": "jax", "op": "vote",
                  "at": length, "count": 1},
        "expect": {"pop": detail["pop_a"], "key": list(detail["key"])},
        "budget_pops": 10,
    }
    fd, path = tempfile.mkstemp(
        prefix="waffle-diverge-repro-", suffix=".json"
    )
    with os.fdopen(fd, "w") as fh:
        json.dump(repro, fh)
    print(
        f"waffle_diverge: minimized repro at {path} "
        f"(checkpoint at pop {ckpt_pops}, expect divergence at "
        f"pop {detail['pop_a']})"
    )
    return path


def _drill_replay(path: str) -> None:
    with open(path) as fh:
        repro = json.load(fh)
    detail = _replay_repro(repro)
    expect = repro["expect"]
    if list(detail["key"]) != list(expect["key"]):
        raise _fail(
            f"replayed divergence key {detail['key']} != recorded "
            f"{expect['key']}"
        )
    resumed_pops = int(detail["pop_a"]) - int(repro["ckpt_pops"])
    if resumed_pops > int(repro["budget_pops"]):
        raise _fail(
            f"replay took {resumed_pops} pops "
            f"(> budget {repro['budget_pops']})"
        )
    print(
        f"waffle_diverge: repro replayed to the same divergence in "
        f"{resumed_pops} pops (pop {detail['pop_a']}, key match)"
    )


def cmd_drill() -> int:
    _drill_clean_shadow()
    length = _drill_find_target()
    detail = _drill_seeded_shadow(length)
    _drill_offline_diff(length, detail)
    repro_path = _drill_minimize(length, detail)
    try:
        _drill_replay(repro_path)
    finally:
        try:
            os.unlink(repro_path)
        except OSError:
            pass
    print("waffle_diverge: drill PASSED")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="command")
    p_diff = sub.add_parser("diff", help="first divergence of two logs")
    p_diff.add_argument("log_a")
    p_diff.add_argument("log_b")
    p_replay = sub.add_parser("replay", help="replay a minimized repro")
    p_replay.add_argument("repro")
    parser.add_argument(
        "--drill", action="store_true",
        help="run the seeded-divergence CI self-test",
    )
    args = parser.parse_args()
    if args.drill:
        return cmd_drill()
    if args.command == "diff":
        return cmd_diff(args.log_a, args.log_b)
    if args.command == "replay":
        return cmd_replay(args.repro)
    parser.error("nothing to do: pass a subcommand or --drill")
    return 2


if __name__ == "__main__":
    sys.exit(main())
