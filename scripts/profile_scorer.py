#!/usr/bin/env python
"""Profile the JaxScorer device loop: steps/sec of run_extend, growth
events, and per-call wall time, at a configurable problem size.

Obs integration: with ``WAFFLE_METRICS=1`` the scorer is wrapped in the
obs ``TimedScorer`` and a registry snapshot (per-op dispatch latency
histograms) is printed at the end; with ``WAFFLE_TRACE=<path>`` the
nested dispatch/device-sync spans are written there as a Chrome trace
at exit."""

import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from waffle_con_tpu.config import CdwfaConfigBuilder
from waffle_con_tpu.obs import metrics_enabled, registry
from waffle_con_tpu.obs.instrument import maybe_instrument
from waffle_con_tpu.ops.jax_scorer import JaxScorer
from waffle_con_tpu.utils.example_gen import generate_test


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    L = int(sys.argv[2]) if len(sys.argv) > 2 else 2000
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 500
    err = 0.01
    mc = max(2, R // 4)
    truth, reads = generate_test(4, L, R, err, seed=0)
    cfg = CdwfaConfigBuilder().min_count(mc).build()
    sc = maybe_instrument(JaxScorer(reads, cfg), "jax")
    h = sc.root(np.ones(R, dtype=bool))

    cons = b""
    t_all = time.perf_counter()
    calls = 0
    while True:
        t0 = time.perf_counter()
        steps, code, app, _stats, _recs = sc.run_extend(
            h, cons, 10**9, 2**31 - 1, 0, mc, False, chunk
        )
        dt = time.perf_counter() - t0
        calls += 1
        cons += app
        per = dt / max(steps, 1) * 1e3
        print(
            f"len={len(cons):6d} steps={steps:4d} code={code} "
            f"E={sc.bucket_e:4d} wall={dt:7.3f}s per_step={per:7.3f}ms"
        )
        if code == 2:
            break
        if code == 1:
            # votes need host arbitration: resolve by pushing the plurality
            # symbol so the profile covers the configured length, not just
            # the unambiguous prefix
            stats = sc.stats(h, cons)
            votes = stats.occ.sum(axis=0)
            if votes.sum() == 0:
                print("no candidates; stopping")
                break
            sym = int(sc.symtab[int(np.argmax(votes))])
            cons += bytes([sym])
            sc.push(h, cons)
        elif steps == 0 and code not in (4, 5):
            break
        if len(cons) > L + 200:
            break
    total = time.perf_counter() - t_all
    print(
        f"TOTAL: {total:.2f}s for {len(cons)} symbols in {calls} calls "
        f"({total/max(len(cons),1)*1e3:.3f} ms/symbol), final E={sc.bucket_e}"
    )
    if metrics_enabled():
        print(registry().render_prometheus(), end="")


if __name__ == "__main__":
    main()
