#!/usr/bin/env python
"""Profile the JaxScorer device loop: steps/sec of run_extend, growth
events, and per-call wall time, at a configurable problem size.

Usage: python scripts/profile_scorer.py [--reads R] [--len L]
           [--chunk STEPS] [--platform cpu|device] [--profile]
           [--perfdb / --no-perfdb]

Obs integration: ``--profile`` (or ``WAFFLE_PROFILE=1``) turns on
phase-attributed dispatch profiling and prints the per-kernel
host-prep / device-compute / transfer / host-post breakdown at the
end; with ``WAFFLE_METRICS=1`` the scorer is wrapped in the obs
``TimedScorer`` and a registry snapshot (per-op dispatch latency
histograms) is printed too; with ``WAFFLE_TRACE=<path>`` the nested
dispatch/device-sync spans are written there as a Chrome trace at
exit.  Unless ``--no-perfdb``, the run appends one ``profile``
record (ms/symbol) to the perf database so the trajectory shows up
in ``scripts/perf_report.py``.
"""

import argparse
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def _parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--reads", type=int, default=64)
    parser.add_argument("--len", type=int, dest="seq_len", default=2000)
    parser.add_argument("--chunk", type=int, default=500,
                        help="max device steps per run_extend call")
    parser.add_argument("--platform", default="cpu",
                        choices=["cpu", "device"])
    parser.add_argument("--profile", action="store_true",
                        help="phase-attributed dispatch profiling "
                        "(WAFFLE_PROFILE)")
    parser.add_argument("--perfdb", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="append a perfdb record (default on)")
    return parser.parse_args(argv)


# Parse BEFORE anything imports jax: the platform pin must be decided
# by real argparse semantics, and setting JAX_PLATFORMS in the env
# ahead of the import pins it however late the backend initializes.
if __name__ == "__main__":
    _ARGS = _parse_args()
    if _ARGS.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    if _ARGS.profile:
        os.environ["WAFFLE_PROFILE"] = "1"

import numpy as np  # noqa: E402

from waffle_con_tpu.config import CdwfaConfigBuilder  # noqa: E402
from waffle_con_tpu.obs import metrics_enabled, registry  # noqa: E402
from waffle_con_tpu.obs import phases as obs_phases  # noqa: E402
from waffle_con_tpu.obs.instrument import maybe_instrument  # noqa: E402
from waffle_con_tpu.ops.jax_scorer import JaxScorer  # noqa: E402
from waffle_con_tpu.utils.example_gen import generate_test  # noqa: E402


def main(args):
    R, L, chunk = args.reads, args.seq_len, args.chunk
    err = 0.01
    mc = max(2, R // 4)
    truth, reads = generate_test(4, L, R, err, seed=0)
    cfg = CdwfaConfigBuilder().min_count(mc).build()
    sc = maybe_instrument(JaxScorer(reads, cfg), "jax")
    h = sc.root(np.ones(R, dtype=bool))

    cons = b""
    t_all = time.perf_counter()
    calls = 0
    while True:
        t0 = time.perf_counter()
        steps, code, app, _stats, _recs = sc.run_extend(
            h, cons, 10**9, 2**31 - 1, 0, mc, False, chunk
        )
        dt = time.perf_counter() - t0
        calls += 1
        cons += app
        per = dt / max(steps, 1) * 1e3
        print(
            f"len={len(cons):6d} steps={steps:4d} code={code} "
            f"E={sc.bucket_e:4d} wall={dt:7.3f}s per_step={per:7.3f}ms"
        )
        if code == 2:
            break
        if code == 1:
            # votes need host arbitration: resolve by pushing the plurality
            # symbol so the profile covers the configured length, not just
            # the unambiguous prefix
            stats = sc.stats(h, cons)
            votes = stats.occ.sum(axis=0)
            if votes.sum() == 0:
                print("no candidates; stopping")
                break
            sym = int(sc.symtab[int(np.argmax(votes))])
            cons += bytes([sym])
            sc.push(h, cons)
        elif steps == 0 and code not in (4, 5):
            break
        if len(cons) > L + 200:
            break
    total = time.perf_counter() - t_all
    ms_per_symbol = total / max(len(cons), 1) * 1e3
    print(
        f"TOTAL: {total:.2f}s for {len(cons)} symbols in {calls} calls "
        f"({ms_per_symbol:.3f} ms/symbol), final E={sc.bucket_e}"
    )
    if obs_phases.profiling_enabled():
        print("phase breakdown (per kernel/op/K/geometry):")
        for label, row in obs_phases.snapshot().items():
            print(
                f"  {label:36s} n={row['count']:4d} "
                f"mean={row['mean_ms']:.2f}ms "
                f"prep={row['host_prep']:.3f}s "
                f"dev={row['device_compute']:.3f}s "
                f"xfer={row['transfer']:.3f}s "
                f"post={row['host_post']:.3f}s"
            )
    if metrics_enabled():
        print(registry().render_prometheus(), end="")
    if args.perfdb:
        from waffle_con_tpu.obs import perfdb

        rec = perfdb.make_record(
            "profile", f"profile_{R}x{L}_ms_per_symbol",
            round(ms_per_symbol, 4), "ms/symbol",
            platform=args.platform, calls=calls,
            symbols=len(cons), chunk=chunk,
        )
        if obs_phases.profiling_enabled():
            rec["phases"] = {
                k: round(v, 6) for k, v in obs_phases.totals().items()
            }
        path = perfdb.append_record(rec)
        print(f"perfdb: appended profile record to {path}")


if __name__ == "__main__":
    main(_ARGS)
