#!/usr/bin/env python
"""Tier-1-equivalent test runner: one pytest subprocess per test file.

The monolithic ``python -m pytest tests/`` run is vulnerable to a known
XLA:CPU teardown segfault (see ROADMAP.md "end-of-round compile flake"):
a crash in ONE file's interpreter teardown takes down the whole run and
every not-yet-reported result with it.  Sharding by file puts a process
boundary around each file, so a segfault (or a wedged TPU-runtime
thread) costs exactly that file — the rest of the suite still reports.

Usage::

    python scripts/run_suite.py            # all of tests/, tier-1 flags
    python scripts/run_suite.py -k fault   # extra args forwarded to pytest

Exit code is 0 iff every shard passed (pytest rc 0, or rc 5 = nothing
collected after deselection, which the tier-1 ``-m 'not slow'`` filter
makes routine).  Per-shard wall-clock is bounded by
``WAFFLE_SUITE_TIMEOUT`` seconds (default 600); a timeout kills the
shard's whole process group and counts as a failure.
"""

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHARD_TIMEOUT_S = int(os.environ.get(  # waffle-lint: disable=WL001(stdlib-only runner: importing the package would pull jax into the shard driver)
    "WAFFLE_SUITE_TIMEOUT", "600"))

#: the tier-1 flag set (ROADMAP.md) minus the paths
PYTEST_FLAGS = [
    "-q",
    "-m",
    "not slow",
    "--continue-on-collection-errors",
    "-p",
    "no:cacheprovider",
    "-p",
    "no:xdist",
    "-p",
    "no:randomly",
]


def discover(tests_dir):
    return sorted(
        name
        for name in os.listdir(tests_dir)
        if name.startswith("test_") and name.endswith(".py")
    )


def run_shard(test_file, extra_args):
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        os.path.join("tests", test_file),
        *PYTEST_FLAGS,
        *extra_args,
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    start = time.monotonic()
    proc = subprocess.Popen(cmd, cwd=REPO, env=env, start_new_session=True)
    try:
        rc = proc.wait(timeout=SHARD_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()
        rc = -signal.SIGKILL
    return rc, time.monotonic() - start


def main() -> int:
    extra_args = sys.argv[1:]
    tests_dir = os.path.join(REPO, "tests")
    shards = discover(tests_dir)
    if not shards:
        print("no test files found", file=sys.stderr)
        return 2

    results = []
    for test_file in shards:
        print(f"=== {test_file} ===", flush=True)
        rc, wall = run_shard(test_file, extra_args)
        # rc 5 = pytest collected nothing (e.g. every test deselected by
        # the tier-1 marker filter): not a failure
        ok = rc in (0, 5)
        results.append((test_file, rc, wall, ok))

    print("\n=== suite summary ===")
    failed = 0
    for test_file, rc, wall, ok in results:
        status = "ok" if ok else f"FAIL (rc={rc})"
        if rc == 5:
            status = "ok (nothing collected)"
        print(f"  {test_file:<32} {status:<24} {wall:6.1f}s")
        failed += not ok
    print(f"{len(results) - failed}/{len(results)} shards passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
