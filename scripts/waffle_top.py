#!/usr/bin/env python
"""waffle_top: live terminal view of a serving process.

Polls the JSON stats file a :class:`ConsensusService` publishes when
``WAFFLE_STATS_FILE`` is set (see ``serve/service.py``) and renders a
compact top-style dashboard: job counts and queue depth, dispatcher
batching occupancy, rolling SLO percentiles (p50/p95/p99 + EWMA over
dispatch latency and job wall time), per-backend dispatch latency from
the metrics snapshot, and the most recent flight-recorder incidents.
When the payload comes from a :class:`ProcFrontDoor` (out-of-process
serving) the per-worker table shows pid, health state, outstanding
jobs, slot occupancy, requeue/demote/shed counters, and the
checkpoint/migration columns (frames + bytes streamed, jobs migrated
from a checkpoint vs restarted from scratch) instead of the
in-process replica table.  A door running the fleet observability
plane additionally gets a ``fleet`` section: per-worker metric
snapshot age (from the worker's last STATS frame), forwarded
incident counts, each worker's own rolling dispatch p95, and the
door-side e2e job p50/p95 the whole fleet is judged by.

Usage::

    WAFFLE_STATS_FILE=/tmp/waffle_stats.json python bench.py --serve 8 &
    python scripts/waffle_top.py /tmp/waffle_stats.json

    python scripts/waffle_top.py /tmp/waffle_stats.json --once  # one frame

No dependencies beyond the standard library; plain ANSI, no curses.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

CLEAR = "\x1b[2J\x1b[H"
BOLD = "\x1b[1m"
DIM = "\x1b[2m"
RESET = "\x1b[0m"


def _fmt_s(value) -> str:
    if value is None:
        return "-"
    if value < 0.001:
        return f"{value * 1e6:.0f}us"
    if value < 1.0:
        return f"{value * 1e3:.1f}ms"
    return f"{value:.2f}s"


def _load(path: str):
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _backend_latency_rows(metrics: dict) -> list:
    """``(label, mean, count)`` per series of the dispatch-latency
    histogram family."""
    family = metrics.get("waffle_dispatch_latency_seconds", {})
    rows = []
    for label, hist in sorted(family.get("series", {}).items()):
        count = hist.get("count", 0)
        mean = hist.get("sum", 0.0) / count if count else None
        rows.append((label, mean, count))
    return rows


def render(payload: dict, plain: bool = False) -> str:
    bold = "" if plain else BOLD
    dim = "" if plain else DIM
    reset = "" if plain else RESET
    lines = []
    age = time.time() - payload.get("unix_time", 0)
    lines.append(
        f"{bold}waffle_top{reset} — service "
        f"{payload.get('service', '?')!r}  "
        f"{dim}(sampled {age:.1f}s ago){reset}"
    )

    stats = payload.get("stats", {})
    jobs = stats.get("jobs", {})
    lines.append(
        f"jobs: submitted={jobs.get('submitted', 0)} "
        f"done={jobs.get('done', 0)} failed={jobs.get('failed', 0)} "
        f"expired={jobs.get('expired', 0)} "
        f"cancelled={jobs.get('cancelled', 0)} "
        f"rejected={jobs.get('rejected', 0)}  "
        f"queue_depth={stats.get('queue_depth', 0)}"
    )
    dispatch = stats.get("dispatch", {})
    if dispatch:
        lines.append(
            f"dispatch: batches={dispatch.get('batches', 0)} "
            f"coalesced={dispatch.get('coalesced_batches', 0)} "
            f"direct={dispatch.get('direct_dispatches', 0)} "
            f"mean_occupancy={dispatch.get('mean_batch_occupancy', 0):.2f} "
            f"max_occupancy={dispatch.get('occupancy_max', 0)}"
        )
    cache = stats.get("cache")
    if cache:
        hits = (cache.get("exact", 0) + cache.get("certified", 0)
                + cache.get("checkpoint", 0))
        lines.append(
            f"cache: hits={hits} "
            f"(exact={cache.get('exact', 0)} "
            f"certified={cache.get('certified', 0)} "
            f"ckpt={cache.get('checkpoint', 0)}) "
            f"misses={cache.get('misses', 0)} "
            f"quarantined={cache.get('quarantined', 0)}  "
            f"store={cache.get('results', 0)}r/"
            f"{cache.get('checkpoints', 0)}c"
        )
    audit = payload.get("audit")
    if audit:
        shadow = audit.get("shadow") or "off"
        lines.append(
            f"audit: records={audit.get('records', 0)} "
            f"shadow={shadow} "
            f"shadow_pops={audit.get('shadow_pops', 0)} "
            f"divergences={audit.get('divergences', 0)}"
        )

    replicas = payload.get("replicas") or stats.get("replicas")
    if replicas:
        lines.append(f"{bold}replicas{reset} ({len(replicas)})")
        lines.append(
            f"  {'replica':<16} {'state':<9} {'outst':>5} {'queue':>5} "
            f"{'routed':>6} {'done':>6} {'demote':>6} {'shed':>4} "
            f"{'occ':>5} {'hold':>8}"
        )
        for rep in replicas:
            jobs_r = rep.get("jobs", {})
            hold = rep.get("last_hold_ms")
            lines.append(
                f"  {str(rep.get('replica', '?'))[:16]:<16} "
                f"{str(rep.get('state', '?')):<9} "
                f"{rep.get('outstanding', 0):>5} "
                f"{rep.get('queue_depth', 0):>5} "
                f"{rep.get('routed', 0):>6} "
                f"{jobs_r.get('done', 0):>6} "
                f"{rep.get('demotions', 0):>6} "
                f"{rep.get('sheds', 0):>4} "
                f"{rep.get('mean_batch_occupancy', 0):>5.2f} "
                f"{(str(hold) + 'ms') if hold is not None else '-':>8}"
            )

    workers = payload.get("workers") or stats.get("workers")
    if workers:
        lines.append(f"{bold}worker processes{reset} ({len(workers)})")
        lines.append(
            f"  {'worker':<16} {'pid':>7} {'state':<9} {'outst':>5} "
            f"{'slots':>5} {'occ':>5} {'routed':>6} {'requeue':>7} "
            f"{'migr':>4} {'rst':>3} {'ckpt':>5} {'ckptKB':>6} "
            f"{'demote':>6} {'shed':>4} {'readmit':>7}"
        )
        for wkr in workers:
            lines.append(
                f"  {str(wkr.get('worker', '?'))[:16]:<16} "
                f"{wkr.get('pid') or '-':>7} "
                f"{str(wkr.get('state', '?')):<9} "
                f"{wkr.get('outstanding', 0):>5} "
                f"{wkr.get('slots', 0):>5} "
                f"{wkr.get('occupancy', 0):>5.2f} "
                f"{wkr.get('routed', 0):>6} "
                f"{wkr.get('requeues', 0):>7} "
                f"{wkr.get('migrations', 0):>4} "
                f"{wkr.get('restarts', 0):>3} "
                f"{wkr.get('ckpt_frames', 0):>5} "
                f"{wkr.get('ckpt_bytes', 0) // 1024:>6} "
                f"{wkr.get('demotions', 0):>6} "
                f"{wkr.get('sheds', 0):>4} "
                f"{wkr.get('readmits', 0):>7}"
            )

    fleet = payload.get("fleet")
    if fleet and workers:
        slo_all = payload.get("slo", {})
        job_w = slo_all.get("job", {})
        lines.append(
            f"{bold}fleet{reset} "
            f"stats_frames={fleet.get('stats_frames', 0)} "
            f"incidents_forwarded={fleet.get('incidents_forwarded', 0)} "
            f"span_events={fleet.get('span_events', 0)}  "
            f"e2e p50={_fmt_s(job_w.get('p50_s'))} "
            f"p95={_fmt_s(job_w.get('p95_s'))}"
        )
        lines.append(
            f"  {'worker':<16} {'snap_age':>8} {'stats':>5} "
            f"{'incid':>5} {'spans':>6} {'disp_p95':>9}"
        )
        now = payload.get("unix_time") or time.time()
        for wkr in workers:
            at = wkr.get("stats_at")
            snap_age = f"{max(0.0, now - at):.1f}s" if at else "-"
            lines.append(
                f"  {str(wkr.get('worker', '?'))[:16]:<16} "
                f"{snap_age:>8} "
                f"{wkr.get('stats_frames', 0):>5} "
                f"{wkr.get('incidents', 0):>5} "
                f"{wkr.get('span_events', 0):>6} "
                f"{_fmt_s(wkr.get('dispatch_p95_s')):>9}"
            )

    slo = payload.get("slo", {})
    lines.append(f"{bold}rolling SLO{reset} (k={slo.get('k')}, "
                 f"slow_searches={slo.get('slow_searches', 0)})")
    for window in ("dispatch", "job"):
        w = slo.get(window, {})
        lines.append(
            f"  {window:>8}: n={w.get('count', 0):<5} "
            f"p50={_fmt_s(w.get('p50_s'))} p95={_fmt_s(w.get('p95_s'))} "
            f"p99={_fmt_s(w.get('p99_s'))} ewma={_fmt_s(w.get('ewma_s'))}"
        )

    metrics = payload.get("metrics")
    if metrics:
        rows = _backend_latency_rows(metrics)
        if rows:
            lines.append(f"{bold}dispatch latency by series{reset}")
            for label, mean, count in rows[:8]:
                lines.append(
                    f"  {label[:52]:<52} mean={_fmt_s(mean)} n={count}"
                )

    incidents = payload.get("incidents", [])
    lines.append(f"{bold}recent incidents{reset} ({len(incidents)})")
    for inc in incidents[-5:]:
        when = time.strftime(
            "%H:%M:%S", time.localtime(inc.get("unix_time", 0))
        )
        lines.append(
            f"  [{when}] {inc.get('reason')} "
            f"trace={inc.get('trace_id') or '-'} "
            f"{dim}{inc.get('path') or '(in-memory)'}{reset}"
        )
    if not incidents:
        lines.append(f"  {dim}none{reset}")
    return "\n".join(lines)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "stats_file", nargs="?",
        default=os.environ.get("WAFFLE_STATS_FILE", ""),  # waffle-lint: disable=WL001(stdlib-only viewer: must not import the package, i.e. jax, just to read a path)
        help="stats JSON written by the service (WAFFLE_STATS_FILE)",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--once", action="store_true",
        help="render one frame (no screen clear) and exit",
    )
    parser.add_argument(
        "--plain", action="store_true", help="no ANSI styling"
    )
    args = parser.parse_args()
    if not args.stats_file:
        parser.error("no stats file (argument or WAFFLE_STATS_FILE)")

    while True:
        payload = _load(args.stats_file)
        if payload is None:
            frame = (
                f"waffle_top: waiting for {args.stats_file} "
                "(is a service running with WAFFLE_STATS_FILE set?)"
            )
        else:
            frame = render(payload, plain=args.plain or args.once)
        if args.once:
            print(frame)
            return 0 if payload is not None else 1
        sys.stdout.write(CLEAR + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
