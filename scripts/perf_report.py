#!/usr/bin/env python
"""Render the perfdb trend and gate the latest run against it.

The perf database (``waffle_con_tpu/obs/perfdb.py``) is an append-only
JSONL of schema-versioned records written by ``bench.py`` and
``scripts/ci.sh``.  This script is its read side:

* default: a per-(kind, metric) trend table of the recent history —
  count, min/median/max, latest value, and delta vs the rolling
  baseline (median of the prior ``--window`` records);

* ``--check``: the CI regression gate.  The LATEST record of
  ``--kind`` (default ``microbench``) must be within ``--tolerance``
  (default 5%) of the rolling baseline computed over the records
  BEFORE it, and above the absolute ``--floor`` backstop
  (``WAFFLE_MICROBENCH_FLOOR``, default 900 — the same constant
  ``scripts/ci.sh`` passes to ``--assert-steps-floor``).  Exit 1 on
  breach.  With no prior history the baseline check is vacuous (first
  run seeds the database) but the floor still applies.

Values are throughput-style (higher is better) for every current
record kind; the gate compares one-sided accordingly.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from waffle_con_tpu.obs import perfdb  # noqa: E402  (path bootstrap above)


def _fmt(v):
    return f"{v:.1f}" if isinstance(v, (int, float)) else str(v)


def render_trend(records, limit):
    by_series = {}
    for rec in records:
        key = (rec.get("kind", "?"), rec.get("metric", "?"))
        by_series.setdefault(key, []).append(rec)
    if not by_series:
        print("perfdb is empty (run bench.py or scripts/ci.sh to seed it)")
        return
    print(f"{'kind':12s} {'metric':34s} {'n':>4s} {'min':>9s} "
          f"{'median':>9s} {'max':>9s} {'latest':>9s} {'vs base':>8s}")
    for (kind, metric), recs in sorted(by_series.items()):
        values = [r["value"] for r in recs
                  if isinstance(r.get("value"), (int, float))]
        if not values:
            continue
        latest = values[-1]
        base = perfdb.rolling_baseline(recs[:-1])
        vs = f"{100 * (latest / base - 1):+6.1f}%" if base else "     --"
        tail = values[-limit:]
        srt = sorted(tail)
        med = srt[len(srt) // 2]
        print(f"{kind:12s} {metric[:34]:34s} {len(values):4d} "
              f"{_fmt(min(tail)):>9s} {_fmt(med):>9s} {_fmt(max(tail)):>9s} "
              f"{_fmt(latest):>9s} {vs:>8s}")


def check(records, args):
    recs = [r for r in records
            if isinstance(r.get("value"), (int, float))
            and (args.metric is None or r.get("metric") == args.metric)]
    if not recs:
        print(f"perfdb check: no {args.kind!r} records in "
              f"{args.db} — nothing to gate (first run seeds the db)")
        return 0
    latest = recs[-1]
    value = float(latest["value"])
    # judge against same-platform history only: a cpu run gated
    # against device steps/s (or vice versa) is always wrong
    prior = [r for r in recs[:-1]
             if r.get("platform") == latest.get("platform")]
    base = perfdb.rolling_baseline(prior, window=args.window)
    unit = latest.get("unit", "")
    where = (f"{latest.get('kind')}/{latest.get('metric')} on "
             f"{latest.get('platform', '?')}")
    ok = True
    if value < args.floor:
        print(f"perfdb check FAIL: {where} latest {value} {unit} < "
              f"absolute floor {args.floor}")
        ok = False
    if base is not None:
        allowed = base * (1.0 - args.tolerance)
        verdict = "ok" if value >= allowed else "FAIL"
        print(f"perfdb check {verdict}: {where} latest {_fmt(value)} "
              f"{unit} vs rolling baseline {_fmt(base)} "
              f"(window {min(args.window, len(prior))}, "
              f"tolerance {100 * args.tolerance:.0f}% -> "
              f"allowed >= {_fmt(allowed)})")
        ok = ok and value >= allowed
    else:
        print(f"perfdb check ok: {where} latest {_fmt(value)} {unit}, "
              f"no prior history (floor {args.floor} passed)")
    return 0 if ok else 1


def main():
    parser = argparse.ArgumentParser(
        description="perfdb trend report + CI regression gate"
    )
    parser.add_argument("--db", default=None,
                        help="perfdb path (default: WAFFLE_PERFDB or "
                        "evidence/perfdb.jsonl)")
    parser.add_argument("--kind", default=None,
                        help="filter to one record kind "
                        "(--check defaults to 'microbench')")
    parser.add_argument("--metric", default=None,
                        help="filter to one metric name")
    parser.add_argument("--limit", type=int, default=20,
                        help="trend stats window per series (default 20)")
    parser.add_argument("--check", action="store_true",
                        help="gate the latest record vs the rolling "
                        "baseline; exit 1 on breach")
    parser.add_argument("--window", type=int, default=10,
                        help="rolling-baseline window (default 10)")
    parser.add_argument("--tolerance", type=float, default=0.05,
                        help="allowed fractional drop vs the rolling "
                        "baseline (default 0.05)")
    parser.add_argument(
        "--floor", type=float,
        default=float(os.environ.get("WAFFLE_MICROBENCH_FLOOR", "900")),
        help="absolute backstop floor (default: WAFFLE_MICROBENCH_FLOOR "
        "or 900, matching ci.sh's --assert-steps-floor)",
    )
    args = parser.parse_args()
    if args.check and args.kind is None:
        args.kind = "microbench"

    records = perfdb.load_records(args.db, kind=args.kind)
    if args.metric is not None and not args.check:
        records = [r for r in records if r.get("metric") == args.metric]
    if args.check:
        sys.exit(check(records, args))
    render_trend(records, args.limit)


if __name__ == "__main__":
    main()
