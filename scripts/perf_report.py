#!/usr/bin/env python
"""Render the perfdb trend and gate the latest run against it.

The perf database (``waffle_con_tpu/obs/perfdb.py``) is an append-only
JSONL of schema-versioned records written by ``bench.py`` and
``scripts/ci.sh``.  This script is its read side:

* default: a per-(kind, metric) trend table of the recent history —
  count, min/median/max, latest value, and delta vs the rolling
  baseline (median of the prior ``--window`` records);

* ``--check``: the CI regression gate.  The LATEST record of
  ``--kind`` (default ``microbench``) must be within ``--tolerance``
  (default 5%) of the rolling baseline computed over the records
  BEFORE it, and above the absolute ``--floor`` backstop
  (``WAFFLE_MICROBENCH_FLOOR``, default 900 — the same constant
  ``scripts/ci.sh`` passes to ``--assert-steps-floor``).  Exit 1 on
  breach.  With no prior history the baseline check is vacuous (first
  run seeds the database) but the floor still applies.

* ``--check --kinds a,b,c``: gate several kinds in one run (ci.sh
  gates ``microbench,serve-mix,storm`` this way).  Every kind uses the
  same tolerance against its own same-platform, same-metric rolling
  baseline; the absolute ``--floor`` backstop applies to the
  ``microbench`` kind only (serving jobs/s have no equivalent
  constant — their floors live in the ci.sh smoke asserts).

Baselines are platform- AND metric-scoped: a cpu run never gates
against device history, and a ``--storm 16`` record never becomes the
baseline for the ci ``--storm 8`` geometry.

Values are throughput-style (higher is better) for every current
record kind; the gate compares one-sided accordingly.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from waffle_con_tpu.obs import perfdb  # noqa: E402  (path bootstrap above)
from waffle_con_tpu.utils import envspec  # noqa: E402


def _fmt(v):
    return f"{v:.1f}" if isinstance(v, (int, float)) else str(v)


def render_trend(records, limit):
    by_series = {}
    for rec in records:
        key = (rec.get("kind", "?"), rec.get("metric", "?"))
        by_series.setdefault(key, []).append(rec)
    if not by_series:
        print("perfdb is empty (run bench.py or scripts/ci.sh to seed it)")
        return
    print(f"{'kind':12s} {'metric':34s} {'n':>4s} {'min':>9s} "
          f"{'median':>9s} {'max':>9s} {'latest':>9s} {'vs base':>8s}")
    for (kind, metric), recs in sorted(by_series.items()):
        values = [r["value"] for r in recs
                  if isinstance(r.get("value"), (int, float))]
        if not values:
            continue
        latest = values[-1]
        base = perfdb.rolling_baseline(recs[:-1])
        vs = f"{100 * (latest / base - 1):+6.1f}%" if base else "     --"
        tail = values[-limit:]
        srt = sorted(tail)
        med = srt[len(srt) // 2]
        print(f"{kind:12s} {metric[:34]:34s} {len(values):4d} "
              f"{_fmt(min(tail)):>9s} {_fmt(med):>9s} {_fmt(max(tail)):>9s} "
              f"{_fmt(latest):>9s} {vs:>8s}")


#: minimum same-platform, same-metric prior records before the rolling
#: baseline gates: a 1–2 record "baseline" is one noisy run judging
#: another, so below this the kind is reported (not gated) with an
#: explicit ``no-baseline (n=<k>)`` line
MIN_BASELINE_N = 3


def check(records, args, kind=None, floor=None):
    kind = kind if kind is not None else args.kind
    floor = floor if floor is not None else args.floor
    recs = [r for r in records
            if isinstance(r.get("value"), (int, float))
            and (args.metric is None or r.get("metric") == args.metric)]
    if not recs:
        print(f"perfdb check: {kind!r} no-baseline (n=0) — no records "
              f"in {args.db or perfdb.default_path()}, nothing to gate "
              f"(first run seeds the db)")
        return 0
    latest = recs[-1]
    value = float(latest["value"])
    # judge against same-platform, same-metric history only: a cpu run
    # gated against device steps/s — or a --storm 8 run gated against
    # --storm 16 throughput — is always wrong
    prior = [r for r in recs[:-1]
             if r.get("platform") == latest.get("platform")
             and r.get("metric") == latest.get("metric")]
    base = (perfdb.rolling_baseline(prior, window=args.window)
            if len(prior) >= MIN_BASELINE_N else None)
    unit = latest.get("unit", "")
    where = (f"{latest.get('kind')}/{latest.get('metric')} on "
             f"{latest.get('platform', '?')}")
    ok = True
    if value < floor:
        print(f"perfdb check FAIL: {where} latest {value} {unit} < "
              f"absolute floor {floor}")
        ok = False
    if base is not None:
        allowed = base * (1.0 - args.tolerance)
        verdict = "ok" if value >= allowed else "FAIL"
        print(f"perfdb check {verdict}: {where} latest {_fmt(value)} "
              f"{unit} vs rolling baseline {_fmt(base)} "
              f"(window {min(args.window, len(prior))}, "
              f"tolerance {100 * args.tolerance:.0f}% -> "
              f"allowed >= {_fmt(allowed)})")
        ok = ok and value >= allowed
    else:
        print(f"perfdb check ok: {where} latest {_fmt(value)} {unit}, "
              f"no-baseline (n={len(prior)}) — rolling gate needs >= "
              f"{MIN_BASELINE_N} same-platform records"
              f" (floor {floor} passed)")
    return 0 if ok else 1


def main():
    parser = argparse.ArgumentParser(
        description="perfdb trend report + CI regression gate"
    )
    parser.add_argument("--db", default=None,
                        help="perfdb path (default: WAFFLE_PERFDB or "
                        "evidence/perfdb.jsonl)")
    parser.add_argument("--kind", default=None,
                        help="filter to one record kind "
                        "(--check defaults to 'microbench')")
    parser.add_argument("--kinds", default=None,
                        help="with --check: comma-separated kinds to "
                        "gate in one run; the absolute --floor backstop "
                        "applies to 'microbench' only")
    parser.add_argument("--metric", default=None,
                        help="filter to one metric name")
    parser.add_argument("--limit", type=int, default=20,
                        help="trend stats window per series (default 20)")
    parser.add_argument("--check", action="store_true",
                        help="gate the latest record vs the rolling "
                        "baseline; exit 1 on breach")
    parser.add_argument("--window", type=int, default=10,
                        help="rolling-baseline window (default 10)")
    parser.add_argument("--tolerance", type=float, default=0.05,
                        help="allowed fractional drop vs the rolling "
                        "baseline (default 0.05)")
    parser.add_argument(
        "--floor", type=float,
        default=float(envspec.get_raw("WAFFLE_MICROBENCH_FLOOR", "900")),
        help="absolute backstop floor (default: WAFFLE_MICROBENCH_FLOOR "
        "or 900, matching ci.sh's --assert-steps-floor)",
    )
    args = parser.parse_args()
    if args.check:
        if args.kinds:
            kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
        else:
            kinds = [args.kind or "microbench"]
        rc = 0
        for kind in kinds:
            records = perfdb.load_records(args.db, kind=kind)
            floor = args.floor if kind == "microbench" else 0.0
            rc = max(rc, check(records, args, kind=kind, floor=floor))
        sys.exit(rc)

    records = perfdb.load_records(args.db, kind=args.kind)
    if args.metric is not None:
        records = [r for r in records if r.get("metric") == args.metric]
    render_trend(records, args.limit)


if __name__ == "__main__":
    main()
