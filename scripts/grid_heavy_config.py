#!/usr/bin/env python
"""One-off: measure the tie-heaviest criterion-grid config
(consensus_4x10000x8_0.02 — never completed in any round's budget) on
the jax-CPU backend with a multi-hour cap, appending the line to
evidence/GRID_r05_jaxcpu.jsonl."""
import json
import subprocess
import sys
import time

CHILD = '''
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")
from waffle_con_tpu.utils.cache import enable_compilation_cache
enable_compilation_cache()
import bench
out = bench.bench_single(8, 10000, 0.02)
out["metric"] = "consensus_4x10000x8_0.02"
out["device_platform"] = "cpu"
print("GRIDLINE " + json.dumps(out))
'''


def main():
    t0 = time.time()
    p = subprocess.run(
        [sys.executable, "-c", CHILD], capture_output=True, text=True,
        timeout=28000,
    )
    for ln in (p.stdout or "").splitlines():
        if ln.startswith("GRIDLINE "):
            d = json.loads(ln[9:])
            d["runner_wall_s"] = round(time.time() - t0, 1)
            with open(
                "/root/repo/evidence/GRID_r05_jaxcpu.jsonl", "a"
            ) as f:
                f.write(json.dumps(d) + "\n")
            print("captured", d["metric"], d.get("value"), flush=True)
            return
    print("no line; rc", p.returncode, (p.stderr or "")[-300:], flush=True)


if __name__ == "__main__":
    main()
