"""Configuration for the consensus DWFA engines.

Capability-parity with the reference config module
(``/root/reference/src/cdwfa_config.rs:18-102``): same knobs, same
defaults, plus a ``backend`` selector for the scorer implementation
(``python`` oracle, ``native`` C++, or ``jax`` TPU) which the reference
does not have (it is the whole point of this framework).

Typical usage::

    from waffle_con_tpu import CdwfaConfigBuilder, ConsensusCost

    config = (
        CdwfaConfigBuilder()
        .consensus_cost(ConsensusCost.L2_DISTANCE)
        .wildcard(ord("N"))
        .build()
    )
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class ConsensusCost(enum.Enum):
    """Scoring model for a consensus (reference ``ConsensusCost``,
    ``/root/reference/src/cdwfa_config.rs:18-24``)."""

    #: Minimize the total edit distance across all sequences.
    L1_DISTANCE = "l1"
    #: Minimize the sum of squared edit distances across all sequences.
    L2_DISTANCE = "l2"

    def apply(self, edit_distance: int) -> int:
        """Map a raw integer edit distance into this cost space."""
        if self is ConsensusCost.L1_DISTANCE:
            return edit_distance
        return edit_distance * edit_distance


@dataclasses.dataclass(frozen=True)
class CdwfaConfig:
    """Shared configuration for every consensus engine.

    Field semantics and defaults mirror the reference
    (``/root/reference/src/cdwfa_config.rs:40-102``).
    """

    #: The consensus scoring cost.
    consensus_cost: ConsensusCost = ConsensusCost.L1_DISTANCE
    #: Maximum queue size: how many active branches are allowed during
    #: exploration (counted at or above the rising length threshold).
    max_queue_size: int = 20
    #: Maximum number of nodes *processed* at each consensus length.
    max_capacity_per_size: int = 20
    #: Maximum number of equally-good results tracked.
    max_return_size: int = 10
    #: Maximum explored nodes without constraining the queue threshold;
    #: prevents hyper-branching in truly ambiguous regions.
    max_nodes_wo_constraint: int = 1000
    #: Minimum occurrences of a candidate extension to be used (the
    #: largest-observed candidate is always eligible regardless).
    min_count: int = 3
    #: Minimum fraction of sequences voting for a candidate extension.
    min_af: float = 0.0
    #: For dual consensus: weight nominated extensions by relative edit
    #: distance, accelerating convergence.
    weighted_by_ed: bool = False
    #: Optional wildcard symbol (byte value) that matches anything.
    wildcard: Optional[int] = None
    #: Dual-mode pruning threshold: when a read's two tracked wavefronts
    #: diverge in edit distance by more than this, drop the worse one.
    dual_max_ed_delta: int = 20
    #: If true, input sequences shorter than the final consensus are not
    #: penalized for the unmatched consensus tail.
    allow_early_termination: bool = False
    #: If true, shift all provided offsets down when none start at zero.
    auto_shift_offsets: bool = True
    #: Number of bases before the last offset searched for the optimal
    #: start point of a late-activating sequence.
    offset_window: int = 50
    #: Number of bases compared when scoring candidate start points.
    offset_compare_length: int = 50
    #: Scorer backend: "python" (pure-Python oracle), "native" (C++),
    #: or "jax" (batched TPU scorer).  Framework extension beyond the
    #: reference config.
    backend: str = "python"
    #: Shard the jax scorer's read axis over this many devices (a
    #: ``jax.sharding.Mesh`` over the first N devices; 0 = single-device).
    #: Engines are sharding-agnostic: results are identical on 1 or N
    #: chips.  Framework extension beyond the reference config.
    mesh_shards: int = 0
    #: Seed the jax scorer's band half-width (``e_max``) from the caller's
    #: error model instead of growing it from a small default: a value of
    #: ``margin + 2 * error_rate * max_read_len`` makes band-growth
    #: replays (and their per-width kernel recompiles) vanish for
    #: workloads whose noise level is known, e.g. HiFi reads.  ``None``
    #: keeps the grow-on-demand default.  Rounded up to a power of two.
    #: Framework extension beyond the reference config.
    initial_band: Optional[int] = None
    #: Speculatively expand up to this many queue nodes per scorer
    #: dispatch (frontier-synchronous batching): the children of the
    #: popped node and of the next best queued nodes are cloned and
    #: pushed in one fused device call, and consumed (bit-identically)
    #: when those nodes are actually popped.  1 disables speculation.
    #: Framework extension beyond the reference config.
    prefetch_width: int = 16
    #: Frontier-parallel speculation width M: alongside each popped
    #: node's device run, gang the next best M-1 queued branches
    #: through the ragged kernel and hold their advanced states as
    #: consume-once deposits (byte-identical to M=1 at every M).
    #: ``None`` (default) picks M adaptively from queue depth, cost gap
    #: and the rolling gang-commit rate; 1 disables; the
    #: ``WAFFLE_FRONTIER_M`` env var overrides either.  Framework
    #: extension beyond the reference config.
    frontier_width: Optional[int] = None
    #: Route every scorer dispatch through the fault-tolerant
    #: :class:`~waffle_con_tpu.runtime.supervisor.BackendSupervisor`
    #: (timeout, retry/backoff, mid-search backend demotion).  Implied
    #: by setting ``backend_chain``.  Framework extension.
    supervised: bool = False
    #: Explicit fallback chain for the supervisor, e.g. ``("jax",
    #: "python")``.  ``None`` derives the health-ordered suffix from
    #: ``backend`` (jax -> native -> python).  Framework extension.
    backend_chain: Optional[tuple] = None
    #: Wall-clock budget per blocking dispatch before the supervisor
    #: declares it hung (seconds; ``None`` disables the timer — injected
    #: fault timeouts still work).  Framework extension.
    dispatch_timeout_s: Optional[float] = None
    #: Retries per dispatch on the current backend before the
    #: supervisor demotes.  Framework extension.
    dispatch_retries: int = 2
    #: Base delay of the exponential retry backoff (seconds).
    retry_backoff_s: float = 0.05
    #: Uniform-random jitter fraction added to each backoff delay.
    retry_jitter: float = 0.25
    #: Circuit breaker: consecutive dispatch failures (across ops)
    #: before the supervisor demotes the live search.
    breaker_threshold: int = 3
    #: After this many clean dispatches on a demoted backend, probe the
    #: next-better backend for re-promotion (doubling on each failed
    #: probe).  ``None`` disables re-promotion.  Framework extension.
    repromote_after: Optional[int] = None
    #: Engagement watchdog: pinned blocking-dispatch budget for one
    #: ``consensus()`` search (summed over ``DISPATCH_COUNTER_KEYS``);
    #: ``None`` disables the check.  Framework extension.
    dispatch_budget: Optional[int] = None
    #: Watchdog strict mode: raise ``WatchdogError`` instead of warning
    #: when the dispatch budget is exceeded.  Framework extension.
    watchdog_strict: bool = False
    #: Log each search's one-line summary (the ``SearchReport``
    #: ``summary_line``) at INFO instead of DEBUG.  Framework extension.
    log_search_summary: bool = False

    def __post_init__(self) -> None:
        if self.wildcard is not None and not 0 <= self.wildcard <= 255:
            raise ValueError("wildcard must be a byte value (0..=255)")
        if self.backend not in ("python", "native", "jax"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.mesh_shards and self.backend != "jax":
            raise ValueError("mesh_shards requires the jax backend")
        if self.prefetch_width < 1:
            raise ValueError("prefetch_width must be >= 1")
        if self.frontier_width is not None and self.frontier_width < 1:
            raise ValueError("frontier_width must be >= 1")
        if self.initial_band is not None and self.initial_band < 1:
            raise ValueError("initial_band must be >= 1")
        if self.backend_chain is not None:
            chain = tuple(self.backend_chain)
            if not chain:
                raise ValueError("backend_chain must not be empty")
            for b in chain:
                if b not in ("python", "native", "jax"):
                    raise ValueError(f"unknown backend {b!r} in chain")
            if len(set(chain)) != len(chain):
                raise ValueError("backend_chain entries must be unique")
            object.__setattr__(self, "backend_chain", chain)
        if self.dispatch_timeout_s is not None and self.dispatch_timeout_s <= 0:
            raise ValueError("dispatch_timeout_s must be positive")
        if self.dispatch_retries < 0:
            raise ValueError("dispatch_retries must be >= 0")
        if self.retry_backoff_s < 0 or self.retry_jitter < 0:
            raise ValueError("retry backoff and jitter must be >= 0")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if self.repromote_after is not None and self.repromote_after < 1:
            raise ValueError("repromote_after must be >= 1")
        if self.dispatch_budget is not None and self.dispatch_budget < 1:
            raise ValueError("dispatch_budget must be >= 1")


class CdwfaConfigBuilder:
    """Fluent builder for :class:`CdwfaConfig` (parity with the
    reference's ``derive_builder`` API, ``CdwfaConfigBuilder``)."""

    def __init__(self) -> None:
        self._values: dict = {}

    def build(self) -> CdwfaConfig:
        return CdwfaConfig(**self._values)

    def __getattr__(self, name: str):
        if name.startswith("_") or name not in CdwfaConfig.__dataclass_fields__:
            raise AttributeError(name)

        def setter(value):
            self._values[name] = value
            return self

        return setter
