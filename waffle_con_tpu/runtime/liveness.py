"""Worker-process liveness: heartbeat tracking and the typed loss error.

The out-of-process front door
(:mod:`waffle_con_tpu.serve.procs.door`) cannot observe a worker's
threads the way the in-process replica set can — all it sees is the
socket.  :class:`Heartbeats` is the door-side ledger: every frame a
worker sends (results, pongs, forwarded flight triggers) counts as a
beat, and :meth:`Heartbeats.lapsed` surfaces the workers whose last
beat is older than ``WAFFLE_PROC_LIVENESS_S`` so the watchdog can
declare them lost even when the OS keeps the dead peer's socket open
(e.g. a worker wedged in a device call, not crashed).

:class:`WorkerLost` is the typed error a job fails with when its
worker dies and the door can neither migrate it (no ``CHECKPOINT``
frame arrived yet, or ``WAFFLE_CKPT_MIGRATE=0``) nor restart it
(``ProcConfig.restart_lost=False``) — callers can distinguish "your
worker crashed" from an engine failure.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from waffle_con_tpu.analysis import lockcheck


class WorkerLost(RuntimeError):
    """The worker process running (or queued to run) a job died or
    went silent past the liveness lapse before finishing it."""


class Heartbeats:
    """Monotonic last-seen ledger keyed by worker name.

    Thread-safe: the door's reader threads :meth:`beat` concurrently
    with the watchdog thread calling :meth:`lapsed`.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None) -> None:
        self._clock = clock or time.monotonic
        self._lock = lockcheck.make_lock("runtime.liveness.Heartbeats")
        self._seen: Dict[str, float] = {}

    def beat(self, name: str) -> None:
        """Record activity from ``name`` now."""
        with self._lock:
            self._seen[name] = self._clock()

    def forget(self, name: str) -> None:
        """Stop tracking ``name`` (worker deliberately shut down)."""
        with self._lock:
            self._seen.pop(name, None)

    def age(self, name: str) -> Optional[float]:
        """Seconds since ``name``'s last beat (``None`` if never seen)."""
        with self._lock:
            seen = self._seen.get(name)
        return None if seen is None else self._clock() - seen

    def lapsed(self, older_than_s: float) -> List[str]:
        """Names whose last beat is more than ``older_than_s`` ago."""
        cutoff = self._clock() - older_than_s
        with self._lock:
            return [n for n, t in self._seen.items() if t < cutoff]
