"""Process-wide runtime event log.

A single append-only list shared by the supervisor (demotions,
promotions, dispatch failures), the compile cache (quarantined
entries), the pallas guard (kernel disables), and the watchdog (budget
violations).  Tests assert on it, and ``bench.py`` records it in the
evidence JSON so a degraded run is visibly degraded.

Events are plain dicts with a ``kind`` key; everything else is
kind-specific detail.  The log is intentionally unbounded-ish but
capped defensively: a pathological retry loop must not turn the event
log itself into the memory leak.

Thread-safety contract: every append (:func:`record`), drain
(:func:`clear_events`), and read (:func:`get_events`,
:func:`summarize_events`) holds ``_LOCK`` — the serve layer records
from many worker threads plus the batching-dispatcher thread into this
one list, and readers get point-in-time copies, never live aliases.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from waffle_con_tpu.analysis import lockcheck

_LOCK = lockcheck.make_lock("runtime.events.LOG")
_EVENTS: List[Dict] = []
#: hard cap; beyond it new events replace a marker rather than growing
_MAX_EVENTS = 10_000


def record(kind: str, **details) -> Dict:
    """Append an event and return it.

    Past the cap, events are counted rather than stored: the trailing
    ``event_log_saturated`` marker's ``dropped`` field says exactly how
    many events were discarded (previously they vanished silently).
    The event log is also one sink of the obs pipeline — every recorded
    event bumps ``waffle_runtime_events_total{kind=...}`` when metrics
    are on, so dropped events still show up in the registry totals.
    """
    event = {"kind": kind, **details}
    dropped = False
    with _LOCK:
        if len(_EVENTS) < _MAX_EVENTS:
            _EVENTS.append(event)
        elif _EVENTS[-1].get("kind") == "event_log_saturated":
            _EVENTS[-1]["dropped"] += 1
            dropped = True
        else:
            _EVENTS.append({"kind": "event_log_saturated", "dropped": 1})
            dropped = True
    # lazy import: obs must stay import-light and cycle-free from here
    from waffle_con_tpu.obs import metrics as obs_metrics

    if obs_metrics.metrics_enabled():
        obs_metrics.registry().counter(
            "waffle_runtime_events_total", kind=kind
        ).inc()
        if dropped:
            # event loss is visible in the exposition, not only in the
            # trailing saturation marker record
            obs_metrics.registry().counter(
                "waffle_runtime_events_dropped_total"
            ).inc()
    return event


def get_events(kind: Optional[str] = None) -> List[Dict]:
    """Snapshot of recorded events (optionally filtered by kind)."""
    with _LOCK:
        return [
            dict(e) for e in _EVENTS if kind is None or e["kind"] == kind
        ]


def summarize_events() -> Dict[str, int]:
    """``{kind: count}`` — the compact form bench.py embeds per line."""
    with _LOCK:
        out: Dict[str, int] = {}
        for e in _EVENTS:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out


def clear_events() -> None:
    with _LOCK:
        del _EVENTS[:]
