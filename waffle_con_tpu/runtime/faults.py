"""Deterministic fault injection for the backend runtime.

A :class:`FaultPlan` is a list of :class:`FaultSpec` rules; each rule
fires when a supervised dispatch matches its ``(kind, backend, op,
at)`` filter, at most ``count`` times.  Dispatch indices are the
supervisor's monotonically increasing attempt counter, so a plan is
exactly reproducible: the same workload sees the same faults at the
same dispatches on every run.

Plans come from two places:

* programmatically (tests): ``faults.install(FaultPlan()).add(...)``
  — see the ``faults`` fixture in ``conftest.py``;
* the environment (whole-process injection, e.g. under ``bench.py`` or
  a child of ``scripts/run_suite.py``)::

      WAFFLE_FAULTS="timeout:jax:*:5:1,device_loss:jax:run:12"

  Comma-separated ``kind[:backend[:op[:at[:count]]]]`` rules with ``*``
  wildcards; ``at`` empty/``*`` means "every matching dispatch",
  ``count`` empty/``*`` means unlimited.

Fault kinds:

* ``timeout`` — the supervisor raises
  :class:`~waffle_con_tpu.runtime.supervisor.DispatchTimeout` before
  touching the backend (state provably unmutated, so retry is safe).
* ``device_loss`` — :class:`InjectedDeviceLoss` before the backend
  call, modelling a vanished device / dead tunnel.
* ``garbage`` — the dispatch runs, then every ``BranchStats`` in the
  result is corrupted to NaN; the supervisor's validation must catch
  it and recover from the pre-call ledger state.
* ``pallas_compile`` — ``JaxScorer._pallas_guarded`` raises as if
  Mosaic lowering failed, exercising the per-kernel XLA fallback.
* ``cache_corrupt`` — ``enable_compilation_cache`` flips bytes in one
  persistent cache entry before integrity verification runs,
  modelling on-disk corruption from a crashed writer.
* ``flip_vote`` — the single-engine pop loop (via
  :func:`maybe_flip_vote`) silently replaces the sole passing symbol
  with a different alphabet symbol before committing it: a wrong
  *decision*, invisible to the supervisor's validation, that only the
  audit plane (``obs/audit.py`` lockstep shadow / differ) can catch.
  The poll index is the node's consensus length, so a length-pinned
  rule replays deterministically through checkpoint resume.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import List, Optional

import numpy as np

from waffle_con_tpu.runtime import events
from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec

FAULT_KINDS = (
    "timeout", "device_loss", "garbage", "pallas_compile", "cache_corrupt",
    "flip_vote",
)


class InjectedFault(Exception):
    """Base class for exceptions raised by injected faults."""


class InjectedTimeout(InjectedFault):
    """Injected dispatch timeout (raised before the backend runs)."""


class InjectedDeviceLoss(InjectedFault):
    """Injected device-loss / dead-tunnel failure."""


@dataclasses.dataclass
class FaultSpec:
    """One injection rule.  ``backend``/``op`` filter with ``"*"`` as
    the wildcard; ``at`` pins a single dispatch index (``None`` = every
    matching dispatch); ``count`` bounds total firings (``None`` =
    unlimited)."""

    kind: str
    backend: str = "*"
    op: str = "*"
    at: Optional[int] = None
    count: Optional[int] = 1
    fired: int = 0

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (known: {FAULT_KINDS})"
            )

    def _exhausted(self) -> bool:
        return self.count is not None and self.fired >= self.count

    def matches(self, backend: str, op: str, index: Optional[int]) -> bool:
        if self._exhausted():
            return False
        if self.backend != "*" and self.backend != backend:
            return False
        if self.op != "*" and self.op != op:
            return False
        if self.at is not None and index != self.at:
            return False
        return True


class FaultPlan:
    """An ordered set of fault rules consulted by the runtime hooks.

    ``poll`` is serialized by a plan-level lock: the serve layer runs
    many supervised jobs on worker threads against one process-wide
    plan, and a ``count``-bounded rule must fire exactly ``count`` times
    total, not ``count`` times per racing thread."""

    def __init__(self, specs: Optional[List[FaultSpec]] = None) -> None:
        self.specs: List[FaultSpec] = list(specs or [])
        self._lock = lockcheck.make_lock("runtime.faults.FaultPlan")

    def add(
        self,
        kind: str,
        backend: str = "*",
        op: str = "*",
        at: Optional[int] = None,
        count: Optional[int] = 1,
    ) -> "FaultPlan":
        self.specs.append(FaultSpec(kind, backend, op, at, count))
        return self

    def poll(
        self, backend: str, op: str, index: Optional[int],
        kinds: Optional[tuple] = None,
    ) -> Optional[FaultSpec]:
        """First matching rule (its firing consumed), or ``None``."""
        with self._lock:
            fired = None
            for spec in self.specs:
                if kinds is not None and spec.kind not in kinds:
                    continue
                if spec.matches(backend, op, index):
                    spec.fired += 1
                    fired = spec
                    break
        if fired is not None:
            events.record(
                "fault_injected", fault=fired.kind, backend=backend,
                op=op, index=index,
            )
        return fired


#: the installed plan; ``None`` means "not yet resolved from the env"
_ACTIVE: Optional[FaultPlan] = None
_ENV_CHECKED = False


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or with ``None``: clear) the process-wide fault plan."""
    global _ACTIVE, _ENV_CHECKED
    _ACTIVE = plan
    _ENV_CHECKED = True  # an explicit install overrides the env
    return plan


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    """The installed plan, lazily resolving ``WAFFLE_FAULTS`` once."""
    global _ACTIVE, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = envspec.get_raw("WAFFLE_FAULTS", "")
        if spec:
            _ACTIVE = plan_from_env(spec)
    return _ACTIVE


def plan_from_env(spec: str) -> FaultPlan:
    """Parse a ``WAFFLE_FAULTS`` rule string (see module docstring)."""
    plan = FaultPlan()
    for rule in spec.split(","):
        rule = rule.strip()
        if not rule:
            continue
        parts = rule.split(":")
        kind = parts[0]
        backend = parts[1] if len(parts) > 1 and parts[1] else "*"
        op = parts[2] if len(parts) > 2 and parts[2] else "*"

        def _int(i: int) -> Optional[int]:
            if len(parts) <= i or parts[i] in ("", "*"):
                return None
            return int(parts[i])

        plan.add(kind, backend, op, at=_int(3), count=_int(4))
    return plan


def poll(backend: str, op: str, index: int) -> Optional[FaultSpec]:
    """Supervisor-side hook: dispatch-targeted fault kinds only."""
    plan = active()
    if plan is None:
        return None
    return plan.poll(
        backend, op, index, kinds=("timeout", "device_loss", "garbage")
    )


def check_pallas(sides: int) -> None:
    """``_pallas_guarded`` hook: raise (inside its try block) when a
    ``pallas_compile`` fault is armed for this kernel."""
    plan = active()
    if plan is None:
        return
    if plan.poll("jax", f"pallas{sides}", None, kinds=("pallas_compile",)):
        raise InjectedFault(
            f"injected pallas compile failure (sides={sides})"
        )


def maybe_corrupt_cache(path: str) -> Optional[str]:
    """``enable_compilation_cache`` hook: when a ``cache_corrupt`` fault
    is armed, flip bytes in the middle of the first cache entry (sorted
    order — deterministic), returning the corrupted filename."""
    plan = active()
    if plan is None:
        return None
    if not plan.poll("cache", "enable", None, kinds=("cache_corrupt",)):
        return None
    try:
        names = sorted(
            n for n in os.listdir(path)
            if os.path.isfile(os.path.join(path, n))
            and not n.startswith(("MANIFEST", "_"))
        )
    except OSError:
        return None
    if not names:
        return None
    target = os.path.join(path, names[0])
    with open(target, "r+b") as f:
        data = f.read()
        mid = len(data) // 2
        f.seek(mid)
        f.write(bytes(b ^ 0xFF for b in data[mid : mid + 16]) or b"\xff")
    events.record("cache_corruption_injected", entry=names[0])
    return names[0]


def maybe_flip_vote(backend: str, length: int) -> bool:
    """Single-engine pop-loop hook: ``True`` when a ``flip_vote`` fault
    is armed for this backend at this consensus length (the poll
    ``index`` is the popped node's consensus length, so a length-pinned
    rule re-fires deterministically on a checkpoint-resume replay).  The
    engine only polls at pops where a flip can commit (exactly one
    passing symbol), so a ``count=1`` rule lands on the first such pop —
    the seeded-divergence drill in ``scripts/waffle_diverge.py`` relies
    on both properties."""
    plan = active()
    if plan is None:
        return False
    return plan.poll(backend, "vote", length, kinds=("flip_vote",)) is not None


def mangle_stats(result):
    """Corrupt every ``BranchStats`` reachable in a dispatch result
    (NaN distances, negative votes) — the ``garbage`` fault payload."""
    from waffle_con_tpu.ops.scorer import BranchStats

    def walk(obj):
        if isinstance(obj, BranchStats):
            obj.eds = np.full(np.shape(obj.eds), np.nan)
            obj.split = np.full(np.shape(obj.split), -1, dtype=np.int64)
            return obj
        if isinstance(obj, list):
            return [walk(x) for x in obj]
        if isinstance(obj, tuple):
            return tuple(walk(x) for x in obj)
        return obj

    return walk(result)
