"""Fault-tolerant backend runtime.

The north star is a serving-grade TPU consensus path, and serving-grade
means the device layer is allowed to misbehave: tunnel flaps, hung
dispatches, garbage tensors from a sick accelerator, a corrupted
persistent compile cache.  This package makes those first-class inputs
instead of crashes:

* :mod:`~waffle_con_tpu.runtime.supervisor` —
  :class:`~waffle_con_tpu.runtime.supervisor.BackendSupervisor`, a
  ``WavefrontScorer`` that wraps every blocking dispatch of a real
  backend with timeout + bounded retry + a circuit breaker, and demotes
  a live search down a health-ordered backend chain (pallas/TPU →
  jax-CPU → C++ native → Python oracle) mid-search with byte-identical
  results.
* :mod:`~waffle_con_tpu.runtime.faults` — deterministic fault injection
  (env or programmatic): dispatch timeouts, device-loss exceptions,
  NaN/garbage score tensors, pallas compile failures, compile-cache
  corruption.
* :mod:`~waffle_con_tpu.runtime.watchdog` — per-engine dispatch-budget
  accounting over the scorer counters, turning silent fast-path
  engagement regressions into loud warnings (or failures in strict
  mode).
* :mod:`~waffle_con_tpu.runtime.liveness` — heartbeat ledger and the
  typed :class:`~waffle_con_tpu.runtime.liveness.WorkerLost` error for
  the out-of-process front door's worker watchdog.
* :mod:`~waffle_con_tpu.runtime.events` — the process-wide runtime
  event log every component above records into; ``bench.py`` ships it
  in the evidence JSON.
"""

from waffle_con_tpu.runtime.events import (  # noqa: F401
    clear_events,
    get_events,
    record,
)
from waffle_con_tpu.runtime.liveness import (  # noqa: F401
    Heartbeats,
    WorkerLost,
)
from waffle_con_tpu.runtime.faults import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    InjectedDeviceLoss,
    InjectedFault,
    InjectedTimeout,
)
from waffle_con_tpu.runtime.supervisor import (  # noqa: F401
    BackendFailure,
    BackendSupervisor,
    DispatchTimeout,
    GarbageStats,
    effective_chain,
)
from waffle_con_tpu.runtime.watchdog import (  # noqa: F401
    WatchdogError,
    dispatch_total,
    enforce_dispatch_budget,
)
