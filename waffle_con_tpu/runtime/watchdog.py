"""Engagement watchdog: dispatch-budget accounting.

The device fast paths (``run_extend``, arenas, fused clone+push) are
what make the TPU path fast — and a silent regression to per-symbol
dispatching passes every parity test while destroying performance
(round-5 VERDICT weak #5).  Wall time on tunneled platforms is
``blocking_dispatches x ~80 ms``, so the blocking-dispatch count IS
the performance contract.  This module turns it into an enforced one:
engines call :func:`enforce_dispatch_budget` at the end of every
``consensus()`` with their scorer-counter totals; a workload that
exceeds its pinned ``config.dispatch_budget`` warns by default and
raises in strict mode (``config.watchdog_strict`` or
``WAFFLE_WATCHDOG=strict``).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from waffle_con_tpu.ops.scorer import DISPATCH_COUNTER_KEYS
from waffle_con_tpu.runtime import events
from waffle_con_tpu.utils import envspec

logger = logging.getLogger(__name__)


class WatchdogError(RuntimeError):
    """Strict-mode budget violation."""


class DeadlineExceeded(WatchdogError):
    """A per-job wall-clock deadline expired (serve layer).

    Subclasses :class:`WatchdogError` so callers that already treat
    watchdog violations as "the runtime stopped this search on purpose"
    handle deadlines the same way.
    """


def enforce_deadline(
    deadline_monotonic: Optional[float], label: str = ""
) -> None:
    """Raise :class:`DeadlineExceeded` when ``time.monotonic()`` is past
    ``deadline_monotonic`` (``None`` = no deadline; a no-op).

    The serve layer calls this at job admission-queue pop and before
    every routed scorer dispatch, so a job whose deadline lapses stops
    at the next dispatch boundary rather than running to completion.
    Records a ``deadline_exceeded`` runtime event on the way out.
    """
    if deadline_monotonic is None:
        return
    now = time.monotonic()
    if now >= deadline_monotonic:
        overrun = now - deadline_monotonic
        events.record(
            "deadline_exceeded", label=label, overrun_s=round(overrun, 6)
        )
        from waffle_con_tpu.obs import flight, trace

        flight.trigger(
            "deadline_exceeded", trace_id=trace.current_trace_id(),
            label=label, overrun_s=round(overrun, 6),
        )
        raise DeadlineExceeded(
            f"deadline exceeded{f' ({label})' if label else ''}: "
            f"{overrun * 1000:.1f} ms past the per-job budget"
        )


def dispatch_total(counters: Dict[str, int]) -> int:
    """Blocking-dispatch count: the sum of the counter keys that each
    correspond to one blocking device dispatch (``ops/scorer.py``)."""
    return sum(int(counters.get(k, 0)) for k in DISPATCH_COUNTER_KEYS)


def enforce_dispatch_budget(
    config, counters: Dict[str, int], engine: str
) -> Optional[int]:
    """Check one search's dispatch count against its pinned budget.

    Returns the total (``None`` when no budget is configured).  Over
    budget: records a ``watchdog_budget_exceeded`` event and warns, or
    raises :class:`WatchdogError` in strict mode.
    """
    budget = getattr(config, "dispatch_budget", None)
    if budget is None:
        return None
    total = dispatch_total(counters)
    if total > budget:
        events.record(
            "watchdog_budget_exceeded", engine=engine, total=total,
            budget=budget,
        )
        from waffle_con_tpu.obs import flight, trace

        flight.trigger(
            "watchdog_budget_exceeded",
            trace_id=trace.current_trace_id(),
            engine=engine, total=total, budget=budget,
        )
        message = (
            f"{engine} consensus used {total} blocking dispatches, over "
            f"its pinned budget of {budget} — a device fast path likely "
            "disengaged (see counter breakdown in last_search_stats)"
        )
        strict = bool(getattr(config, "watchdog_strict", False)) or (
            envspec.get_raw("WAFFLE_WATCHDOG") == "strict"
        )
        if strict:
            raise WatchdogError(message)
        logger.warning("%s", message)
    return total
