"""Supervised backend dispatch with retry, demotion, and re-promotion.

:class:`BackendSupervisor` is a :class:`~waffle_con_tpu.ops.scorer.WavefrontScorer`
that owns a real backend scorer and wraps every blocking dispatch with:

* a configurable wall-clock timeout (``config.dispatch_timeout_s``);
* bounded retry with exponential backoff + jitter
  (``dispatch_retries`` / ``retry_backoff_s`` / ``retry_jitter``);
* result validation (NaN / negative score tensors raise
  :class:`GarbageStats` instead of silently poisoning the search);
* a circuit breaker: after ``breaker_threshold`` consecutive failures
  the live search is demoted to the next backend in a health-ordered
  chain (``effective_chain``: pallas/TPU jax → C++ native → Python
  oracle), and — after ``repromote_after`` clean dispatches — probed
  back up.

Demotion mid-search is correct because branch state is a pure
deterministic function of ``(read, consensus, offset, active)`` on
every backend (the repo's cross-backend parity contract).  The
supervisor therefore keeps a per-handle **ledger** of exactly that
tuple, updated only after a dispatch commits, and can rebuild any
branch on any backend: root the offset-0 actives, replay the consensus
symbol-by-symbol, then activate the offset reads (activation replays
from its offset, so late activation is state-identical).  A retry of a
possibly-partially-applied dispatch restores the involved handles from
the ledger first; a demotion rebuilds the whole ledger on the fallback
backend and the search continues byte-identically.

The capability surface (``run_extend`` / ``run_extend_dual`` /
``run_arena`` / ``clone_push_many`` / ``ARENA_*``) is frozen at
construction: engines feature-test these per pop with ``getattr``, and
a mid-pop demotion must not yank a method the engine already tested.
On a backend lacking a frozen capability the wrapper reports a
zero-step stop (run/arena paths — the engines fall through to the
per-op expand path) or emulates via clone+push (``clone_push_many``),
both of which are result-identical by construction.
"""

from __future__ import annotations

import logging
import random
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FuturesTimeout
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.ops.scorer import BranchStats, WavefrontScorer
from waffle_con_tpu.runtime import events, faults

logger = logging.getLogger(__name__)


def _metric_inc(name: str, **labels) -> None:
    """Bump a supervisor counter when the metrics pipeline is on."""
    if obs_metrics.metrics_enabled():
        obs_metrics.registry().counter(name, **labels).inc()

#: fallback order when ``config.backend_chain`` is not set: most
#: capable first, the Python executable-specification oracle last
_HEALTH_ORDER = ("jax", "native", "python")

#: optional fast paths engines feature-test per pop (see models/*)
_FAST_PATHS = (
    "run_extend", "run_extend_dual", "run_arena", "clone_push_many",
    "run_mega",
)


class DispatchTimeout(RuntimeError):
    """A blocking dispatch exceeded ``config.dispatch_timeout_s``."""


class GarbageStats(RuntimeError):
    """A dispatch returned non-finite or negative score tensors."""


class BackendFailure(RuntimeError):
    """Every backend in the chain failed; the search cannot continue."""


def effective_chain(config: CdwfaConfig) -> Tuple[str, ...]:
    """The health-ordered backend chain for a config: the explicit
    ``backend_chain`` (deduped, forced to start at ``config.backend``),
    else the ``_HEALTH_ORDER`` suffix from ``config.backend`` down."""
    explicit = getattr(config, "backend_chain", None)
    if explicit:
        chain = [config.backend]
        for b in explicit:
            if b not in chain:
                chain.append(b)
        return tuple(chain)
    return _HEALTH_ORDER[_HEALTH_ORDER.index(config.backend):]


class _HandleState:
    """Ledger entry: the portable state of one branch handle."""

    __slots__ = ("backend_h", "consensus", "active", "offsets")

    def __init__(self, backend_h, consensus, active, offsets):
        self.backend_h = backend_h
        self.consensus = bytes(consensus)
        self.active = list(active)
        self.offsets = list(offsets)

    def copy_state(self):
        return bytes(self.consensus), list(self.active), list(self.offsets)


class BackendSupervisor(WavefrontScorer):
    """A fault-tolerant ``WavefrontScorer`` over a backend chain."""

    def __init__(self, reads: Sequence[bytes], config: CdwfaConfig) -> None:
        super().__init__(reads, config)
        self.counters: Dict[str, int] = {}
        self.chain = effective_chain(config)
        self._ledger: Dict[int, _HandleState] = {}
        self._next_handle = 0
        self._dispatch_index = 0
        self._consecutive_failures = 0
        self._successes_since_demotion = 0
        self._probe_interval = config.repromote_after
        self._executor: Optional[ThreadPoolExecutor] = None
        #: demotion/promotion generation: bumped on every backend swap so
        #: engine-side ``fast_paths()`` snapshots over this scorer (or a
        #: proxy view of it) re-resolve instead of going stale
        self.fastpath_gen = 0

        self._pos = None
        last_exc: Optional[Exception] = None
        for i, backend in enumerate(self.chain):
            try:
                scorer = self._new_backend(backend)
            except Exception as exc:  # noqa: BLE001 - any constructor failure
                events.record(
                    "backend_unavailable", backend=backend, error=repr(exc)
                )
                logger.warning("backend %s unavailable: %r", backend, exc)
                last_exc = exc
                continue
            self._pos = i
            self._scorer = scorer
            self._adopt_counters(scorer)
            break
        if self._pos is None:
            raise BackendFailure(
                f"no backend in chain {self.chain} could be constructed"
            ) from last_exc
        #: frozen capability surface (see module docstring)
        self._capabilities = {
            name: getattr(self._scorer, name, None) is not None
            for name in _FAST_PATHS
        }
        events.record(
            "supervisor_started", chain=list(self.chain), backend=self.backend
        )

    # ------------------------------------------------------------------
    # backend lifecycle

    @property
    def backend(self) -> str:
        """Name of the backend currently serving dispatches."""
        return self.chain[self._pos]

    def _new_backend(self, backend: str) -> WavefrontScorer:
        from waffle_con_tpu.ops.scorer import construct_backend

        return construct_backend(self.reads, self.config, backend)

    def _adopt_counters(self, scorer: WavefrontScorer) -> None:
        # accumulate across backends, then share one dict so both the
        # backend's increments and the engines' direct writes
        # (e.g. ``scorer.counters["arena_dual_steps"]``) land here
        for k, v in dict(getattr(scorer, "counters", {}) or {}).items():
            self.counters[k] = self.counters.get(k, 0) + int(v)
        scorer.counters = self.counters

    def _rebuild_handle(self, scorer: WavefrontScorer, st: _HandleState):
        """Reconstruct one branch on ``scorer`` from its ledger state."""
        mask = np.array(
            [bool(a) and off == 0 for a, off in zip(st.active, st.offsets)],
            dtype=bool,
        )
        h = scorer.root(mask)
        for i in range(len(st.consensus)):
            scorer.push(h, st.consensus[: i + 1])
        for r, (a, off) in enumerate(zip(st.active, st.offsets)):
            if a and off not in (0, None):
                scorer.activate(h, r, int(off), st.consensus)
        return h

    def _migrate(self, scorer: WavefrontScorer) -> None:
        """Rebuild every ledger handle on ``scorer`` (all-or-nothing:
        backend handles are only swapped in once every rebuild worked)."""
        rebuilt = {
            h: self._rebuild_handle(scorer, st)
            for h, st in self._ledger.items()
        }
        for h, bh in rebuilt.items():
            self._ledger[h].backend_h = bh

    def _demote(self, cause: Exception) -> None:
        """Move down the chain, migrating the live search; raises
        :class:`BackendFailure` when the chain is exhausted."""
        while True:
            next_pos = self._pos + 1
            if next_pos >= len(self.chain):
                raise BackendFailure(
                    f"backend chain {self.chain} exhausted"
                ) from cause
            target = self.chain[next_pos]
            try:
                scorer = self._new_backend(target)
                self._adopt_counters(scorer)
                self._migrate(scorer)
            except Exception as exc:  # noqa: BLE001 - skip a dead rung
                events.record(
                    "backend_unavailable", backend=target, error=repr(exc)
                )
                logger.warning(
                    "fallback backend %s unavailable: %r", target, exc
                )
                self._pos = next_pos
                continue
            old = self.backend
            self._release_ragged()
            self._pos = next_pos
            self._scorer = scorer
            self.fastpath_gen += 1
            self._consecutive_failures = 0
            self._successes_since_demotion = 0
            self._probe_interval = self.config.repromote_after
            events.record(
                "backend_demoted", from_backend=old, to_backend=target,
                handles=len(self._ledger), cause=repr(cause),
            )
            _metric_inc(
                "waffle_backend_demotions_total",
                from_backend=old, to_backend=target,
            )
            from waffle_con_tpu.obs import flight, trace

            flight.trigger(
                "backend_demoted", trace_id=trace.current_trace_id(),
                from_backend=old, to_backend=target,
                handles=len(self._ledger), cause=repr(cause),
            )
            logger.warning(
                "demoting backend %s -> %s (%d live handles migrated): %r",
                old, target, len(self._ledger), cause,
            )
            return

    def _release_ragged(self) -> None:
        """A backend swap (demotion or re-promotion) rebuilds the live
        search on a fresh backend, so the outgoing scorer's paged-arena
        residency — if it has any — must be released NOW: its pages
        would otherwise leak until job end and any pending ragged
        injections would go stale against the rebuilt state."""
        rel = getattr(self._scorer, "ragged_release", None)
        if rel is None:
            return
        try:
            rel()
        except Exception:  # noqa: BLE001 - release must never block a swap
            logger.warning(
                "ragged-arena release failed during backend swap",
                exc_info=True,
            )

    def ragged_run_probe(self, h: int):
        """Ragged-dispatch hop through the supervisor: translate the
        engine handle to the current backend's handle and delegate.
        Returns None whenever the live backend cannot take part — the
        dispatch then simply runs solo through the supervised path."""
        inner = getattr(self._scorer, "ragged_run_probe", None)
        if inner is None:
            return None
        try:
            bh = self._ledger[h].backend_h
        except KeyError:
            return None
        return inner(bh)

    def _note_success(self) -> None:
        self._consecutive_failures = 0
        if self._pos == 0 or self._probe_interval is None:
            return
        self._successes_since_demotion += 1
        if self._successes_since_demotion >= self._probe_interval:
            self._successes_since_demotion = 0
            self._probe()

    def _probe(self) -> None:
        """Try to re-promote one chain level: construct the better
        backend, prove it live with a trivial dispatch, then migrate."""
        target_pos = self._pos - 1
        target = self.chain[target_pos]
        try:
            plan = faults.active()
            if plan is not None and plan.poll(
                target, "probe", None,
                kinds=("timeout", "device_loss", "garbage"),
            ):
                raise faults.InjectedFault("injected probe failure")
            scorer = self._new_backend(target)
            ph = scorer.root(np.zeros(self.num_reads, dtype=bool))
            self._validate(scorer.stats(ph, b""))
            scorer.free(ph)
            self._adopt_counters(scorer)
            self._migrate(scorer)
        except Exception as exc:  # noqa: BLE001 - probe failure is benign
            events.record("probe_failed", backend=target, error=repr(exc))
            logger.info("re-promotion probe of %s failed: %r", target, exc)
            # back off exponentially so a flapping device isn't probed
            # (and the search re-migrated) on a tight loop
            self._probe_interval *= 2
            return
        old = self.backend
        self._release_ragged()
        self._pos = target_pos
        self._scorer = scorer
        self.fastpath_gen += 1
        self._probe_interval = self.config.repromote_after
        events.record(
            "backend_promoted", from_backend=old, to_backend=target,
            handles=len(self._ledger),
        )
        _metric_inc(
            "waffle_backend_promotions_total",
            from_backend=old, to_backend=target,
        )
        logger.warning(
            "re-promoted backend %s -> %s (%d live handles migrated)",
            old, target, len(self._ledger),
        )

    # ------------------------------------------------------------------
    # the supervised dispatch loop

    def _call_with_timeout(self, call):
        timeout = self.config.dispatch_timeout_s
        if not timeout:
            return call()
        if self._executor is None:
            self._executor = ThreadPoolExecutor(max_workers=1)
        future = self._executor.submit(call)
        try:
            return future.result(timeout=timeout)
        except _FuturesTimeout:
            # the worker may still be wedged inside the backend; abandon
            # the executor so the next dispatch gets a fresh thread
            future.cancel()
            self._executor.shutdown(wait=False)
            self._executor = None
            raise DispatchTimeout(
                f"dispatch exceeded {timeout}s on backend {self.backend}"
            ) from None

    @staticmethod
    def _validate(result) -> None:
        bad = _find_invalid(result)
        if bad is not None:
            raise GarbageStats(f"backend returned garbage scores: {bad}")

    def _sleep_backoff(self, attempt: int) -> None:
        base = self.config.retry_backoff_s
        if base <= 0:
            return
        delay = base * (2 ** (attempt - 1))
        delay *= 1.0 + self.config.retry_jitter * random.random()
        time.sleep(delay)

    def _supervised(
        self, op: str, involved: List[int], call,
        mutating: bool = True, validate: bool = True,
    ):
        """Run ``call`` under the full policy: fault hooks, timeout,
        validation, retry with restore, circuit breaker, demotion.

        ``call`` must resolve backend handles via the ledger *at call
        time* (``self._bh``) so a re-execution after restore/demotion
        targets the rebuilt handles on the current backend.
        """
        attempts = 0
        while True:
            idx = self._dispatch_index
            self._dispatch_index += 1
            attempts += 1
            started = False
            try:
                spec = faults.poll(self.backend, op, idx)
                if spec is not None and spec.kind == "timeout":
                    raise faults.InjectedTimeout(
                        f"injected timeout at dispatch {idx} ({op})"
                    )
                if spec is not None and spec.kind == "device_loss":
                    raise faults.InjectedDeviceLoss(
                        f"injected device loss at dispatch {idx} ({op})"
                    )
                started = True
                result = self._call_with_timeout(call)
                if spec is not None and spec.kind == "garbage":
                    result = faults.mangle_stats(result)
                if validate:
                    self._validate(result)
            except Exception as exc:  # noqa: BLE001 - policy boundary
                self._consecutive_failures += 1
                events.record(
                    "dispatch_failed", backend=self.backend, op=op,
                    index=idx, attempt=attempts, error=repr(exc),
                )
                _metric_inc(
                    "waffle_dispatch_failures_total",
                    backend=self.backend, op=op,
                )
                logger.warning(
                    "dispatch %s failed on %s (attempt %d): %r",
                    op, self.backend, attempts, exc,
                )
                exhausted = attempts > self.config.dispatch_retries
                tripped = (
                    self._consecutive_failures
                    >= self.config.breaker_threshold
                )
                if exhausted or tripped:
                    self._demote(exc)
                    attempts = 0
                    continue
                _metric_inc(
                    "waffle_dispatch_retries_total",
                    backend=self.backend, op=op,
                )
                self._sleep_backoff(attempts)
                if mutating and started:
                    # the failed call may have half-applied; rebuild the
                    # involved branches from the ledger before retrying
                    try:
                        self._restore(involved)
                    except Exception as restore_exc:  # noqa: BLE001
                        self._demote(restore_exc)
                        attempts = 0
                continue
            self._note_success()
            return result

    def _restore(self, involved: List[int]) -> None:
        for h in involved:
            st = self._ledger.get(h)
            if st is None:
                continue
            try:
                self._scorer.free(st.backend_h)
            except Exception:  # noqa: BLE001 - stale slot on a sick device
                pass
            st.backend_h = self._rebuild_handle(self._scorer, st)
        events.record(
            "handles_restored", backend=self.backend, handles=len(involved)
        )

    # ------------------------------------------------------------------
    # ledger plumbing

    def _register(self, backend_h, consensus, active, offsets) -> int:
        h = self._next_handle
        self._next_handle += 1
        self._ledger[h] = _HandleState(backend_h, consensus, active, offsets)
        return h

    def _bh(self, h: int):
        return self._ledger[h].backend_h

    def _prune_active(self, st: _HandleState, act) -> None:
        for r in range(len(st.active)):
            if st.active[r] and not bool(act[r]):
                st.active[r] = False
                st.offsets[r] = None

    # ------------------------------------------------------------------
    # WavefrontScorer surface (core ops)

    def root(self, active: np.ndarray) -> int:
        mask = np.asarray(active, dtype=bool).copy()
        bh = self._supervised(
            "root", [], lambda: self._scorer.root(mask),
            mutating=False, validate=False,
        )
        return self._register(
            bh, b"",
            [bool(a) for a in mask],
            [0 if a else None for a in mask],
        )

    def clone(self, h: int) -> int:
        bh = self._supervised(
            "clone", [h], lambda: self._scorer.clone(self._bh(h)),
            mutating=False, validate=False,
        )
        st = self._ledger[h]
        return self._register(bh, *st.copy_state())

    def clone_many(self, hs: List[int]) -> List[int]:
        bhs = self._supervised(
            "clone", list(hs),
            lambda: self._scorer.clone_many([self._bh(x) for x in hs]),
            mutating=False, validate=False,
        )
        return [
            self._register(bh, *self._ledger[x].copy_state())
            for bh, x in zip(bhs, hs)
        ]

    def free(self, h: int) -> None:
        st = self._ledger.pop(h, None)
        if st is None:
            return
        try:
            self._scorer.free(st.backend_h)
        except Exception as exc:  # noqa: BLE001 - never fail a free
            logger.debug("backend free failed (ignored): %r", exc)

    def push(self, h: int, consensus: bytes) -> BranchStats:
        stats = self._supervised(
            "push", [h], lambda: self._scorer.push(self._bh(h), consensus)
        )
        self._ledger[h].consensus = bytes(consensus)
        return stats

    def push_many(
        self, specs: List[Tuple[int, bytes]]
    ) -> List[BranchStats]:
        out = self._supervised(
            "push",
            [h for h, _ in specs],
            lambda: self._scorer.push_many(
                [(self._bh(h), c) for h, c in specs]
            ),
        )
        for h, c in specs:
            self._ledger[h].consensus = bytes(c)
        return out

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        return self._supervised(
            "stats", [h],
            lambda: self._scorer.stats(self._bh(h), consensus),
            mutating=False,
        )

    def activate(
        self, h: int, read_index: int, offset: int, consensus: bytes
    ) -> None:
        self._supervised(
            "activate", [h],
            lambda: self._scorer.activate(
                self._bh(h), read_index, offset, consensus
            ),
            validate=False,
        )
        st = self._ledger[h]
        st.active[read_index] = True
        st.offsets[read_index] = int(offset)

    def deactivate(self, h: int, read_index: int) -> None:
        self._supervised(
            "activate", [h],
            lambda: self._scorer.deactivate(self._bh(h), read_index),
            validate=False,
        )
        st = self._ledger[h]
        st.active[read_index] = False
        st.offsets[read_index] = None

    def deactivate_many(self, pairs: List[Tuple[int, int]]) -> None:
        self._supervised(
            "activate", [h for h, _ in pairs],
            lambda: self._scorer.deactivate_many(
                [(self._bh(h), r) for h, r in pairs]
            ),
            validate=False,
        )
        for h, r in pairs:
            st = self._ledger[h]
            st.active[r] = False
            st.offsets[r] = None

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        return self._supervised(
            "finalize", [h],
            lambda: self._scorer.finalized_eds(self._bh(h), consensus),
            mutating=False,
        )

    def best_activation_offset(
        self, consensus, seq_index, offset_window, offset_compare_length,
        wildcard,
    ) -> int:
        return self._supervised(
            "activation_offset", [],
            lambda: self._scorer.best_activation_offset(
                consensus, seq_index, offset_window, offset_compare_length,
                wildcard,
            ),
            mutating=False, validate=False,
        )

    # ------------------------------------------------------------------
    # optional fast paths (frozen capability surface, see docstring)

    @property
    def run_extend(self):
        return self._run_extend if self._capabilities["run_extend"] else None

    @property
    def run_extend_dual(self):
        if not self._capabilities["run_extend_dual"]:
            return None
        return self._run_extend_dual

    @property
    def run_arena(self):
        return self._run_arena if self._capabilities["run_arena"] else None

    @property
    def run_mega(self):
        return self._run_mega if self._capabilities["run_mega"] else None

    @property
    def clone_push_many(self):
        if not self._capabilities["clone_push_many"]:
            return None
        return self._clone_push_many

    @property
    def ARENA_CAP(self):
        return getattr(self._scorer, "ARENA_CAP", 0)

    @property
    def ARENA_K(self):
        return getattr(self._scorer, "ARENA_K", 1)

    @property
    def ARENA_CRE_PER_EVENT(self):
        return getattr(self._scorer, "ARENA_CRE_PER_EVENT", 0)

    @property
    def ARENA_TAKE_MAX(self):
        return getattr(self._scorer, "ARENA_TAKE_MAX", 0)

    def _run_extend(self, h, consensus, *args, **kwargs):
        def call():
            fn = getattr(self._scorer, "run_extend", None)
            if fn is None:
                # demoted to a backend without the kernel: report a
                # zero-step stop; the engine adopts the (identical)
                # snapshot and falls through to the expand path
                return (
                    0, 0, b"",
                    self._scorer.stats(self._bh(h), consensus), [],
                )
            return fn(self._bh(h), consensus, *args, **kwargs)

        result = self._supervised("run", [h], call)
        steps = result[0]
        if steps > 0:
            self._ledger[h].consensus = bytes(consensus) + result[2]
        return result

    def _run_mega(self, h, consensus, *args, **kwargs):
        attempts = {"n": 0}

        def call():
            # a FAILED megastep retries as plain stepping: the retry
            # (attempt > 1) or a demotion to a backend without the mega
            # kernel falls back to run_extend — identical results, the
            # supervisor just loses the round-trip bundling for this
            # dispatch — and a backend with neither kernel reports a
            # zero-step stop exactly like _run_extend's fallback
            attempts["n"] += 1
            fn = getattr(self._scorer, "run_mega", None)
            if fn is None or attempts["n"] > 1:
                fn = getattr(self._scorer, "run_extend", None)
            if fn is None:
                return (
                    0, 0, b"",
                    self._scorer.stats(self._bh(h), consensus), [],
                )
            return fn(self._bh(h), consensus, *args, **kwargs)

        result = self._supervised("run", [h], call)
        steps = result[0]
        if steps > 0:
            self._ledger[h].consensus = bytes(consensus) + result[2]
        return result

    def _run_extend_dual(self, h1, h2, consensus1, consensus2,
                         *args, **kwargs):
        def call():
            fn = getattr(self._scorer, "run_extend_dual", None)
            if fn is None:
                st1, st2 = self._ledger[h1], self._ledger[h2]
                return (
                    0, 0, b"", b"",
                    self._scorer.stats(self._bh(h1), consensus1),
                    self._scorer.stats(self._bh(h2), consensus2),
                    np.asarray(st1.active, dtype=bool),
                    np.asarray(st2.active, dtype=bool),
                    [],
                )
            return fn(
                self._bh(h1), self._bh(h2), consensus1, consensus2,
                *args, **kwargs,
            )

        result = self._supervised("run", [h1, h2], call)
        steps, _code, app1, app2 = result[:4]
        act1, act2 = result[6], result[7]
        if steps > 0:
            st1, st2 = self._ledger[h1], self._ledger[h2]
            st1.consensus = bytes(consensus1) + app1
            st2.consensus = bytes(consensus2) + app2
            self._prune_active(st1, act1)
            self._prune_active(st2, act2)
        return result

    def _run_arena(self, node_specs, *args, **kwargs):
        create_mode = kwargs.get("create_mode", 0)

        def call():
            fn = getattr(self._scorer, "run_arena", None)
            if fn is None:
                # zero-step refusal: the engines' nsteps == 0 path
                # restores their queue state and falls back
                n = len(node_specs)
                return ([], 0, 0, -1, [0] * n, [], [], [], [True] * n, [])
            mapped = [
                (
                    self._bh(h1),
                    self._bh(h2) if h2 is not None else None,
                    l1, l2,
                )
                for h1, h2, l1, l2 in node_specs
            ]
            return fn(mapped, *args, **kwargs)

        involved = [h for h1, h2, _, _ in node_specs
                    for h in (h1, h2) if h is not None]
        result = self._supervised("arena", involved, call)
        (_events, nsteps, _code, _stop, node_steps, appended,
         _sides_stats, sides_act, _alive, creations) = result
        if nsteps == 0:
            return result

        # mirror the engines' commit exactly (models/consensus.py and
        # models/dual_consensus.py arena post-processing): extensions to
        # the original nodes first, then children in creation order —
        # a child's parent (possibly itself a child) is always built
        entries = [(h1, h2) for h1, h2, _, _ in node_specs]
        for i, (h1, h2) in enumerate(entries):
            if node_steps[i] == 0:
                continue
            st1 = self._ledger[h1]
            st1.consensus = st1.consensus + appended[2 * i]
            if create_mode == 2:
                self._prune_active(st1, sides_act[2 * i])
            if h2 is not None:
                st2 = self._ledger[h2]
                st2.consensus = st2.consensus + appended[2 * i + 1]
                if create_mode == 2:
                    self._prune_active(st2, sides_act[2 * i + 1])

        n_live = len(node_specs)
        for j, cre in enumerate(creations):
            idx = n_live + j
            ph1, ph2 = entries[cre["parent"]]
            p1 = self._ledger[ph1]
            cut = cre["created_len"] - 1
            cons1 = p1.consensus[:cut] + bytes([cre["sym1"]]) + appended[2 * idx]
            if create_mode == 1:
                active1 = list(p1.active)
                offsets1 = list(p1.offsets)
            else:
                a1 = sides_act[2 * idx]
                active1 = [bool(a) for a in a1[: len(p1.active)]]
                offsets1 = [
                    p1.offsets[r] if active1[r] else None
                    for r in range(len(p1.active))
                ]
            ch1 = self._register(cre["h1"], cons1, active1, offsets1)
            cre["h1"] = ch1
            ch2 = None
            if cre["kind"] == 1 and cre.get("h2") is not None:
                src = self._ledger[ph2] if ph2 is not None else p1
                cons2 = (
                    src.consensus[:cut] + bytes([cre["sym2"]])
                    + appended[2 * idx + 1]
                )
                a2 = sides_act[2 * idx + 1]
                active2 = [bool(a) for a in a2[: len(src.active)]]
                offsets2 = [
                    src.offsets[r] if active2[r] else None
                    for r in range(len(src.active))
                ]
                ch2 = self._register(cre["h2"], cons2, active2, offsets2)
                cre["h2"] = ch2
            entries.append((ch1, ch2))
        return result

    def _clone_push_many(self, specs):
        def call():
            fn = getattr(self._scorer, "clone_push_many", None)
            if fn is not None:
                return fn(
                    [(self._bh(h), c, ip) for h, c, ip in specs]
                )
            # emulate on a backend without the fused path; semantics
            # are identical (clone-only -> stats None, in_place reuses
            # the source slot)
            out = []
            for h, c, ip in specs:
                bh = self._bh(h)
                if c is None:
                    out.append((self._scorer.clone(bh), None))
                elif ip:
                    out.append((bh, self._scorer.push(bh, c)))
                else:
                    nh = self._scorer.clone(bh)
                    out.append((nh, self._scorer.push(nh, c)))
            return out

        res = self._supervised(
            "clone_push", [h for h, _, _ in specs], call
        )
        out = []
        for (bh, st_stats), (h, c, ip) in zip(res, specs):
            src = self._ledger[h]
            if ip:
                src.consensus = bytes(c)
                src.backend_h = bh
                out.append((h, st_stats))
            else:
                cons = src.consensus if c is None else bytes(c)
                nh = self._register(bh, cons, src.active, src.offsets)
                out.append((nh, st_stats))
        return out


def _find_invalid(obj) -> Optional[str]:
    """First non-finite / negative score tensor in a dispatch result."""
    if isinstance(obj, BranchStats):
        for name in ("eds", "split", "occ"):
            arr = np.asarray(getattr(obj, name))
            if arr.size and not np.all(np.isfinite(arr.astype(np.float64))):
                return f"non-finite {name}"
            if arr.size and np.any(arr.astype(np.float64) < 0):
                return f"negative {name}"
        if obj.fin is not None:
            arr = np.asarray(obj.fin)
            if arr.size and not np.all(np.isfinite(arr.astype(np.float64))):
                return "non-finite fin"
        return None
    if isinstance(obj, np.ndarray):
        if obj.size and obj.dtype.kind == "f" and not np.all(np.isfinite(obj)):
            return "non-finite array"
        return None
    if isinstance(obj, (list, tuple)):
        for x in obj:
            bad = _find_invalid(x)
            if bad is not None:
                return bad
    return None
