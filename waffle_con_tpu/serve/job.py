"""Job types for the multi-tenant consensus service.

A :class:`JobRequest` is one independent consensus problem (engine kind
+ reads + config + scheduling attributes); submitting it yields a
:class:`JobHandle`, the client's view of the job's lifecycle.  The
handle doubles as the runtime's *abort ticket*: the worker and the
batching dispatcher call :meth:`JobHandle.check_abort` at every dispatch
boundary, so cancellation and per-job deadlines take effect at the next
scorer dispatch rather than only between jobs.

Typed service errors:

* :class:`ServiceOverloaded` — bounded admission queue full; the submit
  is *rejected*, never blocked (backpressure contract).
* :class:`ServiceClosed` — submit after close, or a job orphaned by
  shutdown.
* :class:`JobCancelled` — the client called :meth:`JobHandle.cancel`.
* deadline lapses raise
  :class:`~waffle_con_tpu.runtime.watchdog.DeadlineExceeded` (the
  watchdog owns wall-clock enforcement) and finalize the job as
  :attr:`JobStatus.EXPIRED`.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from typing import Any, Optional, Sequence, Tuple

from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.obs.trace import JOB_PID_BASE, TraceContext
from waffle_con_tpu.runtime.watchdog import enforce_deadline
from waffle_con_tpu.analysis import lockcheck

JOB_KINDS = ("single", "dual", "priority")


class ServeError(RuntimeError):
    """Base class for service-layer errors."""


class ServiceOverloaded(ServeError):
    """Admission queue full: the job was rejected, not enqueued."""


class ServiceClosed(ServeError):
    """The service is shut down (or shutting down)."""


class JobCancelled(ServeError):
    """The job was cancelled via :meth:`JobHandle.cancel`."""


class JobStatus(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    CANCELLED = "cancelled"
    EXPIRED = "expired"
    #: Served from the consensus cache's exact-hit tier: the job never
    #: ran (``started_at`` stays ``None``) and no worker was touched.
    CACHED = "cached"
    #: Served from a cached near-miss consensus certified at the
    #: optimal cost by one exact scoring pass (propose-then-verify).
    CERTIFIED = "certified"


_TERMINAL = (
    JobStatus.DONE, JobStatus.FAILED, JobStatus.CANCELLED, JobStatus.EXPIRED,
    JobStatus.CACHED, JobStatus.CERTIFIED,
)


@dataclasses.dataclass(frozen=True)
class JobRequest:
    """One consensus job.

    ``reads`` is a sequence of byte strings for ``single``/``dual``
    kinds, or a sequence of chains (each a sequence of byte strings) for
    ``priority``.  ``offsets`` optionally gives per-read last-offset
    seeds (``single``/``dual`` only).  ``priority`` orders admission
    (higher first, FIFO within a class); ``deadline_s`` is a wall-clock
    budget measured from submit.
    """

    kind: str
    reads: Tuple
    config: Optional[CdwfaConfig] = None
    offsets: Optional[Tuple[Optional[int], ...]] = None
    priority: int = 0
    deadline_s: Optional[float] = None
    tag: Optional[str] = None

    def __post_init__(self) -> None:
        if self.kind not in JOB_KINDS:
            raise ValueError(
                f"unknown job kind {self.kind!r} (known: {JOB_KINDS})"
            )
        if not self.reads:
            raise ValueError("a job needs at least one read")
        if self.kind == "priority":
            frozen = tuple(tuple(bytes(s) for s in chain)
                           for chain in self.reads)
        else:
            frozen = tuple(bytes(r) for r in self.reads)
        object.__setattr__(self, "reads", frozen)
        if self.offsets is not None:
            if self.kind == "priority":
                raise ValueError("offsets are not supported for priority "
                                 "jobs (use seeded chains instead)")
            if len(self.offsets) != len(frozen):
                raise ValueError(
                    f"offsets length {len(self.offsets)} != reads length "
                    f"{len(frozen)}"
                )
            object.__setattr__(self, "offsets", tuple(self.offsets))
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be positive")


class JobHandle:
    """Client-side handle and runtime-side abort ticket for one job."""

    def __init__(
        self, job_id: int, request: JobRequest, service: Optional[str] = None
    ) -> None:
        self.job_id = job_id
        self.request = request
        label = f"job-{job_id}"
        if request.tag:
            label += f" [{request.tag}]"
        self.trace = TraceContext(
            trace_id=f"{service or 'serve'}/job-{job_id}",
            chrome_pid=JOB_PID_BASE + job_id,
            label=label,
        )
        self._lock = lockcheck.make_lock("serve.job.JobHandle")
        self._done = threading.Event()
        self._running = threading.Event()
        self._status = JobStatus.QUEUED
        self._cancel_requested = False
        self._result: Any = None
        self._exception: Optional[BaseException] = None
        self._report = None
        self._checkpoint: Any = None
        self._checkpoint_at: Optional[float] = None
        #: optional ``fn(wire_dict)`` invoked on every attached
        #: checkpoint (the out-of-process worker hangs its CHECKPOINT
        #: frame sender here); exceptions are swallowed — a broken
        #: sink must never fail the search that snapshotted
        self.on_checkpoint = None
        self.submitted_at = time.monotonic()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.deadline: Optional[float] = (
            self.submitted_at + request.deadline_s
            if request.deadline_s is not None else None
        )

    # -- client API ----------------------------------------------------

    @property
    def status(self) -> JobStatus:
        with self._lock:
            return self._status

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def wait_running(self, timeout: Optional[float] = None) -> bool:
        """Wait until a worker has picked the job up (or it finished —
        the running event also fires on any terminal transition so a
        waiter can never hang on an already-settled job)."""
        return self._running.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        """Block for the job's consensus result.

        Re-raises the job's failure (:class:`JobCancelled`,
        :class:`~waffle_con_tpu.runtime.watchdog.DeadlineExceeded`, or
        whatever the engine raised); raises :class:`TimeoutError` when
        the wait times out.
        """
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"job {self.job_id} not finished within {timeout}s"
            )
        with self._lock:
            if self._exception is not None:
                raise self._exception
            return self._result

    def cancel(self) -> bool:
        """Request cancellation.

        A queued job finalizes as CANCELLED immediately (the worker
        skips it at pop); a running job aborts at its next dispatch
        boundary.  Returns ``False`` when the job already reached a
        terminal state.
        """
        with self._lock:
            if self._status in _TERMINAL:
                return False
            self._cancel_requested = True
            if self._status is JobStatus.QUEUED:
                self._finalize_locked(
                    JobStatus.CANCELLED,
                    exception=JobCancelled(
                        f"job {self.job_id} cancelled while queued"
                    ),
                )
        return True

    @property
    def latency_s(self) -> Optional[float]:
        """Submit-to-finish wall clock (``None`` until terminal)."""
        with self._lock:
            if self.finished_at is None:
                return None
            return self.finished_at - self.submitted_at

    @property
    def search_report(self):
        """The engine's structured SearchReport (``None`` until DONE or
        when reporting was off for the job's config)."""
        with self._lock:
            return self._report

    @property
    def checkpoint(self):
        """Latest search checkpoint attached to this job (an opaque
        wire dict, see :mod:`waffle_con_tpu.models.checkpoint`), or
        ``None`` if the search never snapshotted.  An EXPIRED job keeps
        its final checkpoint so the caller can resume with a fresh
        deadline; the front door uses it to migrate a job off a lost
        worker instead of restarting from scratch."""
        with self._lock:
            return self._checkpoint

    @property
    def checkpoint_at(self) -> Optional[float]:
        """``time.monotonic()`` when :attr:`checkpoint` was attached
        (``None`` alongside it); the migration path uses it to account
        wasted work between the last snapshot and the crash."""
        with self._lock:
            return self._checkpoint_at

    def _drop_checkpoint(self) -> None:
        """Forget the attached checkpoint (restart-from-scratch paths:
        a stale resume point must not ride into the next dispatch)."""
        with self._lock:
            self._checkpoint = None
            self._checkpoint_at = None

    def _attach_checkpoint(self, data: Any) -> None:
        """Attach/replace the job's latest checkpoint (runtime side:
        the in-process service's snapshot hook, or the front door on a
        worker's CHECKPOINT frame)."""
        if data is None:
            return
        with self._lock:
            self._checkpoint = data
            self._checkpoint_at = time.monotonic()
            callback = self.on_checkpoint
        if callback is not None:
            try:
                callback(data)
            except Exception:  # noqa: BLE001 - sink must never fail a job
                pass

    # -- runtime (ticket) API ------------------------------------------

    def check_abort(self, op: str = "") -> None:
        """Raise when the job must stop: cancellation first, then the
        per-job deadline.  Called by the worker at pop and by the
        dispatcher before every routed scorer dispatch."""
        with self._lock:
            cancelled = self._cancel_requested
        if cancelled:
            raise JobCancelled(
                f"job {self.job_id} cancelled"
                + (f" (at dispatch {op})" if op else "")
            )
        enforce_deadline(self.deadline, label=f"job {self.job_id}")

    def _mark_running(self) -> bool:
        """Worker picked the job up.  Returns ``False`` when the job is
        already terminal (cancelled while queued) — the worker must skip
        it without touching an engine."""
        with self._lock:
            if self._status is not JobStatus.QUEUED:
                return False
            self._status = JobStatus.RUNNING
            self.started_at = time.monotonic()
        self._running.set()
        return True

    def _finish(
        self,
        status: JobStatus,
        result: Any = None,
        exception: Optional[BaseException] = None,
        report=None,
    ) -> None:
        with self._lock:
            if self._status in _TERMINAL:
                return
            self._result = result
            self._report = report
            self._finalize_locked(status, exception=exception)

    def _finalize_locked(
        self, status: JobStatus, exception: Optional[BaseException]
    ) -> None:
        self._status = status
        self._exception = exception
        self.finished_at = time.monotonic()
        self._running.set()
        self._done.set()

    def __repr__(self) -> str:
        return (
            f"JobHandle(id={self.job_id}, kind={self.request.kind!r}, "
            f"status={self.status.value})"
        )


def validate_requests(requests: Sequence[JobRequest]) -> None:
    """Fail fast on a batch submit with a non-JobRequest element."""
    for r in requests:
        if not isinstance(r, JobRequest):
            raise TypeError(f"expected JobRequest, got {type(r).__name__}")
