"""Multi-tenant consensus serving with cross-job dynamic batching.

The serving layer the ROADMAP's "heavy traffic" north star needs on top
of the single-search engine stack:

* :class:`~waffle_con_tpu.serve.service.ConsensusService` — accepts
  many independent jobs (single/dual/priority), bounded admission queue
  with reject-on-full backpressure, priority scheduling (FIFO within a
  class), per-job deadlines and cancellation enforced at every scorer
  dispatch boundary, graceful/shedding shutdown.
* :class:`~waffle_con_tpu.serve.dispatcher.BatchingDispatcher` — the
  cross-job coalescing point: concurrent jobs' blocking scorer
  dispatches are collected within a bounded batching window, grouped by
  compiled-shape bucket, and executed as one device-resident burst by a
  single dispatcher thread (direct fall-through when a job is alone).
  Results are byte-identical to serial execution by construction.
* :class:`~waffle_con_tpu.serve.dispatcher.CoalescingScorer` — the
  per-job transparent scorer proxy (same seam as ``obs.TimedScorer``
  and the runtime's ``BackendSupervisor``) that routes dispatches into
  the shared dispatcher.

Ragged cross-job batching: with ``WAFFLE_RAGGED`` on (the default), the
dispatcher additionally gangs eligible ``run_extend`` dispatches from
*different* shape buckets into single kernel calls over the paged
band-state arena (:mod:`waffle_con_tpu.ops.ragged`); pool exhaustion
raises the typed :class:`~waffle_con_tpu.ops.ragged.ArenaExhausted`
internally and degrades to the bucketed path.

Scale-out serving: :class:`~waffle_con_tpu.serve.placement.PlacementPolicy`
routes large admitted jobs through a mesh-sharded scorer (small jobs
keep the arena path), and
:class:`~waffle_con_tpu.serve.replicas.ReplicatedService` fronts N
in-process replicas — each with its own dispatcher, arena, worker pool
and device slice — with least-outstanding, health-aware routing
(``waffle_replica_*`` gauges; demoted replicas drain and re-admit).

Out-of-process serving: :class:`~waffle_con_tpu.serve.procs.door.
ProcFrontDoor` promotes that replica seam to real worker *processes*
(own GIL, own device slice) behind a typed length-prefixed socket
protocol (:mod:`waffle_con_tpu.serve.procs`) — same admission, aging,
placement, and drain/shed health semantics, plus a liveness watchdog
that requeues a crashed worker's jobs (``waffle_worker_*`` gauges).

Observability: ``waffle_serve_queue_depth``/``waffle_serve_active_jobs``
gauges, ``waffle_serve_jobs_total{outcome}`` /
``waffle_serve_admission_rejections_total`` /
``waffle_serve_direct_dispatches_total`` counters, and the
``waffle_serve_batch_occupancy`` / ``waffle_serve_job_latency_seconds``
histograms (all gated on ``WAFFLE_METRICS``); the arena adds
``waffle_compile_total`` / ``waffle_ragged_pool_pages_{used,free}`` /
``waffle_ragged_occupancy``.
"""

from waffle_con_tpu.ops.ragged import ArenaExhausted
from waffle_con_tpu.runtime.watchdog import DeadlineExceeded
from waffle_con_tpu.serve.dispatcher import (
    BatchingDispatcher,
    CoalescingScorer,
    bucket_key,
)
from waffle_con_tpu.serve.job import (
    JobCancelled,
    JobHandle,
    JobRequest,
    JobStatus,
    ServeError,
    ServiceClosed,
    ServiceOverloaded,
)
from waffle_con_tpu.serve.placement import PlacementPolicy
from waffle_con_tpu.serve.procs.door import ProcConfig, ProcFrontDoor
from waffle_con_tpu.serve.replicas import (
    ReplicatedConfig,
    ReplicatedService,
)
from waffle_con_tpu.serve.scheduler import AdmissionQueue, WorkerPool
from waffle_con_tpu.serve.service import ConsensusService, ServeConfig

__all__ = [
    "AdmissionQueue",
    "ArenaExhausted",
    "BatchingDispatcher",
    "CoalescingScorer",
    "ConsensusService",
    "DeadlineExceeded",
    "JobCancelled",
    "JobHandle",
    "JobRequest",
    "JobStatus",
    "PlacementPolicy",
    "ProcConfig",
    "ProcFrontDoor",
    "ReplicatedConfig",
    "ReplicatedService",
    "ServeConfig",
    "ServeError",
    "ServiceClosed",
    "ServiceOverloaded",
    "WorkerPool",
    "bucket_key",
]
