"""Worker-process entrypoint: one ConsensusService behind a socket.

Launched by the front door as ``python -m
waffle_con_tpu.serve.procs.worker --socket PATH --worker NAME --spec
JSON``.  Each worker owns a full in-process serving stack — batching
dispatcher, ragged arena, worker pool, device slice — exactly the
stack a single-process :class:`~waffle_con_tpu.serve.service.
ConsensusService` runs, so results are byte-identical by construction;
the only new code on this side is the socket plumbing.

Protocol (see :mod:`waffle_con_tpu.serve.procs.wire`):

* connect, send ``HELLO {worker, pid, slots}``;
* every ``SUBMIT`` is decoded (typed codec, never pickle), submitted
  locally, and watched by a per-job thread that reports ``STARTED``
  when the job actually runs, then exactly one of ``RESULT`` /
  ``ERROR`` (kind ``cancelled`` / ``expired`` / ``failed``); a SUBMIT
  carrying a ``checkpoint`` resumes that search instead of restarting
  it (migration off a lost worker);
* every checkpoint the local service snapshots (periodic
  ``WAFFLE_CKPT_INTERVAL_S`` cadence, deadline lapse, or drain) is
  streamed back as a ``CHECKPOINT`` frame so the door always holds the
  latest resume point for this worker's jobs — an ``expired`` ERROR
  additionally carries the final checkpoint inline;
* every local flight-recorder trigger is forwarded as a ``HEALTH``
  frame so the door can attribute demotions and slow searches to this
  worker without any shared memory;
* every post-dedupe flight **incident** (the full JSON dump, not just
  the trigger reason) is forwarded as an ``INCIDENT`` frame
  (``WAFFLE_PROC_INCIDENTS``, default on) — the door re-ingests it
  into its own recorder with worker attribution and fleet-level
  dedupe;
* a SUBMIT carrying a ``trace`` context is **adopted**: the local
  job's spans record under the door's trace id / Chrome pid, and the
  buffered span events travel back on ``RESULT``/``ERROR``/
  ``CHECKPOINT`` frames (capped by ``WAFFLE_TRACE_SPAN_CAP``) so the
  door can stitch one connected cross-process trace per job;
* while metrics are enabled, a periodic ``STATS`` frame
  (``WAFFLE_PROC_STATS_S``) ships this worker's registry snapshot and
  rolling SLO windows for door-side federation;
* ``PING`` answers ``PONG {outstanding, slots}``; ``DRAIN`` rejects
  further submits and asks every running search to checkpoint at its
  next pop boundary while inflight jobs finish; ``SHUTDOWN`` (or
  socket EOF — the door died) closes the service and exits.

The module stays import-light (stdlib + wire) until :func:`main`
actually builds the service, so spawning N workers does not pay N
eager jax imports before the handshake.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time
from typing import Any, Dict, Optional

from waffle_con_tpu.serve.procs import wire
from waffle_con_tpu.utils import envspec

RECV_CHUNK = 1 << 16


def _json_safe(detail: Dict) -> Dict:
    """Flight trigger details can hold arbitrary objects; the wire
    carries strings."""
    out = {}
    for key, value in detail.items():
        out[str(key)] = (value if isinstance(value, (int, float, bool,
                                                     str, type(None)))
                         else str(value))
    return out


class _Worker:
    """Socket-side state for one worker process."""

    def __init__(self, sock: socket.socket, name: str, spec: Dict) -> None:
        from waffle_con_tpu.analysis import lockcheck
        from waffle_con_tpu.serve.service import ConsensusService, ServeConfig

        self._sock = sock
        self._name = name
        self._decoder = wire.FrameDecoder()
        self._send_lock = lockcheck.make_lock("procs.worker.send")
        self._make_thread = lockcheck.make_thread
        self._draining = False
        self._stopped = threading.Event()
        self._slots = int(spec.get("workers", 2))
        # the door arms observability in the spec when it was enabled
        # programmatically on its side (bench --trace-out): env-var
        # arming already travels via os.environ inheritance, but a
        # forced enable_metrics()/Tracer.enable() does not
        if spec.get("metrics"):
            from waffle_con_tpu.obs import metrics as obs_metrics

            obs_metrics.enable_metrics(True)
        if spec.get("trace"):
            from waffle_con_tpu.obs import trace as obs_trace

            obs_trace.get_tracer().enable(True)
        self._service = ConsensusService(
            ServeConfig(
                workers=self._slots,
                queue_limit=int(spec.get("queue_limit", 64)),
                batch_window_s=float(spec.get("batch_window_s", 0.002)),
                max_batch=int(spec.get("max_batch", 8)),
                adaptive_window=bool(spec.get("adaptive_window", True)),
                aging_s=spec.get("aging_s", 0.5),
                name=name,
            ),
            publish_stats=False,
        )
        # share the on-disk XLA cache across the worker fleet so N
        # processes pay each kernel compile once, not N times
        try:
            from waffle_con_tpu.utils.cache import enable_compilation_cache

            enable_compilation_cache()
        except Exception:  # noqa: BLE001 - jax-less stack serves fine
            pass

    # -- sends (serialized: frames must never interleave) --------------

    def send(self, ftype: wire.FrameType, obj: Any) -> None:
        frame = wire.encode_frame(ftype, obj)
        try:
            with self._send_lock:
                self._sock.sendall(frame)
        except OSError:
            pass  # door gone; the reader loop will see EOF and exit

    # -- flight trigger forwarding -------------------------------------

    def on_trigger(self, reason: str, trace_id: Optional[str],
                   detail: Dict) -> None:
        self.send(wire.FrameType.HEALTH, {
            "worker": self._name,
            "reason": reason,
            "trace": trace_id,
            "detail": _json_safe(detail),
        })

    def on_incident(self, incident: Dict) -> None:
        """Forward one post-dedupe flight incident to the door
        (``WAFFLE_PROC_INCIDENTS``; an oversized incident degrades to
        its core identity fields, never to silence)."""
        if envspec.get_raw("WAFFLE_PROC_INCIDENTS", "1") in ("", "0"):
            return
        try:
            # round-trip through json with repr fallback: incident
            # bodies may hold values the strict wire codec rejects
            safe = json.loads(json.dumps(incident, default=repr))
        except (TypeError, ValueError):
            return
        try:
            self.send(wire.FrameType.INCIDENT,
                      {"worker": self._name, "incident": safe})
        except (wire.WireError, ValueError):
            slim = {
                k: safe.get(k)
                for k in ("schema", "seq", "reason", "trace_id",
                          "unix_time", "detail")
            }
            slim["truncated"] = True
            try:
                self.send(wire.FrameType.INCIDENT,
                          {"worker": self._name, "incident": slim})
            except (wire.WireError, ValueError):
                pass

    # -- federated metrics ---------------------------------------------

    def _stats_loop(self) -> None:
        """Ship this worker's registry snapshot + SLO windows to the
        door every ``WAFFLE_PROC_STATS_S`` (first frame immediately, so
        short-lived fleets still federate at least once)."""
        from waffle_con_tpu.obs import flight as obs_flight
        from waffle_con_tpu.obs import metrics as obs_metrics
        from waffle_con_tpu.obs import slo as obs_slo

        period = max(0.05, envspec.get_float("WAFFLE_PROC_STATS_S", 2.0))
        while True:
            try:
                self.send(wire.FrameType.STATS, {
                    "worker": self._name,
                    "unix_time": time.time(),
                    "metrics": obs_metrics.registry().snapshot(),
                    "slo": obs_slo.snapshot(),
                    "incidents": len(obs_flight.incidents()),
                })
            except Exception:  # noqa: BLE001 - one bad snapshot must
                pass           # never kill the cadence
            if self._stopped.wait(period):
                return

    # -- span-buffer return --------------------------------------------

    def _span_payload(self, ctx) -> Optional[Dict]:
        """Drain this job's buffered span events (by adopted Chrome
        pid) for shipment; ``None`` when tracing is off or there is
        nothing to ship — the frame field is absent, not empty."""
        if ctx is None:
            return None
        from waffle_con_tpu.obs import trace as obs_trace

        tracer = obs_trace.get_tracer()
        if not tracer.enabled:
            return None
        cap = envspec.get_int("WAFFLE_TRACE_SPAN_CAP", 512, lo=16)
        events = tracer.drain_events(ctx.chrome_pid, limit=cap)
        if not events:
            return None
        return {"events": events, "origin_us": tracer.unix_origin_us()}

    # -- frame handlers ------------------------------------------------

    def _watch(self, job_id: int, handle, ctx=None,
               flow_id: Optional[int] = None) -> None:
        """Report one job's lifecycle back to the door, in order."""
        from waffle_con_tpu.serve.job import JobStatus

        handle.wait_running()
        if handle.started_at is not None:
            self.send(wire.FrameType.STARTED, {"job": job_id})
        handle.wait()
        status = handle.status
        if ctx is not None and flow_id is not None:
            # return-hop flow arrow: started here, finished by the door
            # at RESULT/ERROR ingest; the event ships in the span drain
            from waffle_con_tpu.obs import trace as obs_trace

            obs_trace.get_tracer().flow("s", flow_id + 1, "result",
                                        ctx=ctx)
        spans = self._span_payload(ctx)
        if status is JobStatus.DONE:
            try:
                frame = {
                    "job": job_id,
                    "kind": handle.request.kind,
                    "result": wire.encode_result(
                        handle.request.kind, handle.result(timeout=0)
                    ),
                }
                if spans is not None:
                    frame["spans"] = spans
                self.send(wire.FrameType.RESULT, frame)
            except Exception as exc:  # noqa: BLE001 - an unencodable
                # result (oversized frame, NaN score, …) must still
                # settle the door-side handle, so report it as a
                # failure instead of dying with neither RESULT nor
                # ERROR ever sent
                self.send(wire.FrameType.ERROR, {
                    "job": job_id,
                    "kind": "failed",
                    "type": type(exc).__name__,
                    "message": f"result not wire-encodable: {exc}",
                })
            return
        try:
            handle.result(timeout=0)
            exc: BaseException = RuntimeError("job failed without exception")
        except BaseException as caught:  # noqa: BLE001 — reported, not handled
            exc = caught
        kind = {JobStatus.CANCELLED: "cancelled",
                JobStatus.EXPIRED: "expired"}.get(status, "failed")
        frame = {
            "job": job_id,
            "kind": kind,
            "type": type(exc).__name__,
            "message": str(exc),
        }
        if kind == "expired" and handle.checkpoint is not None:
            # deadline persistence: the EXPIRED verdict travels with
            # the search's final checkpoint so the client can resubmit
            # with a fresh budget instead of restarting from scratch
            frame["checkpoint"] = handle.checkpoint
        if spans is not None:
            frame["spans"] = spans
        self.send(wire.FrameType.ERROR, frame)

    def _send_checkpoint(self, job_id: int, data, ctx) -> None:
        frame = {
            "job": job_id,
            "data": data,
            "bytes": len(json.dumps(data, separators=(",", ":"))),
        }
        # long jobs stream completed spans incrementally with their
        # snapshots; the final RESULT/ERROR drains the remainder
        spans = self._span_payload(ctx)
        if spans is not None:
            frame["spans"] = spans
        self.send(wire.FrameType.CHECKPOINT, frame)

    def _on_submit(self, obj: Dict) -> None:
        job_id = int(obj["job"])
        if self._draining:
            self.send(wire.FrameType.ERROR, {
                "job": job_id, "kind": "failed",
                "type": "ServiceClosed",
                "message": f"worker {self._name} is draining",
            })
            return
        try:
            trace_obj = wire.decode_trace(obj.get("trace"))
        except wire.WireError:
            trace_obj = None  # malformed context never fails a job
        ctx = None
        try:
            request = wire.decode_request(obj["request"])
            if trace_obj is not None:
                from waffle_con_tpu.obs import trace as obs_trace

                # adopt the door's trace identity BEFORE the handle is
                # queued: local spans then carry the door's trace id and
                # Chrome pid, nesting under its per-job root span
                ctx = obs_trace.context_from_wire(trace_obj)
            handle = self._service.submit(
                request, checkpoint=obj.get("checkpoint"), trace=ctx
            )
        except Exception as exc:  # noqa: BLE001 — reported, not handled
            self.send(wire.FrameType.ERROR, {
                "job": job_id, "kind": "failed",
                "type": type(exc).__name__, "message": str(exc),
            })
            return
        flow_id = trace_obj.get("flow_id") if trace_obj else None
        if ctx is not None and flow_id is not None:
            from waffle_con_tpu.obs import trace as obs_trace

            # finish the door's submit-hop flow arrow on this side of
            # the socket; the event travels back in the span drain
            obs_trace.get_tracer().flow("f", flow_id, "submit", ctx=ctx)
        handle.on_checkpoint = lambda data: self._send_checkpoint(
            job_id, data, ctx
        )
        watcher = self._make_thread(
            target=self._watch, args=(job_id, handle, ctx, flow_id),
            name=f"procs.worker.watch-{job_id}", daemon=True,
        )
        watcher.start()

    def _on_ping(self) -> None:
        self.send(wire.FrameType.PONG, {
            "worker": self._name,
            "outstanding": self._service.outstanding(),
            "slots": self._slots,
        })

    # -- main loop -----------------------------------------------------

    def serve(self) -> None:
        from waffle_con_tpu.obs import flight as obs_flight
        from waffle_con_tpu.obs import metrics as obs_metrics

        self.send(wire.FrameType.HELLO, {
            "worker": self._name, "pid": os.getpid(), "slots": self._slots,
        })
        obs_flight.add_trigger_listener(self.on_trigger)
        obs_flight.add_incident_listener(self.on_incident)
        if obs_metrics.metrics_enabled():
            # federated metrics cadence; with metrics off no thread
            # starts and no STATS frame is ever sent (zero-overhead:
            # absent, not empty)
            self._make_thread(
                target=self._stats_loop,
                name="procs.worker.stats", daemon=True,
            ).start()
        try:
            while True:
                try:
                    data = self._sock.recv(RECV_CHUNK)
                except OSError:
                    return
                if not data:
                    return  # door closed/died: exit with it
                for ftype, obj in self._decoder.feed(data):
                    if ftype is wire.FrameType.SUBMIT:
                        self._on_submit(obj)
                    elif ftype is wire.FrameType.PING:
                        self._on_ping()
                    elif ftype is wire.FrameType.DRAIN:
                        self._draining = True
                        # snapshot every running search at its next pop
                        # boundary: if the drain budget runs out before
                        # a job finishes, the door already holds its
                        # latest resume point
                        self._service.request_checkpoints()
                    elif ftype is wire.FrameType.SHUTDOWN:
                        return
                    # anything else from the door is ignored, not fatal
        finally:
            self._stopped.set()
            obs_flight.remove_trigger_listener(self.on_trigger)
            obs_flight.remove_incident_listener(self.on_incident)
            self._service.close(cancel_pending=True, timeout=10.0)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="waffle_con_tpu out-of-process serving worker"
    )
    parser.add_argument("--socket", required=True,
                        help="front door's AF_UNIX socket path")
    parser.add_argument("--worker", required=True,
                        help="this worker's name (stats/trace label)")
    parser.add_argument("--spec", default="{}",
                        help="JSON ServeConfig field overrides")
    args = parser.parse_args(argv)

    spec = json.loads(args.spec)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(args.socket)
    try:
        _Worker(sock, args.worker, spec).serve()
    finally:
        try:
            sock.close()
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
