"""Length-prefixed frame codec for the out-of-process serving wire.

Every frame is a fixed 10-byte header followed by the payload::

    !BBII  =  version(1)  frame_type(1)  payload_len(4)  crc32(4)

and every payload is JSON (bytes carried as base64) — **never pickle**:
a worker socket is a process boundary and the decoder must not execute
anything the peer sent.  The CRC32 covers the payload only; a mismatch
is a typed :class:`BadChecksum`, a future version byte is a typed
:class:`UnsupportedVersion`, an oversized declared length is a typed
:class:`FrameTooLarge` — decoding never hangs on a torn frame (partial
input just stays buffered in the :class:`FrameDecoder`) and never
raises anything untyped on garbage input.

The config/request/result codecs below are explicit field-by-field
translations (no ``__dict__`` reflection on the decode side): unknown
fields from a newer peer are dropped, enums travel as their ``.value``,
and decoded objects are rebuilt through their real constructors so the
existing ``__eq__``-based byte-parity checks apply unchanged.
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.utils import envspec

#: Protocol version stamped on (and required of) every frame.
FRAME_VERSION = 1

#: version(1) type(1) payload_len(4) crc32(4), network byte order.
HEADER = struct.Struct("!BBII")


class FrameType(enum.IntEnum):
    """Typed frames of the door<->worker protocol."""

    HELLO = 1        #: worker -> door: {worker, pid, slots}
    SUBMIT = 2       #: door -> worker: {job, request[, checkpoint]}
    STARTED = 3      #: worker -> door: {job}
    RESULT = 4       #: worker -> door: {job, kind, result}
    ERROR = 5        #: worker -> door: {job, kind, type, message
                     #:                  [, checkpoint]}
    HEALTH = 6       #: worker -> door: forwarded flight trigger
    PING = 7         #: door -> worker: liveness probe
    PONG = 8         #: worker -> door: {outstanding, occupancy}
    DRAIN = 9        #: door -> worker: stop accepting, finish inflight;
                     #: busy jobs snapshot a checkpoint first
    SHUTDOWN = 10    #: door -> worker: close service and exit
    CHECKPOINT = 11  #: worker -> door: {job, data, bytes} — ``data`` is
                     #: an opaque search-checkpoint wire dict (see
                     #: :mod:`waffle_con_tpu.models.checkpoint`); the
                     #: door stores it verbatim and never decodes it
    STATS = 12       #: worker -> door: periodic {worker, unix_time,
                     #: metrics, slo, incidents} — ``metrics`` is the
                     #: worker's ``MetricsRegistry.snapshot()``, merged
                     #: door-side under ``worker=<name>`` labels; only
                     #: sent when metrics are enabled in the worker
    INCIDENT = 13    #: worker -> door: {worker, incident} — the full
                     #: flight-recorder incident JSON, re-ingested into
                     #: the door's recorder with worker attribution and
                     #: fleet-level (reason, trace_id) dedupe


class WireError(RuntimeError):
    """Base class for frame-codec errors (never a hang, never pickle)."""


class FrameTooLarge(WireError):
    """Declared payload length exceeds ``WAFFLE_PROC_FRAME_MAX``."""


class BadChecksum(WireError):
    """Payload CRC32 does not match the header."""


class UnsupportedVersion(WireError):
    """Frame from a peer speaking a different protocol version."""


class UnknownFrameType(WireError):
    """Well-formed frame with a type byte this side does not know."""


def max_payload() -> int:
    """``WAFFLE_PROC_FRAME_MAX`` — upper bound on one frame's payload
    (default 32 MiB; floor 4 KiB so headers always fit a sane job)."""
    return envspec.get_int("WAFFLE_PROC_FRAME_MAX", 32 * 1024 * 1024,
                           lo=4096)


def encode_frame(ftype: int, obj: Any) -> bytes:
    """One wire frame: header + JSON payload for ``obj``."""
    payload = json.dumps(obj, separators=(",", ":"),
                         allow_nan=False).encode("utf-8")
    if len(payload) > max_payload():
        raise FrameTooLarge(
            f"frame payload {len(payload)} bytes exceeds "
            f"WAFFLE_PROC_FRAME_MAX={max_payload()}"
        )
    return HEADER.pack(
        FRAME_VERSION, int(ftype), len(payload), zlib.crc32(payload)
    ) + payload


class FrameDecoder:
    """Incremental frame parser over a byte stream.

    :meth:`feed` buffers arbitrary chunks (a torn frame simply waits
    for more bytes — there is no blocking read anywhere in the codec)
    and returns every frame completed so far as ``(FrameType, obj)``
    pairs.  Malformed input raises the typed :class:`WireError`
    subclasses; after an error the stream is unrecoverable by design
    (framing is lost), so callers drop the connection.
    """

    def __init__(self) -> None:
        self._buf = bytearray()

    def pending(self) -> int:
        """Bytes buffered but not yet parsed into a full frame."""
        return len(self._buf)

    def feed(self, data: bytes) -> List[Tuple[FrameType, Any]]:
        self._buf += data
        frames: List[Tuple[FrameType, Any]] = []
        while True:
            if len(self._buf) < HEADER.size:
                return frames
            version, ftype, length, crc = HEADER.unpack_from(self._buf)
            if version != FRAME_VERSION:
                raise UnsupportedVersion(
                    f"frame version {version} (speaking {FRAME_VERSION})"
                )
            if length > max_payload():
                raise FrameTooLarge(
                    f"declared payload {length} bytes exceeds "
                    f"WAFFLE_PROC_FRAME_MAX={max_payload()}"
                )
            if len(self._buf) < HEADER.size + length:
                return frames
            payload = bytes(self._buf[HEADER.size:HEADER.size + length])
            del self._buf[:HEADER.size + length]
            if zlib.crc32(payload) != crc:
                raise BadChecksum(
                    f"payload CRC mismatch on frame type {ftype}"
                )
            try:
                kind = FrameType(ftype)
            except ValueError:
                raise UnknownFrameType(f"unknown frame type {ftype}")
            try:
                obj = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as exc:
                raise WireError(f"undecodable payload: {exc}") from None
            frames.append((kind, obj))


# -- bytes-in-JSON helpers ---------------------------------------------

def _b64(data: bytes) -> str:
    return base64.b64encode(bytes(data)).decode("ascii")


def _unb64(text: str) -> bytes:
    try:
        return base64.b64decode(text.encode("ascii"), validate=True)
    except Exception as exc:
        raise WireError(f"bad base64 field: {exc}") from None


# -- trace-context codec -----------------------------------------------

def decode_trace(obj: Optional[Dict]) -> Optional[Dict]:
    """Validate the optional SUBMIT trace context.

    The door mints each job's :class:`~waffle_con_tpu.obs.trace.TraceContext`
    and ships ``{trace_id, chrome_pid, label, parent_span_id, span_base,
    flow_id}`` so the worker's spans join the same Chrome trace tree
    (same synthetic pid, span ids allocated from a disjoint base, root
    spans parented under the door's per-job root span).  ``None``
    passes through (tracing disabled on the door); anything malformed
    is a typed :class:`WireError` — the worker treats that as "no
    context", never a failed job.
    """
    if obj is None:
        return None
    if not isinstance(obj, dict):
        raise WireError("trace context must be an object")
    try:
        out = {
            "trace_id": str(obj["trace_id"]),
            "chrome_pid": int(obj["chrome_pid"]),
            "label": str(obj.get("label") or ""),
            "parent_span_id": (
                int(obj["parent_span_id"])
                if obj.get("parent_span_id") is not None else None
            ),
            "span_base": int(obj.get("span_base") or 0),
            "flow_id": (int(obj["flow_id"])
                        if obj.get("flow_id") is not None else None),
        }
    except (KeyError, TypeError, ValueError) as exc:
        raise WireError(f"bad trace context: {exc}") from None
    if out["chrome_pid"] < 0 or out["span_base"] < 0:
        raise WireError("trace context ids must be non-negative")
    return out


# -- config codec ------------------------------------------------------

def encode_config(config: Optional[CdwfaConfig]) -> Optional[Dict]:
    """A :class:`CdwfaConfig` as plain JSON types (enum -> value,
    tuple -> list); ``None`` passes through."""
    if config is None:
        return None
    out: Dict[str, Any] = {}
    for field in dataclasses.fields(config):
        value = getattr(config, field.name)
        if isinstance(value, ConsensusCost):
            value = value.value
        elif isinstance(value, tuple):
            value = list(value)
        out[field.name] = value
    return out


_CONFIG_FIELDS = frozenset(
    f.name for f in dataclasses.fields(CdwfaConfig)
)


def decode_config(obj: Optional[Dict]) -> Optional[CdwfaConfig]:
    """Rebuild a :class:`CdwfaConfig`, dropping unknown fields so a
    newer peer cannot crash an older worker with an extra knob."""
    if obj is None:
        return None
    if not isinstance(obj, dict):
        raise WireError("config payload must be an object")
    kwargs = {k: v for k, v in obj.items() if k in _CONFIG_FIELDS}
    if "consensus_cost" in kwargs:
        kwargs["consensus_cost"] = ConsensusCost(kwargs["consensus_cost"])
    if kwargs.get("backend_chain") is not None:
        kwargs["backend_chain"] = tuple(kwargs["backend_chain"])
    try:
        return CdwfaConfig(**kwargs)
    except (TypeError, ValueError) as exc:
        raise WireError(f"bad config payload: {exc}") from None


# -- request codec -----------------------------------------------------

def encode_request(request, deadline_left_s: Optional[float] = None) -> Dict:
    """A :class:`~waffle_con_tpu.serve.job.JobRequest` as JSON.

    ``deadline_left_s`` replaces the request's original budget with the
    *remaining* budget as computed by the door — the worker's clock
    starts at its own submit, so the wall-clock deadline keeps meaning
    across the process boundary.
    """
    if request.kind == "priority":
        reads: Any = [[_b64(s) for s in chain] for chain in request.reads]
    else:
        reads = [_b64(r) for r in request.reads]
    return {
        "kind": request.kind,
        "reads": reads,
        "config": encode_config(request.config),
        "offsets": (list(request.offsets)
                    if request.offsets is not None else None),
        "priority": request.priority,
        "deadline_s": (deadline_left_s if deadline_left_s is not None
                       else request.deadline_s),
        "tag": request.tag,
    }


def decode_request(obj: Dict):
    """Rebuild a :class:`~waffle_con_tpu.serve.job.JobRequest` (its
    own ``__post_init__`` validation applies on this side too)."""
    from waffle_con_tpu.serve.job import JobRequest

    if not isinstance(obj, dict):
        raise WireError("request payload must be an object")
    try:
        kind = obj["kind"]
        if kind == "priority":
            reads: Any = tuple(
                tuple(_unb64(s) for s in chain) for chain in obj["reads"]
            )
        else:
            reads = tuple(_unb64(r) for r in obj["reads"])
        offsets = obj.get("offsets")
        return JobRequest(
            kind=kind,
            reads=reads,
            config=decode_config(obj.get("config")),
            offsets=tuple(offsets) if offsets is not None else None,
            priority=int(obj.get("priority", 0)),
            deadline_s=obj.get("deadline_s"),
            tag=obj.get("tag"),
        )
    except WireError:
        raise
    except (KeyError, TypeError, ValueError) as exc:
        raise WireError(f"bad request payload: {exc}") from None


# -- result codec ------------------------------------------------------
#
# The model classes pull the engine modules, so import them lazily:
# the door decodes results without ever importing an engine.

def _encode_consensus(c) -> Dict:
    return {
        "sequence": _b64(c.sequence),
        "cost": c.consensus_cost.value,
        "scores": list(c.scores),
    }


def _decode_consensus(obj: Dict):
    from waffle_con_tpu.models.consensus import Consensus

    return Consensus(
        sequence=_unb64(obj["sequence"]),
        consensus_cost=ConsensusCost(obj["cost"]),
        scores=list(obj["scores"]),
    )


def encode_result(kind: str, result: Any) -> Any:
    """The engine result for one finished job as JSON (tagged by the
    request's ``kind``; every variant roundtrips through ``__eq__``)."""
    if kind == "single":
        return [_encode_consensus(c) for c in result]
    if kind == "dual":
        return [
            {
                "consensus1": _encode_consensus(d.consensus1),
                "consensus2": (_encode_consensus(d.consensus2)
                               if d.consensus2 is not None else None),
                "is_consensus1": list(d.is_consensus1),
                "scores1": list(d.scores1),
                "scores2": list(d.scores2),
            }
            for d in result
        ]
    if kind == "priority":
        return {
            "consensuses": [
                [_encode_consensus(c) for c in tier]
                for tier in result.consensuses
            ],
            "sequence_indices": list(result.sequence_indices),
        }
    raise WireError(f"unknown result kind {kind!r}")


def decode_result(kind: str, obj: Any) -> Any:
    """Inverse of :func:`encode_result`."""
    try:
        if kind == "single":
            return [_decode_consensus(c) for c in obj]
        if kind == "dual":
            from waffle_con_tpu.models.dual_consensus import DualConsensus

            return [
                DualConsensus(
                    consensus1=_decode_consensus(d["consensus1"]),
                    consensus2=(_decode_consensus(d["consensus2"])
                                if d["consensus2"] is not None else None),
                    is_consensus1=list(d["is_consensus1"]),
                    scores1=list(d["scores1"]),
                    scores2=list(d["scores2"]),
                )
                for d in obj
            ]
        if kind == "priority":
            from waffle_con_tpu.models.priority_consensus import (
                PriorityConsensus,
            )

            return PriorityConsensus(
                consensuses=[
                    [_decode_consensus(c) for c in tier]
                    for tier in obj["consensuses"]
                ],
                sequence_indices=list(obj["sequence_indices"]),
            )
    except WireError:
        raise
    except (KeyError, TypeError, ValueError) as exc:
        raise WireError(f"bad result payload: {exc}") from None
    raise WireError(f"unknown result kind {kind!r}")
