"""Out-of-process front door: admission + routing over worker processes.

:class:`ProcFrontDoor` is the process-parallel sibling of
:class:`~waffle_con_tpu.serve.replicas.ReplicatedService`: the same
least-outstanding, health-aware routing shape, but the N replicas are
real **worker processes** (own interpreter, own GIL, own dispatcher +
ragged arena + device slice) reached over an AF_UNIX socket speaking
the typed frame protocol of :mod:`waffle_con_tpu.serve.procs.wire`.

The door owns everything the workers must agree on exactly once:

* **admission** — one bounded priority queue with anti-starvation
  aging (the same :class:`~waffle_con_tpu.serve.scheduler.
  AdmissionQueue` the in-process service uses); a full queue rejects
  with :class:`~waffle_con_tpu.serve.job.ServiceOverloaded`.
* **placement** — :class:`~waffle_con_tpu.serve.placement.
  PlacementPolicy` runs door-side at admission, so the mesh-vs-arena
  decision is made once and travels to the worker inside the job's
  config.
* **health** — each worker forwards its flight-recorder triggers as
  ``HEALTH`` frames; ``backend_demoted`` puts the worker in
  ``draining`` (no new routes until its inflight set empties, then
  automatic re-admission), ``slow_search`` in ``shedding`` for a
  cooldown — mirroring the in-process replica semantics verbatim.
* **liveness** — a watchdog pings every worker
  (``WAFFLE_PROC_PING_S``) and any frame counts as a heartbeat; a dead
  process, closed socket, or silence past ``WAFFLE_PROC_LIVENESS_S``
  marks the worker **lost**: exactly one ``worker_lost`` flight
  trigger fires and its jobs move to healthy workers — not-yet-started
  jobs are requeued, and *started* jobs **migrate**: the door
  re-dispatches each with the latest ``CHECKPOINT`` frame the worker
  streamed back, so the search resumes at its last pop boundary
  instead of re-running (byte-identical either way — the checkpoint
  format is built on the engines' node-identity invariant, see
  :mod:`waffle_con_tpu.models.checkpoint`).  A started job that never
  checkpointed (or with ``WAFFLE_CKPT_MIGRATE=0``) restarts from
  scratch under ``restart_lost=True`` (the fallback), or fails with
  the typed :class:`~waffle_con_tpu.runtime.liveness.WorkerLost`.
* **checkpoints** — workers snapshot long searches periodically
  (``WAFFLE_CKPT_INTERVAL_S``), at deadline lapse, and on ``DRAIN``;
  each snapshot lands on the door-side handle, which is also what a
  graceful :meth:`ProcFrontDoor.close` relies on: once the admission
  queue empties it sends ``DRAIN`` to still-busy workers, so a drain
  that runs out of budget leaves every started job with a fresh
  resume point instead of nothing.
* **observability** — ``waffle_worker_*`` and ``waffle_ckpt_*``
  gauges/counters, a ``workers`` table in the ``WAFFLE_STATS_FILE``
  payload (the door is the only stats publisher; workers run with
  stats disabled), runtime events for every transition.  With the
  fleet observability plane armed, the door additionally (a) mints a
  per-job :class:`~waffle_con_tpu.obs.trace.TraceContext` on each
  SUBMIT and stitches the worker's returned span buffer into one
  connected Chrome trace (flow arrows across the socket hop), (b)
  merges each worker's periodic ``STATS`` metrics snapshot into its
  own registry under a ``worker=`` label — a single fleet-wide
  Prometheus exposition — plus ``waffle_door_job_phase_seconds``
  histograms splitting e2e latency by queued/routed/running phase,
  and (c) re-ingests forwarded ``INCIDENT`` frames into its flight
  recorder with worker attribution and fleet-level dedupe.

Client-side cancellation settles the door-side handle immediately;
the worker keeps computing until its own dispatch-boundary abort and
its late frames land on an already-terminal handle (a no-op).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Set

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import slo as obs_slo
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.runtime import events
from waffle_con_tpu.runtime.liveness import Heartbeats, WorkerLost
from waffle_con_tpu.runtime.watchdog import DeadlineExceeded
from waffle_con_tpu.serve.job import (
    JobCancelled,
    JobHandle,
    JobRequest,
    JobStatus,
    ServiceClosed,
    ServiceOverloaded,
)
from waffle_con_tpu.serve.procs import wire
from waffle_con_tpu.serve.scheduler import AdmissionQueue
from waffle_con_tpu.utils import envspec

#: worker states (the first three mirror the in-process replica set)
UP = "up"
DRAINING = "draining"    # circuit-break: no routes until drained
SHEDDING = "shedding"    # latency flag: deprioritized for a cooldown
LOST = "lost"            # process dead / socket gone / liveness lapse

_HEALTH_REASONS = ("backend_demoted", "slow_search")

RECV_CHUNK = 1 << 16


def ping_interval_s() -> float:
    """``WAFFLE_PROC_PING_S`` — watchdog ping period (default 0.5 s)."""
    return envspec.get_float("WAFFLE_PROC_PING_S", 0.5)


def liveness_lapse_s() -> float:
    """``WAFFLE_PROC_LIVENESS_S`` — silence before a worker is
    declared lost (default 5 s)."""
    return envspec.get_float("WAFFLE_PROC_LIVENESS_S", 5.0)


def migrate_enabled() -> bool:
    """``WAFFLE_CKPT_MIGRATE`` — resume a lost worker's started jobs
    from their last checkpoint (default on; ``0`` falls back to the
    ``restart_lost`` restart-from-scratch path)."""
    raw = envspec.get_raw("WAFFLE_CKPT_MIGRATE", "1") or "1"
    return raw.strip().lower() not in ("0", "false", "off", "no")


@dataclasses.dataclass(frozen=True)
class ProcConfig:
    """Front-door knobs.

    * ``workers`` — worker *process* count.
    * ``worker_slots`` — concurrent jobs inside each worker (its
      in-process ``ServeConfig.workers``).
    * ``inflight`` — routed-but-unfinished jobs the door keeps on one
      worker before holding further routes (default
      ``2 * worker_slots``: one batch running, one queued behind it).
    * ``restart_lost`` — restart a crashed worker's already-started
      jobs from scratch on a healthy worker (deterministic engines
      make the retried result byte-identical); off, those jobs fail
      with :class:`~waffle_con_tpu.runtime.liveness.WorkerLost`.
      Not-yet-started jobs are requeued either way.
    * ``launcher`` — test seam: ``launcher(socket_path, name,
      spec_json)`` returning a Popen-like handle (``pid``/``poll``/
      ``terminate``/``kill``/``wait``); ``None`` spawns
      ``python -m waffle_con_tpu.serve.procs.worker``.
    """

    workers: int = 2
    worker_slots: int = 2
    queue_limit: int = 64
    batch_window_s: float = 0.002
    max_batch: int = 8
    name: str = "consensus"
    adaptive_window: bool = True
    aging_s: Optional[float] = 0.5
    placement: Optional[object] = None
    shed_cooldown_s: float = 2.0
    restart_lost: bool = True
    inflight: Optional[int] = None
    spawn_timeout_s: float = 120.0
    launcher: Optional[Callable[[str, str, str], Any]] = None

    def __post_init__(self) -> None:
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        if self.worker_slots < 1:
            raise ValueError("worker_slots must be >= 1")
        if self.queue_limit < 1:
            raise ValueError("queue_limit must be >= 1")
        if self.shed_cooldown_s < 0:
            raise ValueError("shed_cooldown_s must be >= 0")
        if self.inflight is not None and self.inflight < 1:
            raise ValueError("inflight must be >= 1 (or None)")

    @property
    def window(self) -> int:
        return (self.inflight if self.inflight is not None
                else 2 * self.worker_slots)


class _Worker:
    """Mutable per-worker record (state guarded by the door's lock)."""

    __slots__ = ("index", "name", "proc", "pid", "sock", "slots",
                 "state", "shed_until", "assigned", "started",
                 "routed", "demotions", "sheds", "readmits", "requeues",
                 "migrations", "restarts", "ckpt_frames", "ckpt_bytes",
                 "reported_outstanding", "decoder", "send_lock",
                 "stats_frames", "stats_at", "last_slo", "incidents",
                 "span_events")

    def __init__(self, index: int, name: str) -> None:
        self.index = index
        self.name = name
        self.proc: Any = None
        self.pid: Optional[int] = None
        self.sock: Optional[socket.socket] = None
        self.slots = 1
        self.state = UP
        self.shed_until = 0.0
        self.assigned: Dict[int, JobHandle] = {}
        self.started: Set[int] = set()
        self.routed = 0
        self.demotions = 0
        self.sheds = 0
        self.readmits = 0
        self.requeues = 0
        self.migrations = 0
        self.restarts = 0
        self.ckpt_frames = 0
        self.ckpt_bytes = 0
        self.reported_outstanding = 0
        self.decoder = wire.FrameDecoder()
        self.send_lock = lockcheck.make_lock(f"procs.door.send.{name}")
        self.stats_frames = 0
        self.stats_at: Optional[float] = None
        self.last_slo: Optional[Dict] = None
        self.incidents = 0
        self.span_events = 0


class ProcFrontDoor:
    """N worker processes behind least-outstanding, health-aware
    routing over the typed socket protocol.

    Usage::

        with ProcFrontDoor(ProcConfig(workers=2)) as door:
            handles = [door.submit(req) for req in requests]
            results = [h.result() for h in handles]
    """

    def __init__(
        self,
        config: Optional[ProcConfig] = None,
        autostart: bool = True,
    ) -> None:
        self.config = config if config is not None else ProcConfig()
        self._lock = lockcheck.make_lock("serve.procs.ProcFrontDoor")
        self._closed = False
        self._started = False
        self._next_id = 0
        self._jobs: Dict[int, JobHandle] = {}
        self._counts: Dict[str, int] = collections.defaultdict(int)
        self._retry: Deque[JobHandle] = collections.deque()
        self._queue = AdmissionQueue(
            limit=self.config.queue_limit,
            name=f"{self.config.name}.door",
            aging_s=self.config.aging_s,
        )
        self._beats = Heartbeats()
        #: per-job distributed-trace state ({"root", "dispatches",
        #: "flow"}) keyed by job id; entries exist only while tracing is
        #: enabled and the job is in flight (see _trace_dispatch)
        self._trace_jobs: Dict[int, Dict] = {}
        #: monotonic timestamp of each job's last successful SUBMIT send
        #: — feeds the queued/routed/running phase histograms
        self._routed_at: Dict[int, float] = {}
        self._stats_published_at = 0.0
        self._stopping = False
        from waffle_con_tpu.serve import cache as serve_cache

        #: door-side consensus cache (None when WAFFLE_CACHE is off):
        #: exact/certified hits answer before SUBMIT serialization,
        #: superset hits ride a cached checkpoint to the worker
        self._cache = serve_cache.ConsensusCache.from_env(
            f"{self.config.name}.door"
        )
        self._tmpdir = tempfile.mkdtemp(prefix="waffle-procs-")
        self._socket_path = os.path.join(self._tmpdir, "door.sock")
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self._socket_path)
        self._listener.listen(self.config.workers)
        self._workers = [
            _Worker(i, f"{self.config.name}:w{i}")
            for i in range(self.config.workers)
        ]
        self._threads: List[Any] = []
        if autostart:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def _worker_spec(self) -> str:
        cfg = self.config
        return json.dumps({
            "workers": cfg.worker_slots,
            # the worker's own queue must absorb the door's full
            # routing window; placement stays door-side (None here)
            "queue_limit": max(cfg.queue_limit, cfg.window),
            "batch_window_s": cfg.batch_window_s,
            "max_batch": cfg.max_batch,
            "adaptive_window": cfg.adaptive_window,
            "aging_s": cfg.aging_s,
            # programmatic enables don't travel via the environment:
            # tell the worker to arm its own tracer / metrics registry
            # so spans and STATS frames flow back to the door
            "trace": obs_trace.tracing_enabled(),
            "metrics": obs_metrics.metrics_enabled(),
        })

    @staticmethod
    def _spawn_process(socket_path: str, name: str, spec: str):
        env = dict(os.environ)
        # the door is the only stats publisher
        env.pop("WAFFLE_STATS_FILE", None)
        # the door owns the consensus cache: a worker-side cache would
        # be redundant (the door short-circuits first) and a shared
        # WAFFLE_CACHE_DIR would race N manifest writers
        env.pop("WAFFLE_CACHE", None)
        env.pop("WAFFLE_CACHE_DIR", None)
        # with incident forwarding on the door is also the only
        # incident dumper: the worker forwards its flight dump over the
        # INCIDENT frame and the door re-ingests it with attribution —
        # a worker writing the same incident to the shared dump dir
        # would double every file
        if envspec.get_raw("WAFFLE_PROC_INCIDENTS", "1") not in ("", "0"):
            env.pop("WAFFLE_FLIGHT_DIR", None)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env["PYTHONPATH"] = (
            pkg_root + (os.pathsep + env["PYTHONPATH"]
                        if env.get("PYTHONPATH") else "")
        )
        return subprocess.Popen(
            [sys.executable, "-m", "waffle_con_tpu.serve.procs.worker",
             "--socket", socket_path, "--worker", name, "--spec", spec],
            env=env,
        )

    def start(self) -> None:
        with self._lock:
            if self._started or self._closed:
                return
            self._started = True
        spec = self._worker_spec()
        launcher = self.config.launcher or self._spawn_process
        for worker in self._workers:
            worker.proc = launcher(self._socket_path, worker.name, spec)
        deadline = time.monotonic() + self.config.spawn_timeout_s
        pending = {w.name: w for w in self._workers}
        conn: Optional[socket.socket] = None
        try:
            while pending:
                self._listener.settimeout(
                    max(0.1, deadline - time.monotonic())
                )
                try:
                    conn, _ = self._listener.accept()
                except (socket.timeout, OSError):
                    raise RuntimeError(
                        f"worker handshake timed out; still waiting for "
                        f"{sorted(pending)}"
                    ) from None
                hello, trailing, decoder = self._handshake(conn, deadline)
                worker = pending.pop(hello["worker"], None)
                if worker is None:
                    conn.close()
                    conn = None
                    continue
                worker.sock = conn
                conn = None
                worker.decoder = decoder
                worker.pid = int(hello.get("pid", 0)) or None
                worker.slots = int(hello.get("slots", 1))
                self._beats.beat(worker.name)
                for ftype, obj in trailing:
                    self._on_frame(worker, ftype, obj)
        except Exception:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            self._abort_spawn()
            raise
        for worker in self._workers:
            thread = lockcheck.make_thread(
                target=self._read_loop, args=(worker,),
                name=f"procs.door.read.{worker.name}", daemon=True,
            )
            thread.start()
            self._threads.append(thread)
        router = lockcheck.make_thread(
            target=self._route_loop, name="procs.door.router", daemon=True,
        )
        router.start()
        self._threads.append(router)
        watchdog = lockcheck.make_thread(
            target=self._watch_loop, name="procs.door.watchdog",
            daemon=True,
        )
        watchdog.start()
        self._threads.append(watchdog)
        events.record(
            "procs_door_up", service=self.config.name,
            workers=len(self._workers),
        )

    def _abort_spawn(self) -> None:
        """Handshake failed: close accepted sockets and reap every
        process already launched, so a raising :meth:`start` leaks no
        live workers (each may be mid jax import)."""
        for worker in self._workers:
            if worker.sock is not None:
                try:
                    worker.sock.close()
                except OSError:
                    pass
                worker.sock = None
            proc = worker.proc
            if proc is None:
                continue
            try:
                proc.terminate()
                proc.wait(timeout=2.0)
            except Exception:  # noqa: BLE001 - escalate to kill
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001
                    pass
        # a raising start() means close() will never run: drop the
        # listener + socket dir here or they leak with the object
        try:
            self._listener.close()
            os.unlink(self._socket_path)
            os.rmdir(self._tmpdir)
        except OSError:
            pass

    @staticmethod
    def _handshake(conn: socket.socket, deadline: float):
        """Read frames until HELLO.  Returns the HELLO payload plus any
        frames that rode in the same chunk and the primed decoder —
        the caller must adopt both, or an eager worker's first HEALTH /
        STARTED frame would be silently dropped."""
        decoder = wire.FrameDecoder()
        while True:
            conn.settimeout(max(0.1, deadline - time.monotonic()))
            data = conn.recv(RECV_CHUNK)
            if not data:
                raise RuntimeError("worker closed during handshake")
            frames = decoder.feed(data)
            if not frames:
                continue
            ftype, obj = frames[0]
            if ftype is not wire.FrameType.HELLO:
                raise RuntimeError(f"expected HELLO, got {ftype.name}")
            conn.settimeout(None)
            return obj, frames[1:], decoder

    def close(
        self, cancel_pending: bool = False, timeout: Optional[float] = None
    ) -> None:
        """Shut down.  Default drains gracefully: everything already
        admitted runs to completion first.  ``cancel_pending=True``
        cancels still-queued jobs instead."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._queue.close()
        if cancel_pending:
            leftovers = self._queue.drain()
            with self._lock:
                leftovers.extend(self._retry)
                self._retry.clear()
            for handle in leftovers:
                handle._finish(
                    JobStatus.CANCELLED,
                    exception=ServiceClosed("service closed"),
                )
        budget = timeout if timeout is not None else 60.0
        deadline = time.monotonic() + budget
        drain_sent = False
        while time.monotonic() < deadline:
            # outstanding() counts every admitted non-done handle, so a
            # job mid-route (popped from the queue, not yet in a
            # worker's assigned set) still holds the drain open
            if self.outstanding() == 0:
                break
            if not drain_sent and self._queue.depth() == 0:
                # everything is routed: ask busy workers to checkpoint
                # their running searches (DRAIN also stops late
                # submits), so a drain that runs out of budget still
                # leaves every started job a fresh resume point on its
                # door-side handle
                with self._lock:
                    idle = not self._retry
                    busy = [w for w in self._workers
                            if w.state != LOST and w.sock is not None
                            and w.assigned]
                if idle:
                    for worker in busy:
                        self._send(worker, wire.FrameType.DRAIN, {})
                    drain_sent = True
            time.sleep(0.02)
        self._stopping = True
        for worker in self._workers:
            if worker.state != LOST and worker.sock is not None:
                self._send(worker, wire.FrameType.SHUTDOWN, {})
        for worker in self._workers:
            proc = worker.proc
            if proc is None or not hasattr(proc, "wait"):
                continue
            try:
                proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 - escalate to terminate/kill
                try:
                    proc.terminate()
                    proc.wait(timeout=2.0)
                except Exception:  # noqa: BLE001
                    try:
                        proc.kill()
                    except Exception:  # noqa: BLE001
                        pass
            if worker.sock is not None:
                try:
                    worker.sock.close()
                except OSError:
                    pass
            self._beats.forget(worker.name)
        try:
            self._listener.close()
            os.unlink(self._socket_path)
            os.rmdir(self._tmpdir)
        except OSError:
            pass
        # anything still unfinished is orphaned by shutdown
        with self._lock:
            orphans = [h for h in self._jobs.values() if not h.done()]
        for handle in orphans:
            handle._finish(
                JobStatus.CANCELLED,
                exception=ServiceClosed("service closed before the job "
                                        "finished"),
            )
        events.record("procs_door_down", service=self.config.name)
        self._publish_stats(force=True)

    def __enter__(self) -> "ProcFrontDoor":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()

    # -- client API ----------------------------------------------------

    def submit(self, request: JobRequest,
               checkpoint=None) -> JobHandle:
        """Admit one job; raises :class:`ServiceOverloaded` when the
        bounded queue is full and :class:`ServiceClosed` after close.

        ``checkpoint`` resumes a previously snapshotted search (a wire
        dict from :attr:`~waffle_con_tpu.serve.job.JobHandle.
        checkpoint`, e.g. off an EXPIRED handle): the SUBMIT carries
        it to whichever worker the job routes to."""
        if not isinstance(request, JobRequest):
            raise TypeError(
                f"expected JobRequest, got {type(request).__name__}"
            )
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is closed to new jobs")
        request = self._place(request)
        with self._lock:
            job_id = self._next_id
            self._next_id += 1
            handle = JobHandle(job_id, request, service=self.config.name)
            self._jobs[job_id] = handle
            self._counts["submitted"] += 1
        if checkpoint is not None:
            handle._attach_checkpoint(checkpoint)
        elif self._cache is not None:
            # the short-circuit answers before any SUBMIT frame is even
            # encoded: an exact/certified hit never costs serialization,
            # routing, or a worker slot
            from waffle_con_tpu.serve import cache as serve_cache

            hit = self._cache.lookup(
                request, trace_id=handle.trace.trace_id
            )
            if isinstance(hit, serve_cache.CacheHit):
                status = (
                    JobStatus.CACHED if hit.tier == "exact"
                    else JobStatus.CERTIFIED
                )
                handle._finish(status, result=hit.result)
                self._publish_stats()
                return handle
            if isinstance(hit, serve_cache.CheckpointHit):
                handle._attach_checkpoint(hit.checkpoint)
                handle._resumed_from_checkpoint = True
        try:
            self._queue.put(handle)
        except (ServiceOverloaded, ServiceClosed):
            with self._lock:
                self._counts["submitted"] -= 1
                del self._jobs[job_id]
            raise
        self._publish_stats()
        return handle

    def submit_all(self, requests: Sequence[JobRequest]) -> List[JobHandle]:
        return [self.submit(r) for r in requests]

    def outstanding(self) -> int:
        """Admitted-but-unfinished job count (queued + routed)."""
        with self._lock:
            return sum(1 for h in self._jobs.values() if not h.done())

    def _place(self, request: JobRequest) -> JobRequest:
        """Door-side placement (the decision travels in the config)."""
        policy = self.config.placement
        if policy is None:
            return request
        try:
            from waffle_con_tpu.parallel import mesh as par_mesh

            placed = policy.place(request, par_mesh.probe_device_count())
        except Exception:  # noqa: BLE001 - jax-less stack, probe failure
            return request
        if placed is None:
            return request
        with self._lock:
            self._counts["mesh_placed"] += 1
        events.record(
            "job_placed_mesh", job_kind=request.kind,
            reads=len(request.reads),
            shards=placed.config.mesh_shards,
            service=self.config.name,
        )
        return placed

    # -- routing -------------------------------------------------------

    def _route_loop(self) -> None:
        while True:
            handle: Optional[JobHandle] = None
            with self._lock:
                if self._retry:
                    handle = self._retry.popleft()
            if handle is None:
                handle = self._queue.get(timeout=0.1)
            if handle is None:
                with self._lock:
                    drained = (self._closed and not self._retry)
                if drained and self._queue.depth() == 0:
                    return
                continue
            if handle.done():
                continue  # cancelled while queued
            try:
                self._route_one(handle)
            except Exception as exc:  # noqa: BLE001 - the router is a
                # singleton: an escaping exception would stop routing
                # forever, so settle the one job and keep going
                if not handle.done():
                    handle._finish(JobStatus.FAILED, exception=exc)

    def _route_one(self, handle: JobHandle) -> None:
        """Assign one job to the best worker, holding it while no
        worker has window capacity (bounded by close)."""
        while True:
            self._maintain()
            worker = None
            with self._lock:
                if self._closed and self._stopping:
                    break
                window = self.config.window
                ranked = sorted(
                    (w for w in self._workers if w.state != LOST),
                    key=lambda w: (0 if w.state == UP else 1,
                                   len(w.assigned), w.index),
                )
                healthy = [w for w in ranked if w.state == UP]
                pool = healthy or ranked
                with_room = [w for w in pool if len(w.assigned) < window]
                if not with_room and healthy and len(healthy) < len(ranked):
                    # healthy tier full: overflow onto the remainder
                    with_room = [
                        w for w in ranked
                        if w not in healthy and len(w.assigned) < window
                    ]
                if with_room:
                    worker = with_room[0]
                    worker.assigned[handle.job_id] = handle
                    worker.routed += 1
            if worker is None:
                if handle.done():
                    return
                time.sleep(0.01)
                continue
            if self._dispatch(worker, handle):
                self._publish_worker_metrics(worker)
                self._publish_stats()
            # on dispatch failure the handle was already expired or
            # pushed back onto the retry deque — either way this
            # routing attempt is over
            return
        handle._finish(
            JobStatus.CANCELLED,
            exception=ServiceClosed("service closed before the job "
                                    "was routed"),
        )

    def _dispatch(self, worker: _Worker, handle: JobHandle) -> bool:
        """Send one SUBMIT; on failure unassign and expire/requeue."""
        deadline_left = None
        if handle.deadline is not None:
            deadline_left = handle.deadline - time.monotonic()
            if deadline_left <= 0:
                with self._lock:
                    worker.assigned.pop(handle.job_id, None)
                handle._finish(
                    JobStatus.EXPIRED,
                    exception=DeadlineExceeded(
                        f"job {handle.job_id} deadline lapsed before "
                        "routing"
                    ),
                )
                return False
        payload = {
            "job": handle.job_id,
            "request": wire.encode_request(
                handle.request, deadline_left_s=deadline_left
            ),
        }
        checkpoint = handle.checkpoint
        if checkpoint is not None:
            # the opaque resume point rides in the SUBMIT; the door
            # never decodes it (the worker validates CRC/version and
            # degrades to a fresh search on rejection)
            payload["checkpoint"] = checkpoint
            # a job that starts from any checkpoint (client resume,
            # cache superset hit, migration) must not deposit back into
            # the cache: its search did not cover the space from scratch
            handle._resumed_from_checkpoint = True
        trace_obj = self._trace_dispatch(handle)
        if trace_obj is not None:
            payload["trace"] = trace_obj
        try:
            try:
                frame = wire.encode_frame(wire.FrameType.SUBMIT, payload)
            except wire.FrameTooLarge:
                if "checkpoint" not in payload:
                    raise
                # an oversized checkpoint must not wedge the job: drop
                # it and dispatch a restart-from-scratch instead
                del payload["checkpoint"]
                frame = wire.encode_frame(wire.FrameType.SUBMIT, payload)
        except (wire.WireError, ValueError, TypeError) as exc:
            # an unencodable request (oversized, non-finite, …) must
            # fail this one job, never the router thread
            with self._lock:
                worker.assigned.pop(handle.job_id, None)
            handle._finish(JobStatus.FAILED, exception=exc)
            return False
        try:
            with worker.send_lock:
                worker.sock.sendall(frame)
            if obs_metrics.metrics_enabled():
                with self._lock:
                    self._routed_at[handle.job_id] = time.monotonic()
            return True
        except OSError:
            with self._lock:
                if worker.assigned.pop(handle.job_id, None) is None:
                    # a concurrent _worker_lost already snapshotted and
                    # requeued this job — it owns the retry
                    return False
                self._retry.append(handle)
            return False

    def _send(self, worker: _Worker, ftype: wire.FrameType,
              obj: Any) -> None:
        if worker.sock is None:
            return
        try:
            frame = wire.encode_frame(ftype, obj)
            with worker.send_lock:
                worker.sock.sendall(frame)
        except OSError:
            pass  # the reader/watchdog will declare the worker lost

    # -- distributed tracing -------------------------------------------

    def _trace_dispatch(self, handle: JobHandle) -> Optional[Dict]:
        """Mint this dispatch's wire trace context (``None`` — and zero
        work — with tracing disabled, so the SUBMIT frame stays
        byte-identical to the untraced protocol).

        First dispatch opens the job's door-side **root** span (held
        open until :meth:`_trace_settle`) and records the retrospective
        ``door:queued`` phase under it.  Every dispatch emits the
        submit-hop flow arrow (``"s"`` here, ``"f"`` in the worker) and
        ships a dispatch-disjoint ``span_base`` so a migrated job's
        second worker can never collide span ids with the first.
        """
        tracer = obs_trace.get_tracer()
        if not tracer.enabled:
            return None
        ctx = handle.trace
        queued_span = None
        now = time.monotonic()
        with self._lock:
            state = self._trace_jobs.get(handle.job_id)
            if state is None:
                root, _ = ctx._open_span()  # closed by _trace_settle
                qid, qparent = ctx._open_span()
                ctx._close_span(qid)
                state = {"root": root, "dispatches": 0, "flow": 0}
                self._trace_jobs[handle.job_id] = state
                queued_span = (qid, qparent)
            state["dispatches"] += 1
            n = state["dispatches"]
            # 16 flow ids per job pid: 8 dispatch attempts x (submit
            # arrow, result arrow) before ids recycle
            fid = ctx.chrome_pid * 16 + (n & 7) * 2
            state["flow"] = fid
            root = state["root"]
        if queued_span is not None:
            tracer.record_span(
                ctx, "door:queued", "door", handle.submitted_at, now,
                span_id=queued_span[0], parent_id=queued_span[1],
            )
        tracer.flow("s", fid, "submit", ctx=ctx)
        return obs_trace.context_to_wire(
            ctx, parent_span_id=root, span_base=1_000_000 * n,
            flow_id=fid,
        )

    def _trace_settle(self, handle: JobHandle, status: str) -> None:
        """Close out the job's door-side trace: the result-hop flow
        arrow (finishing the worker's ``"s"``) and the ``door:job``
        envelope span the whole stitched tree hangs under."""
        with self._lock:
            state = self._trace_jobs.pop(handle.job_id, None)
        tracer = obs_trace.get_tracer()
        if state is None or not tracer.enabled:
            return
        ctx = handle.trace
        tracer.flow("f", state["flow"] + 1, "result", ctx=ctx)
        end = handle.finished_at
        if end is None:
            end = time.monotonic()
        tracer.record_span(
            ctx, "door:job", "door", handle.submitted_at, end,
            span_id=state["root"], parent_id=None,
            status=status, dispatches=state["dispatches"],
        )
        ctx._close_span(state["root"])

    def _ingest_spans(self, worker: _Worker, obj: Dict) -> None:
        """Stitch a frame's piggybacked worker span buffer into the
        door's tracer (rebasing onto the door's clock)."""
        spans = obj.get("spans") if isinstance(obj, dict) else None
        if not isinstance(spans, dict):
            return
        events_list = spans.get("events")
        if not isinstance(events_list, list):
            return
        n = obs_trace.get_tracer().ingest_remote_events(
            events_list, origin_us=spans.get("origin_us"),
            worker=worker.name,
        )
        if n:
            with self._lock:
                worker.span_events += n

    def _observe_phases(self, handle: JobHandle) -> None:
        """E2e latency split by door phase — ``queued`` (admission to
        SUBMIT send), ``routed`` (send to worker STARTED), ``running``
        (STARTED to terminal) — as one labelled histogram family."""
        with self._lock:
            routed_at = self._routed_at.pop(handle.job_id, None)
        if not obs_metrics.metrics_enabled():
            return
        finished = handle.finished_at
        started = handle.started_at
        phases = []
        if routed_at is not None:
            phases.append(("queued", routed_at - handle.submitted_at))
            if started is not None:
                phases.append(("routed", started - routed_at))
        if started is not None and finished is not None:
            phases.append(("running", finished - started))
        reg = obs_metrics.registry()
        for phase, seconds in phases:
            if seconds < 0:
                continue
            reg.histogram(
                "waffle_door_job_phase_seconds",
                service=self.config.name, phase=phase,
            ).observe(seconds)

    # -- worker frames -------------------------------------------------

    def _read_loop(self, worker: _Worker) -> None:
        while True:
            try:
                data = worker.sock.recv(RECV_CHUNK)
            except OSError:
                data = b""
            if not data:
                self._worker_lost(worker, "socket closed")
                return
            self._beats.beat(worker.name)
            try:
                frames = worker.decoder.feed(data)
            except wire.WireError as exc:
                self._worker_lost(worker, f"protocol error: {exc}")
                return
            for ftype, obj in frames:
                self._on_frame(worker, ftype, obj)

    def _on_frame(self, worker: _Worker, ftype: wire.FrameType,
                  obj: Any) -> None:
        if ftype is wire.FrameType.STARTED:
            job_id = int(obj["job"])
            with self._lock:
                handle = worker.assigned.get(job_id)
                if handle is not None:
                    worker.started.add(job_id)
            if handle is not None:
                handle._mark_running()
        elif ftype is wire.FrameType.RESULT:
            self._on_result(worker, obj)
        elif ftype is wire.FrameType.ERROR:
            self._on_error(worker, obj)
        elif ftype is wire.FrameType.HEALTH:
            self._apply_health(worker, obj)
        elif ftype is wire.FrameType.CHECKPOINT:
            self._on_checkpoint(worker, obj)
        elif ftype is wire.FrameType.STATS:
            self._on_stats(worker, obj)
        elif ftype is wire.FrameType.INCIDENT:
            self._on_incident(worker, obj)
        elif ftype is wire.FrameType.PONG:
            with self._lock:
                worker.reported_outstanding = int(
                    obj.get("outstanding", 0)
                )
        # HELLO repeats and unknown-but-valid frames are ignored

    def _on_stats(self, worker: _Worker, obj: Any) -> None:
        """Federate one worker's periodic STATS frame: merge its
        metrics snapshot into the door registry under a ``worker=``
        label (one fleet-wide exposition) and keep its latest SLO
        windows for the stats payload / ``waffle_top`` fleet view."""
        if not isinstance(obj, dict):
            return
        slo = obj.get("slo")
        with self._lock:
            worker.stats_frames += 1
            worker.stats_at = time.time()
            if isinstance(slo, dict):
                worker.last_slo = slo
        metrics_snap = obj.get("metrics")
        if obs_metrics.metrics_enabled() and isinstance(metrics_snap, dict):
            obs_metrics.registry().merge_snapshot(
                metrics_snap, worker=worker.name
            )
        self._publish_stats()

    def _on_incident(self, worker: _Worker, obj: Any) -> None:
        """Aggregate a worker-side flight incident: re-ingest it into
        the door's recorder (fleet-level dedupe, ``WAFFLE_FLIGHT_DIR``
        dump with worker attribution) and record the event."""
        incident = obj.get("incident") if isinstance(obj, dict) else None
        if not isinstance(incident, dict):
            return
        with self._lock:
            worker.incidents += 1
        stored = obs_flight.ingest_remote(incident, worker=worker.name)
        events.record(
            "worker_incident", worker=worker.name,
            reason=incident.get("reason"),
            trace_id=incident.get("trace_id"),
            deduped=stored is None,
        )
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().counter(
                "waffle_door_worker_incidents_total",
                service=self.config.name, worker=worker.name,
            ).inc()
        self._publish_stats()

    def _on_checkpoint(self, worker: _Worker, obj: Any) -> None:
        """Store the worker's latest snapshot on the door-side handle
        (verbatim, never decoded) — the resume point migration and
        deadline persistence run on."""
        self._ingest_spans(worker, obj)
        try:
            job_id = int(obj["job"])
            data = obj["data"]
            size = int(obj.get("bytes", 0) or 0)
        except (KeyError, TypeError, ValueError):
            return  # malformed accounting frame: ignored, never fatal
        with self._lock:
            handle = worker.assigned.get(job_id)
            worker.ckpt_frames += 1
            worker.ckpt_bytes += size
        if handle is not None:
            handle._attach_checkpoint(data)
            if self._cache is not None:
                from waffle_con_tpu.serve import cache as serve_cache

                # bound-free snapshots double as the job's cache
                # deposit candidate — only those resume a read
                # superset exactly
                if serve_cache.resumable_wire(data):
                    handle._cache_ckpt = data
        if obs_metrics.metrics_enabled():
            reg = obs_metrics.registry()
            labels = {"service": self.config.name, "worker": worker.name}
            reg.counter("waffle_ckpt_snapshots_total", **labels).inc()
            reg.counter("waffle_ckpt_bytes_total", **labels).inc(size)

    def _take_assigned(self, worker: _Worker,
                       job_id: int) -> Optional[JobHandle]:
        with self._lock:
            worker.started.discard(job_id)
            return worker.assigned.pop(job_id, None)

    def _on_result(self, worker: _Worker, obj: Dict) -> None:
        self._ingest_spans(worker, obj)
        handle = self._take_assigned(worker, int(obj["job"]))
        if handle is None:
            return
        try:
            result = wire.decode_result(obj["kind"], obj["result"])
        except wire.WireError as exc:
            handle._finish(JobStatus.FAILED, exception=exc)
            self._trace_settle(handle, "failed")
            self._observe_phases(handle)
            return
        handle._finish(JobStatus.DONE, result=result)
        if (self._cache is not None
                and not getattr(handle, "_resumed_from_checkpoint", False)):
            try:
                # the RESULT frame already carries the wire JSON — the
                # deposit costs no re-encoding; the handle's latest
                # bound-free CHECKPOINT frame (if any) feeds the
                # superset tier
                self._cache.deposit_result(handle.request, obj["result"])
                ckpt = getattr(handle, "_cache_ckpt", None)
                if ckpt is not None:
                    self._cache.deposit_checkpoint(handle.request, ckpt)
            except Exception:  # noqa: BLE001 - cache never fails a job
                pass
        if handle.latency_s is not None:
            obs_slo.observe_job(handle.latency_s)
        self._trace_settle(handle, "done")
        self._observe_phases(handle)
        self._publish_worker_metrics(worker)
        self._publish_stats()

    def _on_error(self, worker: _Worker, obj: Dict) -> None:
        self._ingest_spans(worker, obj)
        handle = self._take_assigned(worker, int(obj["job"]))
        if handle is None:
            return
        kind = obj.get("kind", "failed")
        message = obj.get("message", "")
        if kind == "cancelled":
            handle._finish(
                JobStatus.CANCELLED, exception=JobCancelled(message)
            )
        elif kind == "expired":
            # deadline persistence: keep the final checkpoint on the
            # EXPIRED handle so the client can resubmit with a fresh
            # budget and lose nothing
            handle._attach_checkpoint(obj.get("checkpoint"))
            handle._finish(
                JobStatus.EXPIRED, exception=DeadlineExceeded(message)
            )
        else:
            handle._finish(
                JobStatus.FAILED,
                exception=RuntimeError(
                    f"worker {worker.name} failed job: "
                    f"{obj.get('type', 'Error')}: {message}"
                ),
            )
        self._trace_settle(handle, kind)
        self._observe_phases(handle)
        self._publish_stats()

    # -- health --------------------------------------------------------

    def _apply_health(self, worker: _Worker, obj: Dict) -> None:
        """A forwarded flight trigger from this worker's own recorder;
        attribution is the connection itself (no trace parsing)."""
        reason = obj.get("reason")
        if reason not in _HEALTH_REASONS:
            # unknown reasons are the forward-compat backstop for newer
            # workers — ignored for routing, but never silently: the
            # counter + event make a version-skewed fleet visible
            events.record(
                "door_health_ignored", worker=worker.name,
                reason=str(reason), trace_id=obj.get("trace"),
            )
            if obs_metrics.metrics_enabled():
                obs_metrics.registry().counter(
                    "waffle_door_health_ignored_total",
                    service=self.config.name, worker=worker.name,
                    reason=str(reason),
                ).inc()
            return
        with self._lock:
            if self._closed or worker.state == LOST:
                return
            if reason == "backend_demoted":
                worker.demotions += 1
                if worker.state != DRAINING:
                    worker.state = DRAINING
                    events.record(
                        "worker_draining", worker=worker.name,
                        trigger=reason, trace_id=obj.get("trace"),
                    )
            else:  # slow_search
                worker.sheds += 1
                if worker.state == UP:
                    worker.state = SHEDDING
                worker.shed_until = (
                    time.monotonic() + self.config.shed_cooldown_s
                )
                events.record(
                    "worker_shedding", worker=worker.name,
                    trigger=reason, trace_id=obj.get("trace"),
                )
        self._publish_worker_metrics(worker)

    def _maintain(self) -> None:
        """Lazy health maintenance at each routing decision: re-admit
        drained workers, expire shed cooldowns."""
        now = time.monotonic()
        readmitted = []
        with self._lock:
            for worker in self._workers:
                if worker.state == DRAINING and not worker.assigned:
                    worker.state = UP
                    worker.readmits += 1
                    readmitted.append(worker)
                elif worker.state == SHEDDING and now >= worker.shed_until:
                    worker.state = UP
        for worker in readmitted:
            events.record("worker_readmitted", worker=worker.name)
            self._publish_worker_metrics(worker)

    # -- liveness ------------------------------------------------------

    def _watch_loop(self) -> None:
        while True:
            time.sleep(ping_interval_s())
            with self._lock:
                if self._closed:
                    return
                workers = [w for w in self._workers if w.state != LOST]
            lapse = liveness_lapse_s()
            for worker in workers:
                rc = None
                if worker.proc is not None and hasattr(worker.proc, "poll"):
                    rc = worker.proc.poll()
                if rc is not None:
                    self._worker_lost(
                        worker, f"process exited with code {rc}"
                    )
                    continue
                age = self._beats.age(worker.name)
                if age is not None and age > lapse:
                    self._worker_lost(
                        worker, f"no frames for {age:.1f}s "
                        f"(liveness lapse {lapse:.1f}s)"
                    )
                    continue
                self._send(worker, wire.FrameType.PING, {})

    def _worker_lost(self, worker: _Worker, why: str) -> None:
        """Idempotently transition one worker to LOST: requeue its
        not-yet-started jobs, **migrate** its started jobs that have a
        checkpoint (the next dispatch carries the resume point, so the
        search continues from its last pop boundary), restart the
        checkpoint-less rest from scratch with ``restart_lost`` or fail
        them with :class:`WorkerLost`, and fire exactly one
        ``worker_lost`` flight trigger."""
        with self._lock:
            if self._closed or worker.state == LOST:
                return
            worker.state = LOST
            assigned = dict(worker.assigned)
            started = set(worker.started)
            worker.assigned.clear()
            worker.started.clear()
        if worker.sock is not None:
            try:
                worker.sock.close()
            except OSError:
                pass
        self._beats.forget(worker.name)
        events.record(
            "worker_lost", worker=worker.name, why=why,
            jobs=len(assigned),
        )
        obs_flight.trigger(
            "worker_lost", trace_id=worker.name, why=why,
            service=self.config.name, jobs_assigned=len(assigned),
        )
        requeued = 0
        migrated = 0
        restarted = 0
        migrated_jobs: List[int] = []
        wasted_s = 0.0
        migrate = migrate_enabled()
        now = time.monotonic()
        for job_id, handle in sorted(assigned.items()):
            if handle.done():
                continue
            is_migration = (
                job_id in started and migrate
                and handle.checkpoint is not None
            )
            if job_id not in started or is_migration or \
                    self.config.restart_lost:
                with self._lock:
                    worker.requeues += 1
                    if is_migration:
                        worker.migrations += 1
                    elif job_id in started:
                        worker.restarts += 1
                    self._retry.append(handle)
                requeued += 1
                if is_migration:
                    migrated += 1
                    migrated_jobs.append(job_id)
                    # work since the last snapshot is the only loss;
                    # everything before it resumes on the next worker
                    at = handle.checkpoint_at
                    if at is not None:
                        wasted_s += max(0.0, now - at)
                elif job_id in started:
                    restarted += 1
                    # a restart forfeits the whole run so far; drop any
                    # stale checkpoint so the re-dispatch is truly
                    # from-scratch (WAFFLE_CKPT_MIGRATE=0 semantics)
                    handle._drop_checkpoint()
                    if handle.started_at is not None:
                        wasted_s += max(0.0, now - handle.started_at)
            else:
                handle._finish(
                    JobStatus.FAILED,
                    exception=WorkerLost(
                        f"worker {worker.name} lost ({why}) while "
                        f"running job {job_id}"
                    ),
                )
        if migrated or restarted:
            events.record(
                "worker_jobs_rescued", worker=worker.name,
                migrated=migrated, restarted=restarted,
                migrated_jobs=migrated_jobs,
                wasted_s=round(wasted_s, 6),
            )
        if obs_metrics.metrics_enabled():
            reg = obs_metrics.registry()
            labels = {"service": self.config.name, "worker": worker.name}
            reg.counter("waffle_worker_lost_total", **labels).inc()
            reg.counter(
                "waffle_worker_requeued_total", **labels
            ).inc(requeued)
            if migrated:
                reg.counter(
                    "waffle_ckpt_migrations_total", **labels
                ).inc(migrated)
        self._publish_worker_metrics(worker)
        self._publish_stats()

    # -- observability -------------------------------------------------

    def _publish_worker_metrics(self, worker: _Worker) -> None:
        if not obs_metrics.metrics_enabled():
            return
        reg = obs_metrics.registry()
        labels = {"service": self.config.name, "worker": worker.name}
        with self._lock:
            outstanding = len(worker.assigned)
            state = worker.state
            routed = worker.routed
            demotions = worker.demotions
            sheds = worker.sheds
        reg.gauge("waffle_worker_outstanding", **labels).set(outstanding)
        reg.gauge("waffle_worker_healthy", **labels).set(
            1 if state == UP else 0
        )
        reg.gauge("waffle_worker_routed", **labels).set(routed)
        reg.gauge("waffle_worker_demotions", **labels).set(demotions)
        reg.gauge("waffle_worker_sheds", **labels).set(sheds)

    def worker_stats(self) -> List[Dict]:
        """Per-worker snapshot (the ``workers`` table in stats payloads
        and storm evidence)."""
        out = []
        with self._lock:
            for worker in self._workers:
                outstanding = len(worker.assigned)
                out.append({
                    "worker": worker.name,
                    "pid": worker.pid,
                    "state": worker.state,
                    "outstanding": outstanding,
                    "jobs": sorted(worker.assigned),
                    "slots": worker.slots,
                    "occupancy": (outstanding / worker.slots
                                  if worker.slots else 0.0),
                    "routed": worker.routed,
                    "requeues": worker.requeues,
                    "migrations": worker.migrations,
                    "restarts": worker.restarts,
                    "ckpt_frames": worker.ckpt_frames,
                    "ckpt_bytes": worker.ckpt_bytes,
                    "demotions": worker.demotions,
                    "sheds": worker.sheds,
                    "readmits": worker.readmits,
                    "stats_frames": worker.stats_frames,
                    "stats_at": worker.stats_at,
                    "incidents": worker.incidents,
                    "span_events": worker.span_events,
                    "dispatch_p95_s": (
                        (worker.last_slo.get("dispatch") or {}).get("p95_s")
                        if isinstance(worker.last_slo, dict) else None
                    ),
                })
        return out

    def stats(self) -> Dict:
        """Aggregated counters plus the per-worker table."""
        with self._lock:
            # fold terminal handles into the cumulative counts, then
            # drop them so the jobs dict stays bounded (trace/phase
            # state for jobs that settled off the happy path — orphans,
            # worker-lost failures — is purged alongside)
            for job_id in [j for j, h in self._jobs.items() if h.done()]:
                self._counts[self._jobs.pop(job_id).status.value] += 1
                self._trace_jobs.pop(job_id, None)
                self._routed_at.pop(job_id, None)
            counts = dict(self._counts)
        workers = self.worker_stats()
        out = {
            "jobs": counts,
            "queue_depth": self._queue.depth(),
            "aged_pops": self._queue.aged_pops,
            "workers": workers,
            "checkpoints": {
                "frames": sum(w["ckpt_frames"] for w in workers),
                "bytes": sum(w["ckpt_bytes"] for w in workers),
                "migrations": sum(w["migrations"] for w in workers),
                "restarts": sum(w["restarts"] for w in workers),
            },
            "fleet": {
                "stats_frames": sum(w["stats_frames"] for w in workers),
                "incidents_forwarded": sum(
                    w["incidents"] for w in workers
                ),
                "span_events": sum(w["span_events"] for w in workers),
            },
        }
        if self._cache is not None:
            out["cache"] = self._cache.stats()
        return out

    def _publish_stats(self, force: bool = False) -> None:
        """Front-door-owned ``WAFFLE_STATS_FILE`` publication (same
        throttle + atomic-rename contract as the replica door; the
        payload gains a top-level ``workers`` table)."""
        path = envspec.get_raw("WAFFLE_STATS_FILE", "")
        if not path:
            return
        now = time.monotonic()
        with self._lock:
            if not force and now - self._stats_published_at < 0.25:
                return
            self._stats_published_at = now
        stats = self.stats()
        payload = {
            "service": self.config.name,
            "unix_time": time.time(),
            "stats": stats,
            "workers": stats["workers"],
            "fleet": stats["fleet"],
            "slo": obs_slo.snapshot(),
            "incidents": [
                {k: i.get(k) for k in
                 ("seq", "reason", "trace_id", "unix_time", "path")}
                for i in obs_flight.incidents()[-8:]
            ],
        }
        if obs_metrics.metrics_enabled():
            payload["metrics"] = obs_metrics.registry().snapshot()
        try:
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, default=repr)
            os.replace(tmp, path)
        except OSError:  # a broken stats sink must never fail a job
            pass
