"""Out-of-process serving: a front-door process plus N worker
processes speaking a thin length-prefixed socket protocol.

PR 9's :class:`~waffle_con_tpu.serve.replicas.ReplicatedService`
proved the routing/drain/shed shape with N in-process replicas, but
those replicas share one GIL and one device pool.  This package
promotes the same seam to real processes:

* :mod:`~waffle_con_tpu.serve.procs.wire` — the frame codec: version
  byte + checksum on every frame, JSON payloads (no pickle on the
  wire path), typed decode errors.
* :mod:`~waffle_con_tpu.serve.procs.worker` — the worker process
  entrypoint (``python -m waffle_con_tpu.serve.procs.worker``): one
  :class:`~waffle_con_tpu.serve.service.ConsensusService` per process
  with its own dispatcher, ragged arena, worker pool, and device
  slice, forwarding its flight-recorder triggers over the socket.
* :mod:`~waffle_con_tpu.serve.procs.door` — the front door: owns
  admission, anti-starvation aging, and placement; routes to the
  least-loaded healthy worker; demotes/sheds workers from their
  forwarded trigger stream; migrates or requeues a lost worker's jobs.

Crash/migration boundary: a drained or crashed worker's
not-yet-started jobs are requeued verbatim; jobs that had already
*started* **migrate** — workers stream every search checkpoint
(periodic ``WAFFLE_CKPT_INTERVAL_S`` cadence, deadline lapse, drain)
back as ``CHECKPOINT`` frames, and the door re-dispatches a lost
worker's started jobs with their latest checkpoints so each search
resumes at its last pop boundary on a healthy worker, byte-identical
to the uninterrupted run (the checkpoint format rides the engines'
node-identity invariant, see :mod:`waffle_con_tpu.models.checkpoint`).
A started job with no checkpoint yet (or ``WAFFLE_CKPT_MIGRATE=0``)
falls back to a from-scratch restart under
``ProcConfig.restart_lost`` — deterministic engines make that
byte-identical too, only the partial progress is lost; with
``restart_lost=False`` it fails with the typed
:class:`~waffle_con_tpu.runtime.liveness.WorkerLost`.  A corrupt or
version-skewed checkpoint never fails or hangs the job either: the
worker's service rejects it with a ``checkpoint_rejected`` flight
incident and runs the search from scratch.
"""

from waffle_con_tpu.serve.procs.door import ProcConfig, ProcFrontDoor
from waffle_con_tpu.serve.procs.wire import (
    BadChecksum,
    FrameDecoder,
    FrameTooLarge,
    FrameType,
    UnknownFrameType,
    UnsupportedVersion,
    WireError,
    encode_frame,
)

__all__ = [
    "BadChecksum",
    "FrameDecoder",
    "FrameTooLarge",
    "FrameType",
    "ProcConfig",
    "ProcFrontDoor",
    "UnknownFrameType",
    "UnsupportedVersion",
    "WireError",
    "encode_frame",
]
