"""Out-of-process serving: a front-door process plus N worker
processes speaking a thin length-prefixed socket protocol.

PR 9's :class:`~waffle_con_tpu.serve.replicas.ReplicatedService`
proved the routing/drain/shed shape with N in-process replicas, but
those replicas share one GIL and one device pool.  This package
promotes the same seam to real processes:

* :mod:`~waffle_con_tpu.serve.procs.wire` — the frame codec: version
  byte + checksum on every frame, JSON payloads (no pickle on the
  wire path), typed decode errors.
* :mod:`~waffle_con_tpu.serve.procs.worker` — the worker process
  entrypoint (``python -m waffle_con_tpu.serve.procs.worker``): one
  :class:`~waffle_con_tpu.serve.service.ConsensusService` per process
  with its own dispatcher, ragged arena, worker pool, and device
  slice, forwarding its flight-recorder triggers over the socket.
* :mod:`~waffle_con_tpu.serve.procs.door` — the front door: owns
  admission, anti-starvation aging, and placement; routes to the
  least-loaded healthy worker; demotes/sheds workers from their
  forwarded trigger stream; requeues a lost worker's jobs.

Crash/requeue boundary: a drained or crashed worker's not-yet-started
jobs are requeued verbatim; jobs that had already *started* on a
crashed worker are restarted from scratch on a healthy worker when
``ProcConfig.restart_lost`` is on (engines are deterministic, so the
result is byte-identical — only the partial progress is lost).  Full
mid-search state migration stays ROADMAP item 2.
"""

from waffle_con_tpu.serve.procs.door import ProcConfig, ProcFrontDoor
from waffle_con_tpu.serve.procs.wire import (
    BadChecksum,
    FrameDecoder,
    FrameTooLarge,
    FrameType,
    UnknownFrameType,
    UnsupportedVersion,
    WireError,
    encode_frame,
)

__all__ = [
    "BadChecksum",
    "FrameDecoder",
    "FrameTooLarge",
    "FrameType",
    "ProcConfig",
    "ProcFrontDoor",
    "UnknownFrameType",
    "UnsupportedVersion",
    "WireError",
    "encode_frame",
]
