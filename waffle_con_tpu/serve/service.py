"""The multi-tenant consensus service.

:class:`ConsensusService` accepts independent consensus jobs
(:class:`~waffle_con_tpu.serve.job.JobRequest`), admits them through a
bounded priority queue (reject-on-full backpressure), runs them on a
worker pool, and coalesces the concurrent jobs' scorer dispatches via
the shared :class:`~waffle_con_tpu.serve.dispatcher.BatchingDispatcher`.

The engines are untouched: each worker installs a thread-local scorer
decorator (``ops.scorer.set_scorer_decorator``) for the duration of its
job, so every scorer the engine builds — supervised or not — is wrapped
in a :class:`~waffle_con_tpu.serve.dispatcher.CoalescingScorer` routing
dispatches into the shared dispatcher with the job's handle as abort
ticket.  Fault tolerance composes for free: a job whose config asks for
supervision (``supervised``/``backend_chain``) gets its supervisor
*inside* the coalescing proxy, so retries, demotions and the circuit
breaker all happen within one routed dispatch.

Lifecycle: ``submit`` → QUEUED → (worker pop, deadline/cancel check) →
RUNNING → DONE / FAILED / CANCELLED / EXPIRED.  ``close()`` drains
gracefully by default (runs everything already admitted) or sheds the
queue with ``cancel_pending=True``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

from waffle_con_tpu.obs import audit as obs_audit
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import phases as obs_phases
from waffle_con_tpu.obs import slo as obs_slo
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.ops import ragged as ops_ragged
from waffle_con_tpu.runtime import events
from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec
from waffle_con_tpu.runtime.watchdog import DeadlineExceeded
from waffle_con_tpu.serve.dispatcher import BatchingDispatcher, CoalescingScorer
from waffle_con_tpu.serve import placement as serve_placement
from waffle_con_tpu.serve.job import (
    JobCancelled,
    JobHandle,
    JobRequest,
    JobStatus,
    ServiceClosed,
    ServiceOverloaded,
)
from waffle_con_tpu.serve.scheduler import AdmissionQueue, WorkerPool


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Service knobs.

    * ``workers`` — concurrent jobs in flight (also the natural upper
      bound on batch occupancy).
    * ``queue_limit`` — bounded admission queue; the (queue_limit+1)-th
      concurrent submit gets :class:`ServiceOverloaded`.
    * ``batch_window_s`` — how long the first dispatch of a batch waits
      for concurrent company before executing (0 disables coalescing).
    * ``max_batch`` — batch-size wait target for the window.
    * ``adaptive_window`` — arrival-rate-predictive hold inside the
      window cap (see :class:`BatchingDispatcher`); off = fixed window.
    * ``aging_s`` — admission anti-starvation: the oldest queued job
      pops regardless of priority class after waiting this long
      (``None`` = strict priority).
    * ``placement`` — optional
      :class:`~waffle_con_tpu.serve.placement.PlacementPolicy` routing
      large admitted jobs through a mesh-sharded scorer.
    """

    workers: int = 4
    queue_limit: int = 64
    batch_window_s: float = 0.002
    max_batch: int = 8
    name: str = "consensus"
    adaptive_window: bool = True
    aging_s: Optional[float] = 0.5
    placement: Optional[object] = None

    def __post_init__(self) -> None:
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        if self.queue_limit < 1:
            raise ValueError("queue_limit must be >= 1")
        if self.batch_window_s < 0:
            raise ValueError("batch_window_s must be >= 0")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.aging_s is not None and self.aging_s <= 0:
            raise ValueError("aging_s must be > 0 (or None)")


def _build_engine(request: JobRequest):
    """Instantiate the engine for one job (mirrors ``bench._make_engine``
    plus offset seeding).  Imports are local to keep ``serve`` importable
    without pulling the model stack in at module-import time."""
    from waffle_con_tpu.config import CdwfaConfig
    from waffle_con_tpu.models.consensus import ConsensusDWFA
    from waffle_con_tpu.models.dual_consensus import DualConsensusDWFA
    from waffle_con_tpu.models.priority_consensus import PriorityConsensusDWFA

    config = request.config if request.config is not None else CdwfaConfig()
    if request.kind == "priority":
        engine = PriorityConsensusDWFA(config)
        for chain in request.reads:
            engine.add_sequence_chain(list(chain))
        return engine
    cls = ConsensusDWFA if request.kind == "single" else DualConsensusDWFA
    engine = cls(config)
    offsets = request.offsets or (None,) * len(request.reads)
    for read, offset in zip(request.reads, offsets):
        engine.add_sequence_offset(read, offset)
    return engine


class ConsensusService:
    """Accepts, schedules, and batch-serves consensus jobs.

    Usage::

        with ConsensusService(ServeConfig(workers=4)) as svc:
            handles = [svc.submit(req) for req in requests]
            results = [h.result() for h in handles]

    ``autostart=False`` builds the service with workers and dispatcher
    parked (tests use this to exercise admission-queue semantics with
    zero timing dependence); call :meth:`start` to begin serving.
    """

    def __init__(
        self,
        config: Optional[ServeConfig] = None,
        autostart: bool = True,
        device_set=None,
        arena=None,
        publish_stats: bool = True,
    ) -> None:
        """``device_set`` pins this service's workers to one
        :class:`~waffle_con_tpu.parallel.mesh.DeviceSet` (mesh-promoted
        jobs shard onto that slice); ``arena`` pins ragged ganging to
        one replica's band arena; ``publish_stats=False`` lets a
        replicated front door own the ``WAFFLE_STATS_FILE`` output
        instead of N replicas clobbering each other's writes."""
        self.config = config if config is not None else ServeConfig()
        self._device_set = device_set
        self._arena = arena
        self._publish = publish_stats
        self._queue = AdmissionQueue(
            self.config.queue_limit, name=self.config.name,
            aging_s=self.config.aging_s,
        )
        self._dispatcher = BatchingDispatcher(
            window_s=self.config.batch_window_s,
            max_batch=self.config.max_batch,
            name=self.config.name,
            adaptive_window=self.config.adaptive_window,
            arena=arena,
        )
        self._pool = WorkerPool(
            self.config.workers, self._queue, self._run_job,
            name=self.config.name,
        )
        self._lock = lockcheck.make_lock("serve.service.ConsensusService")
        self._next_id = 0
        self._closed = False
        self._handles: List[JobHandle] = []
        #: job_id -> live CheckpointController (running jobs only);
        #: request_checkpoints() fans a snapshot request out over it
        self._controllers: Dict[int, object] = {}
        self._counts = {
            "submitted": 0, "rejected": 0, "done": 0, "failed": 0,
            "cancelled": 0, "expired": 0, "mesh_placed": 0,
            "cached": 0, "certified": 0,
        }
        self._ckpt_counts = {
            "snapshots": 0, "bytes": 0, "resumed": 0, "rejected": 0,
        }
        from waffle_con_tpu.serve import cache as serve_cache

        #: content-addressed consensus cache, or None when WAFFLE_CACHE
        #: is off (the default) — see waffle_con_tpu/serve/cache/
        self._cache = serve_cache.ConsensusCache.from_env(self.config.name)
        if autostart:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._dispatcher.start()
        self._pool.start()

    def close(
        self, cancel_pending: bool = False, timeout: Optional[float] = None
    ) -> None:
        """Shut down.  Default drains gracefully: everything already
        admitted runs to completion first.  ``cancel_pending=True``
        finalizes still-queued jobs as CANCELLED instead."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            handles = list(self._handles)
        if cancel_pending:
            for handle in self._queue.drain():
                handle.cancel()
        if self._pool.started:
            for handle in handles:
                handle.wait(timeout)
        self._pool.stop(wait=True)
        # any job still queued when the pool stopped (never-started
        # service, or drain raced a worker) must not hang its client
        for handle in self._queue.drain():
            handle._finish(
                JobStatus.CANCELLED,
                exception=ServiceClosed("service closed before job ran"),
            )
        self._dispatcher.close()

    def __enter__(self) -> "ConsensusService":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()

    # -- client API ----------------------------------------------------

    def submit(self, request: JobRequest,
               checkpoint=None, trace=None) -> JobHandle:
        """Admit one job; raises :class:`ServiceOverloaded` when the
        bounded queue is full and :class:`ServiceClosed` after close.

        ``checkpoint`` optionally resumes a previously snapshotted
        search (a wire dict from :attr:`JobHandle.checkpoint`): the
        worker picks the search up at the recorded queue state instead
        of restarting from scratch.  A corrupt, version-skewed, or
        mismatched checkpoint never fails the job — it degrades to a
        fresh search with a ``checkpoint_rejected`` flight incident.

        ``trace`` optionally replaces the handle's auto-minted
        :class:`~waffle_con_tpu.obs.trace.TraceContext` — the proc
        worker adopts the door's context here so its spans join the
        door's per-job trace tree.  It must be installed before the
        queue put: a pool worker may pick the handle up (and capture
        ``handle.trace``) the moment it is queued.
        """
        if not isinstance(request, JobRequest):
            raise TypeError(
                f"expected JobRequest, got {type(request).__name__}"
            )
        request = self._place(request)
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is closed to new jobs")
            handle = JobHandle(
                self._next_id, request, service=self.config.name
            )
            if trace is not None:
                handle.trace = trace
            self._next_id += 1
        if checkpoint is not None:
            handle._attach_checkpoint(checkpoint)
        elif self._cache is not None:
            # content-addressed cache: an exact (or certified) hit is
            # finalized here without ever touching the admission queue;
            # a checkpoint-superset hit rides the normal path but
            # resumes from the cached frontier instead of scratch
            from waffle_con_tpu.serve import cache as serve_cache

            hit = self._cache.lookup(
                request, trace_id=handle.trace.trace_id
            )
            if isinstance(hit, serve_cache.CacheHit):
                status = (
                    JobStatus.CACHED if hit.tier == "exact"
                    else JobStatus.CERTIFIED
                )
                handle._finish(status, result=hit.result)
                with self._lock:
                    self._counts["submitted"] += 1
                    self._handles.append(handle)
                self._account(handle, status.value)
                return handle
            if isinstance(hit, serve_cache.CheckpointHit):
                handle._attach_checkpoint(hit.checkpoint)
                handle._from_cache_checkpoint = True
        try:
            self._queue.put(handle)
        except ServiceOverloaded:
            with self._lock:
                self._counts["rejected"] += 1
            events.record(
                "serve_overloaded", job_kind=request.kind,
                queue_limit=self.config.queue_limit,
            )
            # one incident per process for the whole storm (dedupe on
            # reason), carrying the first rejected job's identity
            obs_flight.trigger(
                "service_overloaded",
                rejected_trace_id=handle.trace.trace_id,
                job_kind=request.kind,
                queue_limit=self.config.queue_limit,
                queue_depth=self._queue.depth(),
            )
            raise
        with self._lock:
            self._counts["submitted"] += 1
            self._handles.append(handle)
        return handle

    def submit_all(self, requests: Sequence[JobRequest]) -> List[JobHandle]:
        return [self.submit(r) for r in requests]

    def _place(self, request: JobRequest) -> JobRequest:
        """Apply the configured placement policy at admission: large
        jax-backed jobs get ``mesh_shards`` rewritten into their config
        so backend construction shards them onto the mesh (clamped to
        this service's device set / the cached probe).  Any placement
        failure leaves the job on the arena path — placement is an
        optimization, never a reason to reject work."""
        policy = self.config.placement
        if policy is None:
            return request
        try:
            from waffle_con_tpu.parallel import mesh as par_mesh

            available = (
                len(self._device_set) if self._device_set is not None
                else par_mesh.probe_device_count()
            )
            placed = policy.place(request, available)
        except Exception:  # noqa: BLE001 - jax-less stack, probe failure
            return request
        if placed is None:
            return request
        with self._lock:
            self._counts["mesh_placed"] += 1
        events.record(
            "job_placed_mesh", job_kind=request.kind,
            reads=len(request.reads),
            shards=placed.config.mesh_shards,
            service=self.config.name,
        )
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().counter(
                "waffle_serve_mesh_placed_total",
                service=self.config.name,
            ).inc()
        return placed

    def outstanding(self) -> int:
        """Admitted-but-unfinished job count (queued + running) — the
        replicated front door's least-outstanding routing signal."""
        with self._lock:
            counts = dict(self._counts)
        finished = (counts["done"] + counts["failed"]
                    + counts["cancelled"] + counts["expired"]
                    + counts["cached"] + counts["certified"])
        return max(0, counts["submitted"] - finished)

    # -- worker --------------------------------------------------------

    def _run_job(self, handle: JobHandle) -> None:
        from waffle_con_tpu.ops.scorer import set_scorer_decorator

        if not handle._mark_running():
            # cancelled while queued: finalized by cancel() already,
            # account it now that its heap entry has been consumed
            self._account(handle, "cancelled")
            return
        # activate the job's trace context for everything the worker
        # does on its behalf — spans land under the job's Chrome pid and
        # the flight recorder can attribute records even with tracing
        # off (always-on, one thread-local assignment)
        prev_ctx = obs_trace.set_current_context(handle.trace)
        obs_flight.record(
            "job_start", trace_id=handle.trace.trace_id,
            job_kind=handle.request.kind, job_id=handle.job_id,
            queued_s=round(
                time.monotonic() - handle.submitted_at, 6
            ),
        )
        try:
            handle.check_abort()  # deadline may already have lapsed
        except BaseException as exc:
            self._finalize(handle, exc)
            obs_trace.set_current_context(prev_ctx)
            return
        self._dispatcher.job_started()
        dispatcher, ticket = self._dispatcher, handle
        previous = set_scorer_decorator(
            lambda scorer: CoalescingScorer(scorer, dispatcher, ticket)
        )
        profile = serve_placement.learned_enabled()
        phases_before = obs_phases.totals() if (
            profile and obs_phases.profiling_enabled()
        ) else None
        job_t0 = time.monotonic()
        from waffle_con_tpu.models import checkpoint as ckpt_mod

        ctrl = ckpt_mod.CheckpointController(
            interval_s=envspec.get_float("WAFFLE_CKPT_INTERVAL_S", 30.0),
            max_bytes=envspec.get_int(
                "WAFFLE_CKPT_MAX_BYTES", 8 * 1024 * 1024, lo=0
            ),
            deadline=handle.deadline,
            on_snapshot=lambda ckpt: self._deliver_checkpoint(handle, ckpt),
            label=f"job {handle.job_id}",
        )
        with self._lock:
            self._controllers[handle.job_id] = ctrl
        try:
            with obs_trace.span(
                "serve:job", "serve",
                kind=handle.request.kind, job_id=handle.job_id,
            ):
                # serve scope: scorers built for this job floor their
                # geometry to the ragged arena's pool shapes, making
                # them gang-eligible (see ops.ragged.geometry_hint).
                # The device-set scope pins any mesh-promoted scorer
                # this job builds onto the service's device slice.
                with self._device_scope(), ops_ragged.serve_scope():
                    engine = self._make_engine(handle)
                    try:
                        with ckpt_mod.installed(ctrl):
                            result = engine.consensus()
                    except ckpt_mod.CheckpointRejected as exc:
                        # the engines defer checkpoint-body validation
                        # until the restore state is consumed inside
                        # consensus(); degrade exactly like a
                        # construction-time rejection — restart from
                        # scratch, never fail the job
                        self._record_ckpt_rejection(handle, exc)
                        with self._lock:
                            # it never actually resumed
                            self._ckpt_counts["resumed"] -= 1
                        handle._drop_checkpoint()
                        engine = _build_engine(handle.request)
                        with ckpt_mod.installed(ctrl):
                            result = engine.consensus()
        except BaseException as exc:
            self._finalize(handle, exc)
        else:
            handle._finish(
                JobStatus.DONE, result=result,
                report=getattr(engine, "last_search_report", None),
            )
            self._account(handle, "done")
            self._deposit(handle, result)
            if profile:
                self._record_placement_outcome(
                    handle, time.monotonic() - job_t0, phases_before
                )
        finally:
            with self._lock:
                self._controllers.pop(handle.job_id, None)
            set_scorer_decorator(previous)
            # page-table residency ends with the job: whatever scorers
            # it admitted into the band-state arena free their pages now
            # (arena-scoped — job ids are per-service counters and
            # collide across replicas)
            try:
                ops_ragged.release_job(handle.job_id, arena=self._arena)
            except Exception:  # pragma: no cover - never block teardown
                pass
            self._dispatcher.job_finished()
            obs_trace.set_current_context(prev_ctx)

    def _deposit(self, handle: JobHandle, result) -> None:
        """Feed a finished job back into the consensus cache: its wire
        result under the canonical key, plus its last *bound-free*
        mid-search checkpoint for superset resume (a bound-tightened
        snapshot prunes with subset-only costs and must never seed a
        superset search).  Jobs that themselves resumed from a
        checkpoint never deposit (their search did not cover the full
        space from scratch — fail-closed for parity).  Cache IO never
        fails a job."""
        if self._cache is None:
            return
        if getattr(handle, "_resumed_from_checkpoint", False):
            return
        try:
            from waffle_con_tpu.serve.procs import wire

            self._cache.deposit_result(
                handle.request,
                wire.encode_result(handle.request.kind, result),
            )
            last = getattr(handle, "_cache_ckpt", None)
            if last is not None:
                self._cache.deposit_checkpoint(handle.request, last)
        except Exception:  # noqa: BLE001 - cache must never fail a job
            pass

    def _make_engine(self, handle: JobHandle):
        """Build the job's engine — resuming from the handle's attached
        checkpoint when one is present (migration / incremental-read
        path).  A rejected checkpoint degrades to a fresh search with a
        ``checkpoint_rejected`` flight incident; it never fails or
        hangs the job."""
        from waffle_con_tpu.models import checkpoint as ckpt_mod

        wire_ckpt = handle.checkpoint
        if wire_ckpt is not None:
            try:
                checkpoint = ckpt_mod.SearchCheckpoint.from_wire(wire_ckpt)
                if checkpoint.kind != handle.request.kind:
                    raise ckpt_mod.CheckpointRejected(
                        f"{handle.request.kind} job cannot resume a "
                        f"{checkpoint.kind!r} checkpoint"
                    )
                extras = self._checkpoint_extras(handle.request, checkpoint)
                engine = ckpt_mod.resume_engine(
                    checkpoint, extra_reads=extras
                )
            except ckpt_mod.CheckpointRejected as exc:
                self._record_ckpt_rejection(handle, exc)
            else:
                handle._resumed_from_checkpoint = True
                with self._lock:
                    self._ckpt_counts["resumed"] += 1
                events.record(
                    "job_resumed", job_id=handle.job_id,
                    job_kind=handle.request.kind,
                    service=self.config.name,
                    extra_reads=len(extras),
                )
                return engine
        return _build_engine(handle.request)

    @staticmethod
    def _checkpoint_extras(request: JobRequest, checkpoint) -> tuple:
        """The request reads missing from a checkpoint's read multiset
        (the incremental/superset resume seam): the engine restores the
        recorded frontier and joins these at offset 0.  Empty when the
        multisets match (plain resume) or whenever the overlap cannot
        be established — never a reason to reject the checkpoint."""
        if request.kind != "single" or request.offsets is not None:
            return ()
        try:
            from waffle_con_tpu.models import checkpoint as ckpt_mod
            from waffle_con_tpu.serve.cache import keys as cache_keys

            body_reads = [
                ckpt_mod.unb64(r) for r in checkpoint.body["reads"]
            ]
            extras = cache_keys.multiset_extras(request.reads, body_reads)
        except Exception:  # noqa: BLE001 - malformed body: plain resume
            return ()
        return extras or ()

    def _record_ckpt_rejection(self, handle: JobHandle, exc) -> None:
        """Account one rejected checkpoint (counter, event log, typed
        flight incident, metric) — shared by the construction-time and
        deferred (mid-``consensus()``) degrade paths."""
        with self._lock:
            self._ckpt_counts["rejected"] += 1
        events.record(
            "checkpoint_rejected", job_id=handle.job_id,
            service=self.config.name, why=str(exc),
        )
        obs_flight.trigger(
            "checkpoint_rejected",
            trace_id=handle.trace.trace_id,
            job_id=handle.job_id, job_kind=handle.request.kind,
            service=self.config.name, why=str(exc),
        )
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().counter(
                "waffle_ckpt_rejected_total",
                service=self.config.name,
            ).inc()

    def _deliver_checkpoint(self, handle: JobHandle, checkpoint) -> None:
        """Controller snapshot hook: attach the wire form to the handle
        (which forwards it to any ``on_checkpoint`` sink) and count.
        Bound-free snapshots are also remembered as the job's cache
        deposit candidate — only those resume a read superset exactly
        (see :func:`waffle_con_tpu.serve.cache.resumable_wire`)."""
        size = checkpoint.byte_size()
        wire_ckpt = checkpoint.to_wire()
        handle._attach_checkpoint(wire_ckpt)
        if self._cache is not None:
            from waffle_con_tpu.serve import cache as serve_cache

            if serve_cache.resumable_wire(wire_ckpt):
                handle._cache_ckpt = wire_ckpt
        with self._lock:
            self._ckpt_counts["snapshots"] += 1
            self._ckpt_counts["bytes"] += size
        obs_flight.record(
            "job_checkpoint", trace_id=handle.trace.trace_id,
            job_id=handle.job_id, bytes=size,
        )
        if obs_metrics.metrics_enabled():
            reg = obs_metrics.registry()
            reg.counter(
                "waffle_ckpt_snapshots_total", service=self.config.name
            ).inc()
            reg.counter(
                "waffle_ckpt_bytes_total", service=self.config.name
            ).inc(size)

    def request_checkpoints(self, preempt: bool = False) -> int:
        """Ask every running job to snapshot at its next pop boundary
        (the drain / pre-migration path); with ``preempt`` the searches
        also stop there.  Returns how many jobs were signalled."""
        with self._lock:
            controllers = list(self._controllers.values())
        for ctrl in controllers:
            ctrl.request_snapshot(preempt=preempt)
        return len(controllers)

    def _record_placement_outcome(self, handle: JobHandle, wall_s: float,
                                  phases_before) -> None:
        """Append one placement-profile perfdb record for a finished
        job (``WAFFLE_PLACEMENT_LEARNED`` only — the flag gates both
        the learning write and the learned read, so default runs never
        dirty the checked-in history).  Substrate is what admission
        actually chose: mesh iff ``_place`` rewrote ``mesh_shards``
        into the job's config.  With phase profiling on, the process
        phase-totals delta across the job rides along (concurrent jobs
        blur it; the rolling medians absorb the noise)."""
        config = handle.request.config
        substrate = (
            "mesh" if getattr(config, "mesh_shards", 0) >= 2 else "arena"
        )
        phases = None
        if phases_before is not None:
            after = obs_phases.totals()
            phases = {
                k: max(0.0, after.get(k, 0.0) - phases_before.get(k, 0.0))
                for k in ("host_prep", "device_compute", "transfer")
            }
        try:
            serve_placement.record_outcome(
                substrate, len(handle.request.reads), wall_s,
                phases=phases,
            )
        except Exception:  # pragma: no cover - profile IO never fails jobs
            pass

    def _device_scope(self):
        """Context pinning this worker thread to the service's device
        set (a no-op when the service owns the whole topology)."""
        if self._device_set is None:
            return contextlib.nullcontext()
        from waffle_con_tpu.parallel import mesh as par_mesh

        return par_mesh.use_device_set(self._device_set)

    def _finalize(self, handle: JobHandle, exc: BaseException) -> None:
        if isinstance(exc, JobCancelled):
            handle._finish(JobStatus.CANCELLED, exception=exc)
            self._account(handle, "cancelled")
        elif isinstance(exc, DeadlineExceeded):
            handle._finish(JobStatus.EXPIRED, exception=exc)
            self._account(handle, "expired")
        else:
            handle._finish(JobStatus.FAILED, exception=exc)
            self._account(handle, "failed")

    def _account(self, handle: JobHandle, outcome: str) -> None:
        with self._lock:
            self._counts[outcome] += 1
        latency = handle.latency_s
        obs_flight.record(
            "job_end", trace_id=handle.trace.trace_id,
            outcome=outcome, job_id=handle.job_id,
            latency_s=(round(latency, 6) if latency is not None else None),
        )
        if outcome == "done" and latency is not None:
            obs_slo.observe_job(latency)
        self._publish_stats()
        if obs_metrics.metrics_enabled():
            reg = obs_metrics.registry()
            reg.counter(
                "waffle_serve_jobs_total",
                service=self.config.name, outcome=outcome,
            ).inc()
            if latency is not None:
                reg.histogram(
                    "waffle_serve_job_latency_seconds",
                    service=self.config.name,
                ).observe(latency)
            reg.gauge(
                "waffle_serve_active_jobs", service=self.config.name
            ).set(self._active_jobs())

    def _publish_stats(self) -> None:
        """When ``WAFFLE_STATS_FILE`` is set, atomically rewrite it with
        the live stats + SLO snapshot (throttled) so ``waffle_top`` can
        poll a serving process without a network endpoint."""
        path = envspec.get_raw("WAFFLE_STATS_FILE", "")
        if not path or not self._publish:
            return
        now = time.monotonic()
        with self._lock:
            last = getattr(self, "_stats_published_at", 0.0)
            if now - last < 0.25:
                return
            self._stats_published_at = now
        payload = {
            "service": self.config.name,
            "unix_time": time.time(),
            "stats": self.stats(),
            "slo": obs_slo.snapshot(),
            "incidents": [
                {k: i.get(k) for k in
                 ("seq", "reason", "trace_id", "unix_time", "path")}
                for i in obs_flight.incidents()[-8:]
            ],
        }
        if obs_metrics.metrics_enabled():
            payload["metrics"] = obs_metrics.registry().snapshot()
        audit_status = obs_audit.status()
        if audit_status is not None:
            payload["audit"] = audit_status
        try:
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, default=repr)
            os.replace(tmp, path)
        except OSError:  # a broken stats sink must never fail a job
            pass

    def _active_jobs(self) -> int:
        with self._lock:
            counts = dict(self._counts)
        finished = (counts["done"] + counts["failed"]
                    + counts["cancelled"] + counts["expired"]
                    + counts["cached"] + counts["certified"])
        return max(0, counts["submitted"] - finished - self._queue.depth())

    # -- introspection -------------------------------------------------

    def stats(self) -> Dict:
        """Point-in-time counters + dispatcher batching stats (the
        bench's ``--serve`` evidence embeds this dict verbatim)."""
        with self._lock:
            counts = dict(self._counts)
            ckpt_counts = dict(self._ckpt_counts)
        payload = {
            "jobs": counts,
            "checkpoints": ckpt_counts,
            "queue_depth": self._queue.depth(),
            "aged_pops": self._queue.aged_pops,
            "dispatch": self._dispatcher.stats(),
            "ragged": ops_ragged.arena_stats(self._arena),
        }
        if self._cache is not None:
            payload["cache"] = self._cache.stats()
        return payload
