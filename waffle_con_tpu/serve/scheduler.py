"""Admission control and worker pool for the consensus service.

:class:`AdmissionQueue` is a *bounded* priority queue: higher
``JobRequest.priority`` pops first, FIFO within a priority class (a
monotonically increasing sequence number breaks ties, and makes heap
entries totally ordered without ever comparing handles).  A full queue
**rejects** with :class:`~waffle_con_tpu.serve.job.ServiceOverloaded`
instead of blocking the submitter — under overload the caller must get
a fast typed answer it can retry/shed on, not a stalled thread.

:class:`WorkerPool` is a fixed set of daemon threads draining the queue
through a job-runner callable supplied by the service.  Workers are
deliberately dumb: all lifecycle logic (skip-if-cancelled, deadline at
pop, engine construction, finalization) lives in
``ConsensusService._run_job``.
"""

from __future__ import annotations

import heapq
import threading
from typing import Callable, List, Optional

from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.serve.job import (
    JobHandle,
    ServiceClosed,
    ServiceOverloaded,
)


class AdmissionQueue:
    """Bounded priority queue with reject-on-full backpressure."""

    def __init__(self, limit: int, name: str = "consensus") -> None:
        if limit < 1:
            raise ValueError("queue limit must be >= 1")
        self.limit = limit
        self._name = name
        self._cond = threading.Condition()
        self._heap: List[tuple] = []
        self._seq = 0
        self._closed = False

    def _set_depth_gauge(self, depth: int) -> None:
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().gauge(
                "waffle_serve_queue_depth", service=self._name
            ).set(depth)

    def put(self, handle: JobHandle) -> None:
        """Enqueue or raise — never blocks on a full queue."""
        with self._cond:
            if self._closed:
                raise ServiceClosed("service is closed to new jobs")
            if len(self._heap) >= self.limit:
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().counter(
                        "waffle_serve_admission_rejections_total",
                        service=self._name,
                    ).inc()
                raise ServiceOverloaded(
                    f"admission queue full ({self.limit} jobs queued); "
                    "retry later or shed load"
                )
            heapq.heappush(
                self._heap,
                (-handle.request.priority, self._seq, handle),
            )
            self._seq += 1
            depth = len(self._heap)
            self._cond.notify()
        self._set_depth_gauge(depth)

    def get(self, timeout: Optional[float] = None) -> Optional[JobHandle]:
        """Pop the best job, or ``None`` on timeout / closed-and-empty."""
        with self._cond:
            while not self._heap:
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None
            _neg_prio, _seq, handle = heapq.heappop(self._heap)
            depth = len(self._heap)
        self._set_depth_gauge(depth)
        return handle

    def drain(self) -> List[JobHandle]:
        """Remove and return every queued job (shutdown path)."""
        with self._cond:
            handles = [h for _p, _s, h in self._heap]
            self._heap.clear()
        self._set_depth_gauge(0)
        return handles

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class WorkerPool:
    """Fixed pool of daemon threads feeding jobs to ``run_job``."""

    def __init__(
        self,
        workers: int,
        queue: AdmissionQueue,
        run_job: Callable[[JobHandle], None],
        name: str = "consensus",
    ) -> None:
        if workers < 1:
            raise ValueError("need at least one worker")
        self._queue = queue
        self._run_job = run_job
        self._name = name
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._loop,
                name=f"waffle-serve-{name}-w{i}",
                daemon=True,
            )
            for i in range(workers)
        ]
        self._started = False

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for t in self._threads:
            t.start()

    @property
    def started(self) -> bool:
        return self._started

    def _loop(self) -> None:
        while not self._stop.is_set():
            handle = self._queue.get(timeout=0.05)
            if handle is None:
                continue
            self._run_job(handle)

    def stop(self, wait: bool = True) -> None:
        self._stop.set()
        self._queue.close()
        if wait and self._started:
            for t in self._threads:
                t.join()
