"""Admission control and worker pool for the consensus service.

:class:`AdmissionQueue` is a *bounded* priority queue: higher
``JobRequest.priority`` pops first, FIFO within a priority class (a
monotonically increasing sequence number breaks ties, and makes heap
entries totally ordered without ever comparing handles).  A full queue
**rejects** with :class:`~waffle_con_tpu.serve.job.ServiceOverloaded`
instead of blocking the submitter — under overload the caller must get
a fast typed answer it can retry/shed on, not a stalled thread.

Strict priority starves: a saturating high class would hold a queued
low-priority job forever.  ``aging_s`` bounds that wait — when the
OLDEST queued job has waited longer than the aging window it pops
next regardless of class.  Within the window ordering is exactly the
strict heap order, so latency-sensitive traffic keeps its edge and
the aged pop only fires under sustained cross-class pressure.

:class:`WorkerPool` is a fixed set of daemon threads draining the queue
through a job-runner callable supplied by the service.  Workers are
deliberately dumb: all lifecycle logic (skip-if-cancelled, deadline at
pop, engine construction, finalization) lives in
``ConsensusService._run_job``.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, List, Optional

from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.serve.job import (
    JobHandle,
    ServiceClosed,
    ServiceOverloaded,
)


class AdmissionQueue:
    """Bounded priority queue with reject-on-full backpressure."""

    def __init__(self, limit: int, name: str = "consensus",
                 aging_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        if limit < 1:
            raise ValueError("queue limit must be >= 1")
        if aging_s is not None and aging_s <= 0:
            raise ValueError("aging_s must be > 0 (or None to disable)")
        self.limit = limit
        self.aging_s = aging_s
        self._clock = clock or time.monotonic
        self._name = name
        self._cond = threading.Condition()
        self._heap: List[tuple] = []
        self._seq = 0
        self._closed = False
        self._aged_pops = 0

    def _set_depth_gauge(self, depth: int) -> None:
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().gauge(
                "waffle_serve_queue_depth", service=self._name
            ).set(depth)

    def put(self, handle: JobHandle) -> None:
        """Enqueue or raise — never blocks on a full queue."""
        with self._cond:
            if self._closed:
                raise ServiceClosed("service is closed to new jobs")
            if len(self._heap) >= self.limit:
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().counter(
                        "waffle_serve_admission_rejections_total",
                        service=self._name,
                    ).inc()
                raise ServiceOverloaded(
                    f"admission queue full ({self.limit} jobs queued); "
                    "retry later or shed load"
                )
            heapq.heappush(
                self._heap,
                (-handle.request.priority, self._seq, self._clock(),
                 handle),
            )
            self._seq += 1
            depth = len(self._heap)
            self._cond.notify()
        self._set_depth_gauge(depth)

    def _pop_entry(self) -> tuple:
        """Heap pop with anti-starvation aging: when the oldest queued
        entry (minimum sequence number — sequence is global arrival
        order) has waited past ``aging_s``, it pops instead of the
        strict-priority head.  O(n) scan + heapify, but n is bounded by
        the admission ``limit`` and the path only triggers on an aged
        entry."""
        if self.aging_s is not None and len(self._heap) > 1:
            idx = min(range(len(self._heap)),
                      key=lambda i: self._heap[i][1])
            entry = self._heap[idx]
            if (self._clock() - entry[2] >= self.aging_s
                    and entry[1] != self._heap[0][1]):
                self._heap[idx] = self._heap[-1]
                self._heap.pop()
                heapq.heapify(self._heap)
                self._aged_pops += 1
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().counter(
                        "waffle_serve_aged_pops_total",
                        service=self._name,
                    ).inc()
                return entry
        return heapq.heappop(self._heap)

    def get(self, timeout: Optional[float] = None) -> Optional[JobHandle]:
        """Pop the best job, or ``None`` on timeout / closed-and-empty."""
        with self._cond:
            while not self._heap:
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None
            handle = self._pop_entry()[-1]
            depth = len(self._heap)
        self._set_depth_gauge(depth)
        return handle

    def drain(self) -> List[JobHandle]:
        """Remove and return every queued job (shutdown path)."""
        with self._cond:
            handles = [entry[-1] for entry in self._heap]
            self._heap.clear()
        self._set_depth_gauge(0)
        return handles

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    @property
    def aged_pops(self) -> int:
        with self._cond:
            return self._aged_pops

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class WorkerPool:
    """Fixed pool of daemon threads feeding jobs to ``run_job``."""

    def __init__(
        self,
        workers: int,
        queue: AdmissionQueue,
        run_job: Callable[[JobHandle], None],
        name: str = "consensus",
    ) -> None:
        if workers < 1:
            raise ValueError("need at least one worker")
        self._queue = queue
        self._run_job = run_job
        self._name = name
        self._stop = threading.Event()
        self._threads = [
            lockcheck.make_thread(
                target=self._loop,
                name=f"waffle-serve-{name}-w{i}",
                daemon=True,
            )
            for i in range(workers)
        ]
        self._started = False

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for t in self._threads:
            t.start()

    @property
    def started(self) -> bool:
        return self._started

    def _loop(self) -> None:
        while not self._stop.is_set():
            handle = self._queue.get(timeout=0.05)
            if handle is None:
                continue
            self._run_job(handle)

    def stop(self, wait: bool = True) -> None:
        self._stop.set()
        self._queue.close()
        if wait and self._started:
            for t in self._threads:
                t.join()
