"""Cross-job dynamic batching at the scorer-dispatch boundary.

The engines' host-side search is strictly sequential *within* a job —
each blocking scorer dispatch depends on the previous one's result — so
a single job can never batch with itself.  But N concurrent jobs each
have (at most) one dispatch in flight at any moment, and on a tunneled
device platform each dispatch pays the same launch/transfer overhead
the TPU-serving literature coalesces away (Ragged Paged Attention,
arXiv:2604.15464).  :class:`BatchingDispatcher` is that coalescing
point: worker threads park their job's next dispatch in a shared pend
list, a single dispatcher thread collects everything that arrives
within a bounded batching window, groups the batch by *bucket*
(backend + padded read-count/read-length geometry, the shapes that
share an XLA compilation), and executes each group back-to-back as one
device-resident burst.

What is and is not fused: each job's scorer owns its own device state
and reads arrays (``ops/jax_scorer.py`` keeps one ``[branch, read,
2E+1]`` state per scorer), so requests are *not* merged into a single
XLA call — results stay byte-identical to serial execution by
construction, because every request runs its own ``fn()`` against its
own scorer, in deterministic submission order within the group.  The
win is scheduling-level: one thread owns the device (no GIL/dispatch
interleaving), bucket grouping runs same-compiled-shape kernels
consecutively, and per-dispatch sync overhead is amortized across the
group.  Batch occupancy (requests per executed group) is the quantity
to watch — ``waffle_serve_batch_occupancy`` — and the service's bench
mode reports its mean.

When a job is alone (``active_jobs <= 1``), dispatch falls through to
a direct call on the worker thread: a single-tenant service pays no
batching-window latency at all.

:class:`CoalescingScorer` is the per-job proxy that routes the scorer
protocol's blocking dispatch methods (the same vocabulary as
``obs.TimedScorer``) into the dispatcher; everything else — attribute
reads, capability feature-tests (``getattr(scorer, "run_extend",
None)``), the two-way live ``counters`` view — passes through
untouched, so engines cannot tell they are being served.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import slo as obs_slo
from waffle_con_tpu.obs import trace as obs_trace
from waffle_con_tpu.obs.instrument import TIMED_OPS
# ops.ragged imports nothing heavy at module scope (jax loads lazily
# inside the arena), so this is safe for python-backend-only services
from waffle_con_tpu.ops import ragged as ops_ragged
from waffle_con_tpu.ops.scorer import resolve_stats
from waffle_con_tpu.serve.job import ServiceClosed
from waffle_con_tpu.analysis import lockcheck

logger = logging.getLogger(__name__)


def _pow2_ceil(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 0 else 0


def bucket_key(scorer) -> tuple:
    """Shape bucket of a job's scorer: jobs in the same bucket run the
    same compiled kernels (backend + power-of-two-padded read count and
    max read length + alphabet size), so executing them consecutively
    keeps one compiled program hot instead of ping-ponging."""
    reads = getattr(scorer, "reads", []) or []
    config = getattr(scorer, "config", None)
    backend = getattr(config, "backend", "?")
    max_len = max((len(r) for r in reads), default=0)
    # the speculative block width K is a static kernel argument read per
    # dispatch (WAFFLE_RUN_COLS), so two jobs at different K run
    # different compiled programs even at identical shapes — it must be
    # part of the bucket or the "same compiled kernels" contract above
    # silently breaks
    k_cols = 0
    if "jax" in str(backend):
        try:
            from waffle_con_tpu.ops.jax_scorer import _run_cols

            k_cols = _run_cols()
        except Exception:  # pragma: no cover - jax unavailable
            k_cols = -1
    return (
        backend,
        _pow2_ceil(len(reads)),
        _pow2_ceil(max_len),
        int(getattr(scorer, "num_symbols", 0) or 0),
        k_cols,
    )


class _DispatchRequest:
    __slots__ = ("ticket", "bucket", "op", "fn", "ragged", "result",
                 "exception", "done", "ctx", "enqueued_at")

    def __init__(self, ticket, bucket, op, fn, ragged=None) -> None:
        self.ticket = ticket
        self.bucket = bucket
        self.op = op
        self.fn = fn
        # optional ragged-dispatch payload (probe_fn, args, kwargs): the
        # dispatcher may gang this run_extend with other jobs' through
        # the paged band-state arena (see ops.ragged)
        self.ragged = ragged
        self.result = None
        self.exception: Optional[BaseException] = None
        self.done = threading.Event()
        # the submitting worker's trace context rides along so the
        # dispatcher thread can re-activate it around execution — the
        # dispatch span then lands under the job's pid, parented by the
        # worker-side search span (see obs/trace.py context contract)
        self.ctx = obs_trace.current_context()
        self.enqueued_at = time.perf_counter()


class BatchingDispatcher:
    """Single-threaded executor coalescing concurrent scorer dispatches.

    ``window_s`` bounds how long the first request of a batch waits for
    company; ``max_batch`` bounds how much company it waits *for* (the
    wait target is ``min(max_batch, active_jobs)`` — there is no point
    waiting for more requests than there are jobs able to send one).

    With ``adaptive_window`` (default on) the wait inside that cap is
    arrival-rate-predictive: the dispatcher keeps an EWMA of recent
    inter-arrival gaps and, after each arrival, holds only
    ``max(4 x ewma_gap, window_s / 4)`` for the next one (clamped to
    the configured window).  Under a burst the gaps are tiny, the hold
    refreshes per arrival, and the gang fills to target; when arrivals
    stall the batch launches early instead of idling out the full
    fixed window.  Worst-case added latency is unchanged (the absolute
    ``window_s`` cap from first park still applies); a cold EWMA falls
    back to the fixed window.  The chosen hold is surfaced in
    :meth:`stats` (and from there in serve evidence).

    ``arena`` pins the ragged gang pass to one replica's band-state
    arena; ``None`` uses the process arena (single-service behavior).
    """

    #: EWMA smoothing for inter-arrival gaps (~last 10 arrivals)
    EWMA_ALPHA = 0.2

    def __init__(
        self,
        window_s: float = 0.002,
        max_batch: int = 8,
        name: str = "consensus",
        adaptive_window: bool = True,
        arena=None,
    ) -> None:
        if window_s < 0:
            raise ValueError("window_s must be >= 0")
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.window_s = window_s
        self.max_batch = max_batch
        self.adaptive_window = adaptive_window
        self._arena = arena
        self._name = name
        self._cond = threading.Condition()
        self._pending: List[_DispatchRequest] = []
        self._active_jobs = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # adaptive-hold state (all under the lock): monotonic time of
        # the last routed arrival, the smoothed gap, and the hold the
        # batching loop last chose
        self._last_arrival: Optional[float] = None
        self._ewma_gap: Optional[float] = None
        self._last_hold_s: float = window_s
        self._hold_sum = 0.0
        self._hold_batches = 0
        # internal stats, always maintained (cheap ints under the lock);
        # the obs serve_* metrics mirror them when metrics are enabled
        self._stats = {
            "coalesced_batches": 0,   # executed groups with >= 2 requests
            "solo_batches": 0,        # executed groups of exactly 1
            "routed_requests": 0,     # requests through the dispatcher
            "direct_dispatches": 0,   # fell through (job alone / closed)
            "occupancy_sum": 0,
            "occupancy_max": 0,
            # ragged gang accounting (tentpole) plus the bucketed
            # baseline's run-dispatch clustering, so the two occupancy
            # numbers compare apples to apples in bench evidence
            "ragged_groups": 0,       # ragged kernel calls (>= 2 members)
            "ragged_members": 0,      # run dispatches ganged into them
            "ragged_occupancy_max": 0,
            "run_clusters": 0,        # executed groups containing runs
            "run_cluster_requests": 0,
        }

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        with self._cond:
            if self._thread is not None or self._closed:
                return
            self._thread = lockcheck.make_thread(
                target=self._loop,
                name=f"waffle-serve-{self._name}-dispatcher",
                daemon=True,
            )
            self._thread.start()

    def close(self) -> None:
        """Stop the dispatcher thread; drains already-parked requests
        before exiting, then fails anything that raced in."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join()
        with self._cond:
            leftovers = self._pending[:]
            del self._pending[:]
        for req in leftovers:
            req.exception = ServiceClosed("dispatcher closed mid-dispatch")
            req.done.set()

    # -- job accounting ------------------------------------------------

    def job_started(self) -> None:
        with self._cond:
            self._active_jobs += 1

    def job_finished(self) -> None:
        with self._cond:
            self._active_jobs = max(0, self._active_jobs - 1)

    # -- the dispatch path ---------------------------------------------

    def dispatch(self, ticket, bucket: tuple, op: str, fn, ragged=None):
        """Run one blocking scorer dispatch, coalescing with concurrent
        jobs when possible.  ``ticket.check_abort(op)`` gates both entry
        and execution so cancellations/deadlines bite at this boundary.
        ``ragged`` optionally carries the probe payload letting the
        dispatcher gang this call across jobs (direct fall-through
        ignores it — a lone job has nobody to gang with).
        """
        if ticket is not None:
            ticket.check_abort(op)
        with self._cond:
            direct = (
                self._closed
                or self._thread is None
                or not self._thread.is_alive()
                or self._active_jobs <= 1
                or self.window_s <= 0
                or threading.current_thread() is self._thread
            )
            if direct:
                self._stats["direct_dispatches"] += 1
            else:
                req = _DispatchRequest(ticket, bucket, op, fn, ragged)
                # flow start before the dispatcher can see the request,
                # inside the worker's open search span, so the "s" event
                # temporally precedes the dispatcher-side "f"
                obs_trace.get_tracer().flow("s", id(req))
                now = time.monotonic()
                if self._last_arrival is not None:
                    # idle stretches are not "inter-arrival" signal:
                    # clamp the sample so one quiet second cannot park
                    # the EWMA above the window for the next burst
                    gap = min(now - self._last_arrival, 4 * self.window_s)
                    self._ewma_gap = (
                        gap if self._ewma_gap is None
                        else (self.EWMA_ALPHA * gap
                              + (1 - self.EWMA_ALPHA) * self._ewma_gap)
                    )
                self._last_arrival = now
                self._pending.append(req)
                self._stats["routed_requests"] += 1
                self._cond.notify_all()
        if direct:
            if obs_metrics.metrics_enabled():
                obs_metrics.registry().counter(
                    "waffle_serve_direct_dispatches_total",
                    service=self._name,
                ).inc()
            t0 = time.perf_counter()
            try:
                return fn()
            finally:
                dt = time.perf_counter() - t0
                obs_slo.observe_dispatch(dt)
                obs_flight.record(
                    "dispatch", trace_id=obs_trace.current_trace_id(),
                    op=op, path="direct", total_ms=round(dt * 1e3, 3),
                )
        # park until the dispatcher delivers; poll so a dispatcher that
        # died on an unexpected error cannot strand the worker forever
        while not req.done.wait(0.25):
            with self._cond:
                thread_dead = (
                    self._thread is None or not self._thread.is_alive()
                )
            if thread_dead and not req.done.is_set():
                raise ServiceClosed(
                    "batching dispatcher thread died mid-dispatch"
                )
        if req.exception is not None:
            raise req.exception
        return req.result

    # -- dispatcher thread ---------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                # bounded batching window: wait for company up to
                # window_s, but never for more requests than there are
                # other active jobs to send them.  Inside that cap the
                # adaptive hold trims the wait to a multiple of the
                # observed inter-arrival gap, refreshed per arrival.
                target = min(self.max_batch, max(2, self._active_jobs))
                cap = time.monotonic() + self.window_s
                hold = self.window_s
                while len(self._pending) < target and not self._closed:
                    now = time.monotonic()
                    if self.adaptive_window and self._ewma_gap is not None:
                        hold = min(
                            self.window_s,
                            max(4 * self._ewma_gap, self.window_s / 4),
                        )
                        deadline = min(
                            cap, (self._last_arrival or now) + hold
                        )
                    else:
                        deadline = cap
                    remaining = deadline - now
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                self._last_hold_s = hold
                self._hold_sum += hold
                self._hold_batches += 1
                batch = self._pending[:]
                del self._pending[:]
            self._execute(batch)

    def _execute(self, batch: List[_DispatchRequest]) -> None:
        # ragged pass FIRST: gang eligible run_extend dispatches from
        # *different* buckets — and, with width-agnostic pages,
        # different band widths — into single arena kernel calls.  Each
        # ganged member's result is deposited as a consume-once
        # injection that its ordinary fn() below returns instantly, so
        # execution order, tracing, supervision and error delivery are
        # untouched; anything the pass cannot take simply runs solo.
        injected_keys: List[tuple] = []
        if len(batch) > 1 and ops_ragged.enabled():
            injected_keys = self._ragged_pass(batch)
        try:
            self._execute_groups(batch)
        finally:
            # a member whose dispatch raised before reaching the scorer
            # (abort/deadline) must not leave a stale injection behind
            if injected_keys:
                ops_ragged.discard_injected(injected_keys, arena=self._arena)

    def _ragged_pass(self, batch: List[_DispatchRequest]) -> List[tuple]:
        specs = []
        seen_scorers = set()
        for req in batch:
            if req.ragged is None:
                continue
            try:
                spec = ops_ragged.probe(
                    req.ragged, req.ticket, arena=self._arena
                )
            except Exception:  # noqa: BLE001 - probe failure = solo
                logger.debug("ragged probe failed", exc_info=True)
                continue
            if spec is None:
                continue
            # one scorer may not appear twice in a gang (its pool rows
            # would collide); the duplicate runs solo this round
            sid = id(spec.scorer)
            if sid in seen_scorers:
                continue
            seen_scorers.add(sid)
            specs.append(spec)
        if len(specs) < 2:
            return []
        keys: List[tuple] = []
        gang = ops_ragged.gang_width(self._arena)
        for i in range(0, len(specs), gang):
            chunk = specs[i:i + gang]
            if len(chunk) < 2:
                break  # a trailing singleton just runs solo
            with obs_trace.span(
                "serve:ragged", "serve", members=len(chunk)
            ):
                got = ops_ragged.run_group(chunk, arena=self._arena)
            if not got:
                continue
            keys.extend(got)
            with self._cond:
                self._stats["ragged_groups"] += 1
                self._stats["ragged_members"] += len(got)
                self._stats["ragged_occupancy_max"] = max(
                    self._stats["ragged_occupancy_max"], len(got)
                )
        return keys

    def _execute_groups(self, batch: List[_DispatchRequest]) -> None:
        # group by shape bucket, preserving arrival order within and
        # across groups (first-seen bucket runs first)
        groups: Dict[tuple, List[_DispatchRequest]] = {}
        for req in batch:
            groups.setdefault(req.bucket, []).append(req)
        metrics_on = obs_metrics.metrics_enabled()
        for bucket, reqs in groups.items():
            occupancy = len(reqs)
            run_reqs = sum(1 for r in reqs if r.op == "run")
            with self._cond:
                if occupancy > 1:
                    self._stats["coalesced_batches"] += 1
                else:
                    self._stats["solo_batches"] += 1
                self._stats["occupancy_sum"] += occupancy
                self._stats["occupancy_max"] = max(
                    self._stats["occupancy_max"], occupancy
                )
                if run_reqs:
                    self._stats["run_clusters"] += 1
                    self._stats["run_cluster_requests"] += run_reqs
            if metrics_on:
                obs_metrics.registry().histogram(
                    "waffle_serve_batch_occupancy",
                    buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
                    service=self._name,
                ).observe(occupancy)
            with obs_trace.span(
                "serve:batch", "serve",
                bucket=str(bucket), occupancy=occupancy,
            ):
                for req in reqs:
                    # run under the submitting job's trace context: the
                    # dispatch span gets the job's pid and parents under
                    # the parked worker's search span (safe: that worker
                    # is blocked on req.done until we set it)
                    prev_ctx = obs_trace.set_current_context(req.ctx)
                    obs_trace.get_tracer().flow("f", id(req))
                    t0 = time.perf_counter()
                    try:
                        if req.ticket is not None:
                            req.ticket.check_abort(req.op)
                        # coalesced execution crosses a thread boundary:
                        # force any deferred-sync stats NOW, on the
                        # dispatching thread, so the worker receives a
                        # fully materialized result (async-seam
                        # fall-through — deferral is only safe while
                        # the consumer is the dispatching thread)
                        req.result = resolve_stats(req.fn())
                    except BaseException as exc:  # delivered to the worker
                        req.exception = exc
                    finally:
                        dt = time.perf_counter() - t0
                        obs_slo.observe_dispatch(
                            time.perf_counter() - req.enqueued_at
                        )
                        obs_flight.record(
                            "dispatch",
                            trace_id=(req.ctx.trace_id
                                      if req.ctx is not None else None),
                            op=req.op, path="coalesced",
                            occupancy=occupancy,
                            exec_ms=round(dt * 1e3, 3),
                            queue_ms=round(
                                (t0 - req.enqueued_at) * 1e3, 3
                            ),
                            error=(repr(req.exception)
                                   if req.exception is not None else None),
                        )
                        obs_trace.set_current_context(prev_ctx)
                        req.done.set()

    # -- introspection -------------------------------------------------

    def stats(self) -> Dict:
        with self._cond:
            s = dict(self._stats)
            s["adaptive_window"] = self.adaptive_window
            s["window_s"] = self.window_s
            s["last_hold_ms"] = round(self._last_hold_s * 1e3, 4)
            s["mean_hold_ms"] = round(
                (self._hold_sum / self._hold_batches * 1e3)
                if self._hold_batches else self.window_s * 1e3, 4
            )
            s["ewma_arrival_gap_ms"] = (
                round(self._ewma_gap * 1e3, 4)
                if self._ewma_gap is not None else None
            )
        batches = s["coalesced_batches"] + s["solo_batches"]
        s["batches"] = batches
        s["mean_batch_occupancy"] = (
            s["occupancy_sum"] / batches if batches else 0.0
        )
        s["ragged_mean_occupancy"] = (
            s["ragged_members"] / s["ragged_groups"]
            if s["ragged_groups"] else 0.0
        )
        s["run_cluster_mean_occupancy"] = (
            s["run_cluster_requests"] / s["run_clusters"]
            if s["run_clusters"] else 0.0
        )
        return s


class CoalescingScorer:
    """Per-job scorer proxy routing blocking dispatches into a shared
    :class:`BatchingDispatcher`.

    Same transparency contract as ``obs.TimedScorer`` (which it may be
    stacked on top of): attribute access falls through to the wrapped
    scorer so capability feature-tests see exactly the backend's
    surface, ``counters`` stays a live two-way view (the supervisor
    swaps in shared dicts by plain assignment), and wrapped methods are
    cached in the instance dict after first touch — safe because the
    wrapped scorer's capability surface is fixed after construction.
    """

    def __init__(self, base, dispatcher: BatchingDispatcher, ticket) -> None:
        self._base = base
        self._dispatcher = dispatcher
        self._ticket = ticket
        self._bucket = bucket_key(base)

    @property
    def counters(self):
        return self._base.counters

    @counters.setter
    def counters(self, value):
        self._base.counters = value

    @property
    def coalesce_bucket(self) -> tuple:
        return self._bucket

    def __getattr__(self, name: str):
        base = self.__dict__["_base"]
        attr = getattr(base, name)
        op = TIMED_OPS.get(name)
        if op is None or not callable(attr):
            return attr
        dispatcher = self.__dict__["_dispatcher"]
        ticket = self.__dict__["_ticket"]
        bucket = self.__dict__["_bucket"]
        # run_extend dispatches carry the ragged probe hop when the
        # wrapped stack exposes one (JaxScorer / BackendSupervisor do;
        # python backends and subset scorers don't) — resolution down to
        # the live endpoint happens on the dispatcher thread, so a
        # mid-flight backend demotion is seen, not raced
        probe_attr = (
            getattr(base, "ragged_run_probe", None)
            if name == "run_extend" else None
        )

        def routed(*args, **kwargs):
            payload = (
                (probe_attr, args, kwargs)
                if probe_attr is not None else None
            )
            return dispatcher.dispatch(
                ticket, bucket, op, lambda: attr(*args, **kwargs),
                ragged=payload,
            )

        routed.__name__ = name
        self.__dict__[name] = routed
        return routed
