"""Replicated front door: N in-process serve replicas behind one door.

Scale-out inside one process: each replica is a full
:class:`~waffle_con_tpu.serve.service.ConsensusService` — its own
admission queue, batching dispatcher, ragged band arena, and worker
pool — pinned to a disjoint :class:`~waffle_con_tpu.parallel.mesh.DeviceSet`
slice of the local topology.  :class:`ReplicatedService` is the shared
admission point in front of them:

* **least-outstanding-work routing** — every submit goes to the
  healthy replica with the fewest admitted-but-unfinished jobs; a
  replica at its admission limit overflows to the next-best instead of
  rejecting the client.
* **health-driven shedding** — the front door listens to the flight
  recorder's trigger stream (the same always-on signals the incident
  path uses).  A ``backend_demoted`` on a replica puts it in
  ``draining``: no new admissions until its outstanding work reaches
  zero, then it re-admits automatically (circuit-break drain /
  re-admit).  A ``slow_search`` puts it in ``shedding`` for a
  cooldown: routing prefers other replicas while its latency recovers.
  When every replica is unhealthy the door falls back to plain
  least-outstanding — degraded beats down.
* **per-replica observability** — ``waffle_replica_*`` gauges and
  counters, a ``replicas`` table in the ``WAFFLE_STATS_FILE`` payload
  (rendered by ``scripts/waffle_top.py``), and runtime events for
  every state transition.  The front door owns stats publication; the
  member services have theirs disabled so N replicas never clobber
  one file.

Results stay byte-identical to serial execution: replicas add routing,
not math — each job still runs on exactly one service, and the ragged
arena / mesh placement parity contracts hold per replica.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import slo as obs_slo
from waffle_con_tpu.ops import ragged as ops_ragged
from waffle_con_tpu.runtime import events
from waffle_con_tpu.serve.job import (
    JobHandle,
    JobRequest,
    ServiceClosed,
    ServiceOverloaded,
)
from waffle_con_tpu.serve.service import ConsensusService, ServeConfig
from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec

#: replica states
UP = "up"
DRAINING = "draining"    # circuit-break: no admissions until drained
SHEDDING = "shedding"    # latency flag: deprioritized for a cooldown

#: flight-trigger reasons the health listener acts on
_HEALTH_REASONS = ("backend_demoted", "slow_search")


@dataclasses.dataclass(frozen=True)
class ReplicatedConfig:
    """Front-door knobs.

    * ``replicas`` — member service count; each gets its own
      dispatcher, arena, worker pool, and device slice.
    * ``base`` — per-replica :class:`ServeConfig` template (name is
      rewritten to ``<name>:r<i>`` per replica).
    * ``shed_cooldown_s`` — how long a ``slow_search``-flagged replica
      stays deprioritized.
    """

    replicas: int = 2
    base: Optional[ServeConfig] = None
    name: str = "consensus"
    shed_cooldown_s: float = 2.0

    def __post_init__(self) -> None:
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.shed_cooldown_s < 0:
            raise ValueError("shed_cooldown_s must be >= 0")


class _Replica:
    """Mutable per-replica record (state guarded by the door's lock)."""

    __slots__ = ("index", "name", "service", "arena", "device_set",
                 "state", "shed_until", "routed", "demotions", "sheds",
                 "readmits")

    def __init__(self, index: int, name: str, service: ConsensusService,
                 arena, device_set) -> None:
        self.index = index
        self.name = name
        self.service = service
        self.arena = arena
        self.device_set = device_set
        self.state = UP
        self.shed_until = 0.0
        self.routed = 0
        self.demotions = 0
        self.sheds = 0
        self.readmits = 0


class ReplicatedService:
    """N serve replicas behind least-outstanding, health-aware routing.

    Usage::

        with ReplicatedService(ReplicatedConfig(replicas=2)) as door:
            handles = [door.submit(req) for req in requests]
            results = [h.result() for h in handles]
    """

    def __init__(
        self,
        config: Optional[ReplicatedConfig] = None,
        autostart: bool = True,
    ) -> None:
        self.config = config if config is not None else ReplicatedConfig()
        base = (self.config.base if self.config.base is not None
                else ServeConfig())
        self._lock = lockcheck.make_lock("serve.replicas.ReplicatedService")
        self._closed = False
        self._stats_published_at = 0.0
        slices = self._device_slices(self.config.replicas)
        self._replicas: List[_Replica] = []
        for i in range(self.config.replicas):
            rname = f"{self.config.name}:r{i}"
            arena = ops_ragged.new_arena(rname)
            service = ConsensusService(
                dataclasses.replace(base, name=rname),
                autostart=False,
                device_set=slices[i],
                arena=arena,
                publish_stats=False,
            )
            self._replicas.append(
                _Replica(i, rname, service, arena, slices[i])
            )
        obs_flight.add_trigger_listener(self._on_trigger)
        if autostart:
            self.start()

    @staticmethod
    def _device_slices(n: int) -> List:
        """Disjoint device slices for the replicas, or all-``None``
        when the stack has no importable device runtime (python-backend
        services still replicate fine — they just share the host)."""
        try:
            from waffle_con_tpu.parallel import mesh as par_mesh

            return list(par_mesh.device_slices(n, name_prefix="replica"))
        except Exception:  # noqa: BLE001 - jax-less / deviceless stack
            return [None] * n

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        for rep in self._replicas:
            rep.service.start()

    def close(
        self, cancel_pending: bool = False, timeout: Optional[float] = None
    ) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        obs_flight.remove_trigger_listener(self._on_trigger)
        for rep in self._replicas:
            rep.service.close(cancel_pending=cancel_pending,
                              timeout=timeout)
        for rep in self._replicas:
            ops_ragged.drop_arena(rep.name)

    def __enter__(self) -> "ReplicatedService":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()

    # -- health --------------------------------------------------------

    def _on_trigger(self, reason: str, trace_id: Optional[str],
                    detail: Dict) -> None:
        """Flight-trigger listener: attribute health signals to a
        replica by trace-id prefix (job trace ids are
        ``<replica-name>/job-<id>``) and transition its state."""
        if reason not in _HEALTH_REASONS or not trace_id:
            return
        rep = next(
            (r for r in self._replicas
             if trace_id.startswith(r.name + "/")), None,
        )
        if rep is None:
            return
        with self._lock:
            if self._closed:
                return
            if reason == "backend_demoted":
                rep.demotions += 1
                if rep.state != DRAINING:
                    rep.state = DRAINING
                    events.record(
                        "replica_draining", replica=rep.name,
                        trigger=reason, trace_id=trace_id,
                    )
            else:  # slow_search
                rep.sheds += 1
                if rep.state == UP:
                    rep.state = SHEDDING
                rep.shed_until = (
                    time.monotonic() + self.config.shed_cooldown_s
                )
                events.record(
                    "replica_shedding", replica=rep.name,
                    trigger=reason, trace_id=trace_id,
                )
        self._publish_replica_metrics(rep)

    def _maintain(self) -> None:
        """Lazy health maintenance at each routing decision: re-admit
        drained replicas, expire shed cooldowns."""
        now = time.monotonic()
        readmitted = []
        with self._lock:
            for rep in self._replicas:
                if rep.state == DRAINING \
                        and rep.service.outstanding() == 0:
                    rep.state = UP
                    rep.readmits += 1
                    readmitted.append(rep)
                elif rep.state == SHEDDING and now >= rep.shed_until:
                    rep.state = UP
        for rep in readmitted:
            events.record("replica_readmitted", replica=rep.name)
            self._publish_replica_metrics(rep)

    # -- client API ----------------------------------------------------

    def submit(self, request: JobRequest) -> JobHandle:
        """Route one job to the least-outstanding healthy replica.

        Draining/shedding replicas are skipped while any healthy one
        exists; a full replica overflows to the next-best.  Raises
        :class:`ServiceOverloaded` only when EVERY replica rejected.
        """
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is closed to new jobs")
        self._maintain()
        with self._lock:
            ranked = sorted(
                self._replicas,
                key=lambda r: (0 if r.state == UP else 1,
                               r.service.outstanding(), r.index),
            )
            healthy = [r for r in ranked if r.state == UP]
        # no healthy replica: degraded least-outstanding beats rejecting
        candidates = healthy if healthy else ranked
        last_exc: Optional[ServiceOverloaded] = None
        for rep in candidates:
            try:
                handle = rep.service.submit(request)
            except ServiceOverloaded as exc:
                last_exc = exc
                continue
            with self._lock:
                rep.routed += 1
            self._publish_replica_metrics(rep)
            self._publish_stats()
            return handle
        if healthy and len(healthy) < len(ranked):
            # healthy tier full: overflow onto the unhealthy remainder
            for rep in [r for r in ranked if r not in healthy]:
                try:
                    handle = rep.service.submit(request)
                except ServiceOverloaded as exc:
                    last_exc = exc
                    continue
                with self._lock:
                    rep.routed += 1
                self._publish_replica_metrics(rep)
                self._publish_stats()
                return handle
        raise last_exc if last_exc is not None else ServiceOverloaded(
            "no replica accepted the job"
        )

    def submit_all(self, requests: Sequence[JobRequest]) -> List[JobHandle]:
        return [self.submit(r) for r in requests]

    # -- observability -------------------------------------------------

    def _publish_replica_metrics(self, rep: _Replica) -> None:
        if not obs_metrics.metrics_enabled():
            return
        reg = obs_metrics.registry()
        labels = {"service": self.config.name, "replica": rep.name}
        reg.gauge("waffle_replica_outstanding", **labels).set(
            rep.service.outstanding()
        )
        reg.gauge("waffle_replica_healthy", **labels).set(
            1 if rep.state == UP else 0
        )
        reg.gauge("waffle_replica_routed", **labels).set(rep.routed)
        reg.gauge("waffle_replica_demotions", **labels).set(rep.demotions)
        reg.gauge("waffle_replica_sheds", **labels).set(rep.sheds)

    def replica_stats(self) -> List[Dict]:
        """Per-replica snapshot (the ``replicas`` table in stats
        payloads and storm evidence)."""
        out = []
        with self._lock:
            reps = list(self._replicas)
            states = {r.name: r.state for r in reps}
        for rep in reps:
            svc_stats = rep.service.stats()
            dispatch = svc_stats.get("dispatch", {})
            out.append({
                "replica": rep.name,
                "state": states[rep.name],
                "outstanding": rep.service.outstanding(),
                "queue_depth": svc_stats.get("queue_depth", 0),
                "routed": rep.routed,
                "demotions": rep.demotions,
                "sheds": rep.sheds,
                "readmits": rep.readmits,
                "jobs": svc_stats.get("jobs", {}),
                "mean_batch_occupancy": dispatch.get(
                    "mean_batch_occupancy", 0.0
                ),
                "ragged_mean_occupancy": dispatch.get(
                    "ragged_mean_occupancy", 0.0
                ),
                "last_hold_ms": dispatch.get("last_hold_ms"),
                "devices": (
                    len(rep.device_set)
                    if rep.device_set is not None else None
                ),
            })
        return out

    def stats(self) -> Dict:
        """Aggregated counters plus the per-replica table."""
        agg: Dict[str, int] = {}
        queue_depth = 0
        aged_pops = 0
        per_replica = self.replica_stats()
        for rep in self._replicas:
            svc_stats = rep.service.stats()
            for key, val in svc_stats.get("jobs", {}).items():
                agg[key] = agg.get(key, 0) + int(val)
            queue_depth += svc_stats.get("queue_depth", 0)
            aged_pops += svc_stats.get("aged_pops", 0)
        return {
            "jobs": agg,
            "queue_depth": queue_depth,
            "aged_pops": aged_pops,
            "replicas": per_replica,
        }

    def outstanding(self) -> int:
        return sum(r.service.outstanding() for r in self._replicas)

    def _publish_stats(self) -> None:
        """Front-door-owned ``WAFFLE_STATS_FILE`` publication (same
        throttle + atomic-rename contract as the single service; the
        payload gains a top-level ``replicas`` table)."""
        path = envspec.get_raw("WAFFLE_STATS_FILE", "")
        if not path:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._stats_published_at < 0.25:
                return
            self._stats_published_at = now
        stats = self.stats()
        payload = {
            "service": self.config.name,
            "unix_time": time.time(),
            "stats": stats,
            "replicas": stats["replicas"],
            "slo": obs_slo.snapshot(),
            "incidents": [
                {k: i.get(k) for k in
                 ("seq", "reason", "trace_id", "unix_time", "path")}
                for i in obs_flight.incidents()[-8:]
            ],
        }
        if obs_metrics.metrics_enabled():
            payload["metrics"] = obs_metrics.registry().snapshot()
        try:
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, default=repr)
            os.replace(tmp, path)
        except OSError:  # a broken stats sink must never fail a job
            pass
