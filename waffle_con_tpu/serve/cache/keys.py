"""Canonical request hashing for the consensus cache.

Two requests must map to the same key exactly when a consensus engine
would return the same answer for both (modulo per-read score order):

* the **read multiset** — reads are order-insensitive for the engines'
  tie-set semantics, but multiplicity matters (duplicate reads double
  votes), so the key digests the sorted multiset of ``(read, offset)``
  pairs.  Priority chains keep their within-chain order (seeding is
  positional) while the chain multiset itself is order-insensitive.
* the **scoring config fingerprint** — every :class:`CdwfaConfig`
  field that shapes the search result (cost model, queue/nomination
  bounds, wildcard, offset policy, …).  Placement and performance
  fields (``backend``, ``mesh_shards``, supervisor/retry knobs, band
  seeds, speculation widths) are deliberately EXCLUDED: they decide
  where and how fast a job runs, never what it returns, and admission
  rewrites some of them (mesh placement) after the client built the
  request.

The digests are hex sha256 over canonical JSON (sorted keys, no
whitespace) — stable across processes and safe as file names for the
optional on-disk store.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from waffle_con_tpu.serve.procs.wire import encode_config

#: CdwfaConfig fields that never change a search's *result* — only its
#: placement, speed, or supervision.  Everything not listed here is
#: scoring-relevant and participates in the key (fail-closed: a new
#: config field changes keys until someone proves it placement-only).
PLACEMENT_ONLY_FIELDS = frozenset({
    "backend", "mesh_shards", "initial_band", "prefetch_width",
    "frontier_width", "supervised", "backend_chain",
    "dispatch_timeout_s", "dispatch_retries", "retry_backoff_s",
    "retry_jitter", "breaker_threshold", "repromote_after",
    "dispatch_budget", "watchdog_strict", "log_search_summary",
})


def scoring_config_fields(config) -> Dict:
    """The scoring-relevant slice of a config as plain JSON types
    (``None`` config means engine defaults, fingerprinted as such)."""
    if config is None:
        from waffle_con_tpu.config import CdwfaConfig

        config = CdwfaConfig()
    encoded = encode_config(config)
    return {k: v for k, v in encoded.items()
            if k not in PLACEMENT_ONLY_FIELDS}


def config_fingerprint(config) -> str:
    """Hex digest of the scoring-relevant config slice."""
    return _digest({"config": scoring_config_fields(config)})


def read_elements(request) -> List:
    """The request's read multiset as sortable JSON elements.

    ``single``/``dual``: ``[read_hex, offset]`` pairs (offset ``None``
    when unseeded).  ``priority``: each chain is a list of read hexes
    in chain order (within-chain order is positional seeding and must
    NOT be canonicalized away)."""
    if request.kind == "priority":
        return [[bytes(s).hex() for s in chain] for chain in request.reads]
    offsets = request.offsets or (None,) * len(request.reads)
    return [[bytes(r).hex(), o] for r, o in zip(request.reads, offsets)]


def request_key(request) -> str:
    """The canonical content-addressed key for one job request:
    order-insensitive read multiset + kind + scoring config."""
    return _digest({
        "kind": request.kind,
        "reads": sorted(read_elements(request), key=_sort_token),
        "config": scoring_config_fields(request.config),
    })


def reads_digest(reads: Sequence[bytes],
                 offsets: Optional[Sequence[Optional[int]]] = None) -> str:
    """Order-insensitive digest of a plain read multiset (the
    checkpoint store's subset-overlap key; ``single`` kind only)."""
    offs = offsets or (None,) * len(reads)
    elements = [[bytes(r).hex(), o] for r, o in zip(reads, offs)]
    return _digest({"reads": sorted(elements, key=_sort_token)})


def read_multiset(reads: Sequence[bytes]) -> Counter:
    """Multiset of raw read bytes (offset-free; used for the
    subset/superset overlap tests, which are gated to unseeded jobs)."""
    return Counter(bytes(r) for r in reads)


def multiset_extras(superset_reads: Sequence[bytes],
                    subset_reads: Sequence[bytes],
                    ) -> Optional[Tuple[bytes, ...]]:
    """The reads in ``superset_reads`` left after removing one copy of
    each read in ``subset_reads`` (kept in superset order), or ``None``
    when ``subset_reads`` is not a sub-multiset."""
    need = read_multiset(subset_reads)
    extras: List[bytes] = []
    for read in superset_reads:
        read = bytes(read)
        if need.get(read, 0) > 0:
            need[read] -= 1
        else:
            extras.append(read)
    if any(v > 0 for v in need.values()):
        return None
    return tuple(extras)


def match_permutation(request_elements: List,
                      stored_elements: List) -> Optional[List[int]]:
    """``perm[i] = j`` assigning each request read position ``i`` a
    distinct stored position ``j`` with an equal ``(read, offset)``
    value, or ``None`` when the multisets differ.  Equal-valued reads
    have equal per-read scores (the scorer is a deterministic function
    of ``(read, consensus, offset)``), so any consistent assignment
    remaps a cached result's score vectors correctly."""
    slots: Dict[str, List[int]] = {}
    for j, element in enumerate(stored_elements):
        slots.setdefault(_sort_token(element), []).append(j)
    perm: List[int] = []
    for element in request_elements:
        bucket = slots.get(_sort_token(element))
        if not bucket:
            return None
        perm.append(bucket.pop())
    if any(bucket for bucket in slots.values()):
        return None
    return perm


def _sort_token(element) -> str:
    return json.dumps(element, sort_keys=True, separators=(",", ":"))


def _digest(obj: Dict) -> str:
    blob = json.dumps(
        obj, sort_keys=True, separators=(",", ":"), allow_nan=False
    ).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


# sanity: the placement-only list must stay a subset of the real config
# fields, so a renamed field cannot silently start leaking into keys
def _check_fields() -> None:
    from waffle_con_tpu.config import CdwfaConfig

    names = {f.name for f in dataclasses.fields(CdwfaConfig)}
    unknown = PLACEMENT_ONLY_FIELDS - names
    if unknown:
        raise RuntimeError(
            f"PLACEMENT_ONLY_FIELDS names unknown config fields: "
            f"{sorted(unknown)}"
        )


_check_fields()
