"""Content-addressed consensus cache with checkpoint overlap reuse.

The cache sits between admission and dispatch in both
:class:`~waffle_con_tpu.serve.service.ConsensusService` and the
proc-fleet front door, and answers in three tiers (cheapest first):

1. **exact hit** — the request's canonical key (order-insensitive read
   multiset + scoring config fingerprint, :mod:`.keys`) matches a
   stored result: serve it straight from the wire-codec JSON, zero
   worker involvement.  Byte-parity holds by construction because the
   key collapses exactly the degrees of freedom the engines ignore
   (read order; placement-only config fields) and nothing else —
   per-read score vectors are remapped to the request's read order.
2. **proposal certify** — a cached result for a read *subset* is
   re-scored against the full request by one exact oracle pass and
   served only at the cached optimal cost (:mod:`.proposal`); anything
   short degrades to a full search.
3. **checkpoint superset** — a finished job's last *bound-free*
   mid-search checkpoint whose read multiset is a subset of the
   request's resumes through the existing ``resume(checkpoint,
   extra_reads=)`` seam; the worker still runs, but from a paid-for
   frontier instead of scratch.  Only snapshots taken before the
   subset search found any complete candidate qualify
   (:func:`resumable_wire`): such a snapshot carries no incumbent
   bound (``maximum_error`` unset, no pending results), so no branch
   has been pruned against subset-only costs and the resumed superset
   search explores the same tree a from-scratch one would.  A
   bound-tightened snapshot would prune the superset's optimum with
   the subset's incumbent — those are never deposited.

Everything here is fail-closed: any gate miss, decode error, or store
corruption (quarantined, never served) falls through to the normal
full-search path, so the cache can cost a lookup but never an answer.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.obs import flight as obs_flight
from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.runtime import events
from waffle_con_tpu.serve.cache import keys
from waffle_con_tpu.serve.cache.store import CheckpointStore, FileStore, ResultStore
from waffle_con_tpu.utils import envspec

#: Per-read score vector fields in the wire result JSON, by job kind —
#: the parts that are functions of read *position* and must be remapped
#: when serving a permuted duplicate.
_SCORE_FIELDS = {
    "single": ("scores",),
    "dual": ("scores1", "scores2", "is_consensus1"),
}


def resumable_wire(wire_ckpt) -> bool:
    """True when a wire-form checkpoint is safe to resume with extra
    reads: its search had found no complete candidate yet, so it
    carries no incumbent bound (``maximum_error`` unset, no pending
    ``results``) and has a live frontier.  Resuming a bound-tightened
    snapshot over a read *superset* would prune with subset-only costs
    and can miss the superset's optimum — never deposit those."""
    try:
        state = wire_ckpt["body"]["state"]
        return bool(
            state["entries"]
            and state.get("maximum_error") is None
            and not state.get("results")
        )
    except (KeyError, TypeError):
        return False


@dataclasses.dataclass(frozen=True)
class CacheHit:
    """A result served without a full search.  ``tier`` is ``"exact"``
    or ``"certified"``; ``result`` is a fresh decoded engine result."""

    tier: str
    result: object


@dataclasses.dataclass(frozen=True)
class CheckpointHit:
    """A cached checkpoint whose reads are a sub-multiset of the
    request's: attach ``checkpoint`` (wire dict) to the job and let the
    engine resume with the extra reads."""

    checkpoint: Dict
    extras: int


class ConsensusCache:
    """Bounded three-tier consensus cache (thread-safe facade)."""

    def __init__(
        self,
        name: str,
        max_results: int = 256,
        max_checkpoints: int = 64,
        proposals: bool = True,
        cache_dir: Optional[str] = None,
    ) -> None:
        self.name = name
        self.proposals = proposals
        self._lock = lockcheck.make_lock(f"serve.cache.ConsensusCache.{name}")
        self._results = ResultStore(max_results)
        self._checkpoints = CheckpointStore(max_checkpoints)
        self._files = FileStore(cache_dir) if cache_dir else None
        self._counts = {
            "exact": 0, "certified": 0, "checkpoint": 0, "misses": 0,
            "deposits": 0, "ckpt_deposits": 0, "certify_failed": 0,
        }

    @classmethod
    def from_env(cls, name: str) -> Optional["ConsensusCache"]:
        """The cache configured by the ``WAFFLE_CACHE_*`` knobs, or
        ``None`` when caching is off (the default)."""
        if not envspec.flag("WAFFLE_CACHE"):
            return None
        proposals = envspec.get_raw(
            "WAFFLE_CACHE_PROPOSALS", "1"
        ) not in ("", "0")
        return cls(
            name,
            max_results=envspec.get_int("WAFFLE_CACHE_MAX", 256, lo=1),
            max_checkpoints=envspec.get_int("WAFFLE_CACHE_CKPTS", 64, lo=1),
            proposals=proposals,
            cache_dir=envspec.get_raw("WAFFLE_CACHE_DIR", "") or None,
        )

    # -- lookup --------------------------------------------------------

    def lookup(self, request, trace_id: Optional[str] = None):
        """``CacheHit`` / ``CheckpointHit`` / ``None`` (miss)."""
        key = keys.request_key(request)
        with self._lock:
            entry = self._results.get(key)
            if entry is None and self._files is not None:
                entry = self._files.get(key)
                if entry is not None and self._valid_file_entry(request, entry):
                    self._results.put(key, entry)
                else:
                    entry = None
            if entry is not None:
                result = self._serve(request, entry)
                if result is not None:
                    self._counts["exact"] += 1
                    self._observe("exact", request, trace_id)
                    return CacheHit("exact", result)
            if self.proposals:
                hit = self._certify_locked(request)
                if hit is not None:
                    self._counts["certified"] += 1
                    self._observe("certified", request, trace_id)
                    return hit
            hit = self._checkpoint_locked(request)
            if hit is not None:
                self._counts["checkpoint"] += 1
                self._observe("checkpoint", request, trace_id)
                return hit
            self._counts["misses"] += 1
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().counter(
                "waffle_cache_misses_total", service=self.name
            ).inc()
        return None

    @staticmethod
    def _valid_file_entry(request, entry: Dict) -> bool:
        """Shape gate for entries read back off disk: the seal proves
        the bytes, this proves they are a result entry for this kind."""
        return (
            isinstance(entry, dict)
            and entry.get("kind") == request.kind
            and isinstance(entry.get("result"), list)
            and isinstance(entry.get("elements"), list)
        )

    def _serve(self, request, entry: Dict):
        """Decode a stored entry into fresh result objects, remapping
        per-read score vectors into the request's read order."""
        from waffle_con_tpu.serve.procs import wire

        elements = keys.read_elements(request)
        stored = entry.get("elements")
        if request.kind == "priority":
            # chain order is positional seeding: serve only the exact
            # ordered form, a permuted chain list is a different job
            if elements != stored:
                return None
            return wire.decode_result(request.kind, entry["result"])
        perm = keys.match_permutation(elements, stored or [])
        if perm is None:
            return None
        obj = entry["result"]
        if perm != list(range(len(perm))):
            fields = _SCORE_FIELDS.get(request.kind, ())
            remapped = []
            for item in obj:
                item = dict(item)
                for field in fields:
                    old = item.get(field)
                    if old is not None:
                        item[field] = [old[j] for j in perm]
                remapped.append(item)
            obj = remapped
        try:
            return wire.decode_result(request.kind, obj)
        except (KeyError, ValueError, TypeError):
            return None

    def _certify_locked(self, request):
        from waffle_con_tpu.serve.cache import proposal
        from waffle_con_tpu.serve.procs import wire

        if request.kind != "single" or request.offsets is not None:
            return None
        for _key, entry in reversed(self._results.items()):
            if not proposal.eligible(request, entry):
                continue
            stored = [bytes.fromhex(h) for h in entry.get("reads", ())]
            if keys.multiset_extras(request.reads, stored) is None:
                continue
            # one certification attempt against the freshest eligible
            # subset entry; a failed certify degrades to a full search
            # rather than scanning further (bounded lookup cost)
            served = proposal.certify(request, entry)
            if served is None:
                self._counts["certify_failed"] += 1
                events.record(
                    "cache_certify_failed", service=self.name,
                    job_kind=request.kind,
                )
                return None
            obj = wire.encode_result("single", served)
            return CacheHit(
                "certified", wire.decode_result("single", obj)
            )
        return None

    def _checkpoint_locked(self, request):
        if request.kind != "single" or request.offsets is not None:
            return None
        fp = keys.config_fingerprint(request.config)
        for digest, entry in reversed(self._checkpoints.items()):
            if entry.get("config_fp") != fp:
                continue
            stored = [bytes.fromhex(h) for h in entry.get("reads", ())]
            extras = keys.multiset_extras(request.reads, stored)
            if extras is None:
                continue
            self._checkpoints.touch(digest)
            return CheckpointHit(entry["checkpoint"], len(extras))
        return None

    # -- deposits ------------------------------------------------------

    def deposit_result(self, request, wire_result: List[Dict]) -> None:
        """Store a finished job's wire-encoded result under its
        canonical key (and in the file store when configured)."""
        key = keys.request_key(request)
        entry = {
            "kind": request.kind,
            "result": wire_result,
            "elements": keys.read_elements(request),
        }
        if request.kind != "priority":
            entry["reads"] = [bytes(r).hex() for r in request.reads]
            entry["offsets"] = (
                list(request.offsets) if request.offsets is not None else None
            )
        if request.kind == "single":
            from waffle_con_tpu.config import CdwfaConfig

            config = request.config or CdwfaConfig()
            entry["config_fp"] = keys.config_fingerprint(request.config)
            entry["truncated"] = len(wire_result) >= config.max_return_size
        with self._lock:
            self._results.put(key, entry)
            self._counts["deposits"] += 1
            if self._files is not None:
                self._files.put(key, entry)
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().counter(
                "waffle_cache_deposits_total", service=self.name
            ).inc()

    def deposit_checkpoint(self, request, wire_ckpt: Dict) -> None:
        """Store a finished job's last bound-free mid-search checkpoint
        keyed by its read-multiset digest, for superset resume.  Only
        unseeded ``single`` jobs with a live, incumbent-free frontier
        qualify (see :func:`resumable_wire`)."""
        if request.kind != "single" or request.offsets is not None:
            return
        if not resumable_wire(wire_ckpt):
            return
        digest = keys.reads_digest(request.reads)
        entry = {
            "checkpoint": wire_ckpt,
            "reads": [bytes(r).hex() for r in request.reads],
            "config_fp": keys.config_fingerprint(request.config),
        }
        with self._lock:
            self._checkpoints.put(digest, entry)
            self._counts["ckpt_deposits"] += 1

    # -- accounting ----------------------------------------------------

    def _observe(self, tier: str, request, trace_id: Optional[str]) -> None:
        events.record(
            "cache_hit", service=self.name, tier=tier, job_kind=request.kind,
        )
        obs_flight.record(
            "cache_hit", trace_id=trace_id, tier=tier,
            job_kind=request.kind, service=self.name,
        )
        if obs_metrics.metrics_enabled():
            obs_metrics.registry().counter(
                "waffle_cache_hits_total", service=self.name, tier=tier,
            ).inc()

    def stats(self) -> Dict:
        with self._lock:
            counts = dict(self._counts)
            counts["results"] = len(self._results)
            counts["checkpoints"] = len(self._checkpoints)
            counts["quarantined"] = (
                self._files.quarantined if self._files is not None else 0
            )
        return counts
