"""Propose-then-verify: certify a cached near-miss consensus.

A cached entry for read multiset ``R0`` holds the *complete* tied set
of optimal consensuses at cost ``c0``.  For a new request over a
superset ``R = R0 + extras``, every candidate ``s`` satisfies

    total_R(s) = total_R0(s) + total_extras(s) >= total_R0(s) >= c0

so the optimal cost for ``R`` is at least ``c0``.  If any cached
consensus ``t`` achieves ``total_R(t) == c0`` under one exact scoring
pass (every extra read at edit distance 0 against ``t``), then ``c0``
IS the optimum for ``R``, and any optimal ``s`` for ``R`` must have
``total_R0(s) == c0`` — i.e. ``s`` belongs to the cached tied set.
The served answer ``{t in cached : total_R(t) == c0}`` is therefore
the complete tied set for ``R``.  Anything short of equality degrades
to a full search (mirroring the ``checkpoint_rejected`` path), so a
wrong proposal can cost time but never parity.

The completeness premise leans on the cached set being untruncated
(``len(results) < max_return_size``) and on search reachability under
the nomination gates (``min_count``/``min_af``) — the latter is not
proven here, which is why certification is narrowly gated, defaults to
refusing anything unusual, can be disabled outright with
``WAFFLE_CACHE_PROPOSALS=0``, and is empirically byte-parity-checked
in the bench/CI storm gates.
"""

from __future__ import annotations

import base64
from typing import Dict, List, Optional

import numpy as np

from waffle_con_tpu.models.consensus import Consensus
from waffle_con_tpu.ops.scorer import PythonScorer
from waffle_con_tpu.serve.cache import keys


def eligible(request, entry: Dict) -> bool:
    """Cheap gates before the (expensive) scoring pass: unseeded
    ``single`` jobs, identical scoring config, no early termination,
    and an untruncated cached tied set."""
    if request.kind != "single" or entry.get("kind") != "single":
        return False
    if request.offsets is not None or entry.get("offsets") is not None:
        return False
    if entry.get("truncated"):
        return False
    config = request.config
    if config is not None and config.allow_early_termination:
        return False
    if entry.get("config_fp") != keys.config_fingerprint(config):
        return False
    if not entry.get("result"):
        return False
    return True


def certify(request, entry: Dict) -> Optional[List[Consensus]]:
    """Score every cached candidate against the request's full read
    set with the exact python oracle; return the complete tied set if
    one candidate holds the cached optimal cost, else ``None``.

    Caller must have checked :func:`eligible`."""
    stored_reads = [bytes.fromhex(h) for h in entry.get("reads", ())]
    extras = keys.multiset_extras(request.reads, stored_reads)
    if extras is None:
        return None

    if request.config is None:
        from waffle_con_tpu.config import CdwfaConfig

        config = CdwfaConfig()
    else:
        config = request.config
    cost = config.consensus_cost

    cached = entry["result"]
    totals0 = {sum(item["scores"]) for item in cached}
    if len(totals0) != 1:  # a tied set with unequal totals is corrupt
        return None
    c0 = totals0.pop()

    candidates = sorted(
        base64.b64decode(item["sequence"]) for item in cached
    )
    reads = [bytes(r) for r in request.reads]
    scorer = PythonScorer(reads, config)
    active = np.ones(len(reads), dtype=bool)
    served: List[Consensus] = []
    for seq in candidates:
        handle = scorer.root(active)
        for i in range(len(seq)):
            scorer.push(handle, seq[: i + 1])
        eds = scorer.finalized_eds(handle, seq)
        scorer.free(handle)
        scores = [cost.apply(int(e)) for e in eds]
        if sum(scores) == c0:
            served.append(Consensus(seq, cost, scores))
    if not served:
        return None
    return served
