"""Bounded content-addressed stores for the consensus cache.

:class:`ResultStore` and :class:`CheckpointStore` are in-memory LRU
maps (OrderedDict move-to-end on hit, popitem(last=False) on
overflow).  Entries hold only plain JSON types — results travel in the
:mod:`waffle_con_tpu.serve.procs.wire` result codec form and
checkpoints in the :class:`~waffle_con_tpu.models.checkpoint.
SearchCheckpoint` wire-dict form — so every cache hit decodes fresh
objects and a served result can never be aliased/mutated by one client
into another's answer.

:class:`FileStore` is the optional ``WAFFLE_CACHE_DIR`` persistence
layer for results, following the ``utils/cache.py`` hash-sealing
precedent: one ``<key>.json`` file per entry, a ``MANIFEST.json`` of
content sha256 digests beside them, and a ``_quarantine/`` subdir.  A
read whose bytes no longer match their sealed digest (crashed writer,
disk fault, injected corruption) is moved into quarantine and reported
as a ``cache_quarantine`` flight trigger — a corrupt entry is *never*
served; the job simply searches from scratch.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"
QUARANTINE_DIR = "_quarantine"


class ResultStore:
    """LRU of finished results keyed by the canonical request key.

    One entry is ``{"kind", "result", "reads"}`` — the wire-codec
    result JSON plus the deposit request's ordered read elements (so a
    permuted duplicate's score vectors can be remapped)."""

    def __init__(self, max_entries: int) -> None:
        self.max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[str, Dict]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: str) -> Optional[Dict]:
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
        return entry

    def put(self, key: str, entry: Dict) -> None:
        self._entries[key] = entry
        self._entries.move_to_end(key)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    def items(self) -> List[Tuple[str, Dict]]:
        """Snapshot in LRU order (oldest first) — the proposal tier
        scans it for subset near-misses."""
        return list(self._entries.items())


class CheckpointStore:
    """LRU of final mid-search checkpoints keyed by the deposit job's
    read-multiset digest.

    One entry is ``{"checkpoint", "reads", "config_fp"}`` — the wire
    checkpoint dict, the deposit's raw read list (bytes), and the
    scoring config fingerprint (a resumed engine runs the checkpoint's
    own config, so reuse demands fingerprint equality).  Subset lookup
    is a bounded linear scan: the store caps at tens of entries and
    multiset inclusion is cheap next to the search it saves."""

    def __init__(self, max_entries: int) -> None:
        self.max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[str, Dict]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def put(self, digest: str, entry: Dict) -> None:
        self._entries[digest] = entry
        self._entries.move_to_end(digest)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    def items(self) -> List[Tuple[str, Dict]]:
        return list(self._entries.items())

    def touch(self, digest: str) -> None:
        if digest in self._entries:
            self._entries.move_to_end(digest)


class FileStore:
    """Hash-sealed on-disk result entries under ``WAFFLE_CACHE_DIR``."""

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.quarantined = 0
        self._manifest = self._load_manifest()

    # -- manifest ------------------------------------------------------

    def _load_manifest(self) -> Dict[str, str]:
        manifest_path = os.path.join(self.path, MANIFEST_NAME)
        try:
            with open(manifest_path) as fh:
                manifest = json.load(fh)
            if not isinstance(manifest, dict):
                raise ValueError("manifest is not a mapping")
            return {str(k): str(v) for k, v in manifest.items()}
        except FileNotFoundError:
            return {}
        except (OSError, ValueError) as exc:
            logger.warning(
                "rebuilding corrupt consensus-cache manifest: %r", exc
            )
            return {}

    def _save_manifest(self) -> None:
        manifest_path = os.path.join(self.path, MANIFEST_NAME)
        tmp = f"{manifest_path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(self._manifest, fh, indent=0, sort_keys=True)
            os.replace(tmp, manifest_path)
        except OSError:  # a broken cache disk must never fail a job
            pass

    # -- entries -------------------------------------------------------

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.json")

    def get(self, key: str) -> Optional[Dict]:
        """The sealed entry for ``key``, or ``None`` — a digest
        mismatch or undecodable body quarantines the file and reports
        it; it is never served."""
        full = self._entry_path(key)
        try:
            with open(full, "rb") as fh:
                blob = fh.read()
        except OSError:
            return None
        expected = self._manifest.get(key)
        digest = hashlib.sha256(blob).hexdigest()
        if expected is None or digest != expected:
            self._quarantine(key, full, "digest mismatch")
            return None
        try:
            entry = json.loads(blob.decode("utf-8"))
            if not isinstance(entry, dict):
                raise ValueError("entry is not an object")
        except (UnicodeDecodeError, ValueError) as exc:
            # sealed bytes that don't parse mean the seal itself was
            # written over a bad payload: quarantine, don't trust it
            self._quarantine(key, full, f"undecodable entry: {exc}")
            return None
        return entry

    def put(self, key: str, entry: Dict) -> None:
        full = self._entry_path(key)
        blob = json.dumps(
            entry, sort_keys=True, separators=(",", ":"), allow_nan=False
        ).encode("utf-8")
        tmp = f"{full}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, full)
        except OSError:
            return
        self._manifest[key] = hashlib.sha256(blob).hexdigest()
        self._save_manifest()

    def _quarantine(self, key: str, full: str, why: str) -> None:
        qdir = os.path.join(self.path, QUARANTINE_DIR)
        try:
            os.makedirs(qdir, exist_ok=True)
            shutil.move(full, os.path.join(qdir, os.path.basename(full)))
        except OSError:
            try:
                os.unlink(full)
            except OSError:
                pass
        self._manifest.pop(key, None)
        self._save_manifest()
        self.quarantined += 1
        logger.warning(
            "quarantined corrupt consensus-cache entry %s (%s); the job "
            "will search from scratch", key, why,
        )
        from waffle_con_tpu.obs import flight as obs_flight
        from waffle_con_tpu.runtime import events

        events.record("cache_quarantine", entry=key, why=why)
        obs_flight.trigger(
            "cache_quarantine", cache_dir=self.path, entries=[key],
        )
