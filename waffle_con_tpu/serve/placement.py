"""Shard placement for admitted jobs: route by read count.

The serve layer has two execution substrates with opposite sweet
spots.  Small jobs amortize launch overhead by *ganging* — the paged
band-state arena steps many jobs in one ragged kernel call — while a
single large job has enough rows to fill wide hardware on its own and
wants the *mesh* instead: ``parallel.mesh.shard_scorer`` splits its
read axis across devices and lets GSPMD insert the cross-chip
reductions.  :class:`PlacementPolicy` is the classifier that picks per
admitted job.

Mechanism: promotion happens at admission by rewriting the job's
config (``dataclasses.replace(config, mesh_shards=n)``) — the
existing ``construct_backend -> shard_for_config`` path then places
the scorer's state on the mesh with zero new code in the engines, and
the arena's eligibility gate already rejects sharded scorers, so the
two substrates stay naturally exclusive.  Results are byte-identical
either way (mesh parity is pinned by ``tests/test_parallel.py``; the
storm harness re-verifies per job against serial references).

The policy never *forces* hardware that is not there: the effective
shard count is clamped to the available device pool (the replica's
:class:`~waffle_con_tpu.parallel.mesh.DeviceSet` when pinned, else
the cached probe) and rounded down to a power of two so it always
divides the scorer's pow2-padded read count.  Below 2 effective
shards the job simply stays on the arena path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from waffle_con_tpu.serve.job import JobRequest


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 0


@dataclasses.dataclass(frozen=True)
class PlacementPolicy:
    """Classify admitted jobs by read count and pick their substrate.

    * ``large_read_threshold`` — jobs with at least this many reads are
      mesh candidates; smaller jobs stay on the ragged-arena path.
    * ``mesh_shards`` — requested read-axis shard count for promoted
      jobs (clamped to the devices actually available at placement
      time, pow2-floored).
    """

    large_read_threshold: int = 64
    mesh_shards: int = 2

    def __post_init__(self) -> None:
        if self.large_read_threshold < 1:
            raise ValueError("large_read_threshold must be >= 1")
        if self.mesh_shards < 2:
            raise ValueError(
                "mesh_shards must be >= 2 (1 shard is just the "
                "unsharded engine; use placement=None instead)"
            )

    def classify(self, request: JobRequest) -> str:
        """``"mesh"`` or ``"arena"`` for one job."""
        return (
            "mesh" if len(request.reads) >= self.large_read_threshold
            else "arena"
        )

    def effective_shards(self, n_reads: int, available_devices: int) -> int:
        """Shard count a promoted job actually gets: the policy ask,
        clamped to the device pool and to the job's own read count,
        pow2-floored (so it divides the pow2-padded read axis).  < 2
        means no promotion."""
        return _pow2_floor(
            min(self.mesh_shards, available_devices, max(n_reads, 0))
        )

    def place(self, request: JobRequest,
              available_devices: int) -> Optional[JobRequest]:
        """Return the mesh-promoted request, or ``None`` to leave the
        job on the arena path.

        Declines when: the job is small, the backend is not jax
        (``mesh_shards`` is a jax-scorer feature), the caller already
        pinned an explicit shard count (explicit config wins), or the
        device pool yields fewer than 2 effective shards.
        """
        if self.classify(request) != "mesh":
            return None
        config = request.config
        if config is None or getattr(config, "backend", None) != "jax":
            return None
        if getattr(config, "mesh_shards", 0):
            return None
        shards = self.effective_shards(len(request.reads),
                                       available_devices)
        if shards < 2:
            return None
        return dataclasses.replace(
            request, config=dataclasses.replace(config, mesh_shards=shards)
        )
