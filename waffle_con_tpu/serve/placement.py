"""Shard placement for admitted jobs: route by read count.

The serve layer has two execution substrates with opposite sweet
spots.  Small jobs amortize launch overhead by *ganging* — the paged
band-state arena steps many jobs in one ragged kernel call — while a
single large job has enough rows to fill wide hardware on its own and
wants the *mesh* instead: ``parallel.mesh.shard_scorer`` splits its
read axis across devices and lets GSPMD insert the cross-chip
reductions.  :class:`PlacementPolicy` is the classifier that picks per
admitted job.

Mechanism: promotion happens at admission by rewriting the job's
config (``dataclasses.replace(config, mesh_shards=n)``) — the
existing ``construct_backend -> shard_for_config`` path then places
the scorer's state on the mesh with zero new code in the engines, and
the arena's eligibility gate already rejects sharded scorers, so the
two substrates stay naturally exclusive.  Results are byte-identical
either way (mesh parity is pinned by ``tests/test_parallel.py``; the
storm harness re-verifies per job against serial references).

The policy never *forces* hardware that is not there: the effective
shard count is clamped to the available device pool (the replica's
:class:`~waffle_con_tpu.parallel.mesh.DeviceSet` when pinned, else
the cached probe) and rounded down to a power of two so it always
divides the scorer's pow2-padded read count.  Below 2 effective
shards the job simply stays on the arena path.

**Learned placement** (``WAFFLE_PLACEMENT_LEARNED=1``): instead of
the hand-set ``large_read_threshold``, :meth:`PlacementPolicy.classify`
consults the perfdb — the service appends one
``placement_profile`` record per finished job (substrate, pow2 reads
bucket, wall seconds, phase breakdown when profiling is on), and the
classifier compares rolling per-bucket medians of the two substrates'
decision seconds (:func:`waffle_con_tpu.obs.perfdb.decision_seconds`:
host+device+transfer when profiled, else wall).  The learned decision
applies only when BOTH substrates have at least
:data:`MIN_PROFILE_SAMPLES` records in the job's bucket; cold or
one-sided history falls back to the static threshold, so the knob can
never strand a fresh deployment.  Profiles are re-read only when the
database file's (mtime, size) stamp changes — steady-state decisions
cost a dict lookup, not a file parse.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.obs import perfdb
from waffle_con_tpu.serve.job import JobRequest
from waffle_con_tpu.utils import envspec

#: both substrates need this many profile records in a job's reads
#: bucket before the learned decision overrides the static threshold
MIN_PROFILE_SAMPLES = 3


def learned_enabled() -> bool:
    """``WAFFLE_PLACEMENT_LEARNED`` — learn mesh-vs-arena routing from
    perfdb placement profiles (default off)."""
    return envspec.flag("WAFFLE_PLACEMENT_LEARNED")


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 0


class _ProfileCache:
    """Placement-profile history, cached on the perfdb file stamp.

    One process-wide instance backs every policy: profiles are keyed
    by the database *path* so tests pointing ``WAFFLE_PERFDB`` at a
    tmpfile never see another test's history, and the (mtime, size)
    stamp invalidates the cache when the service appends new records.
    """

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("placement.profiles")
        self._stamp: Optional[tuple] = None
        self._records: List[Dict] = []
        self._medians: Dict[int, Dict[str, Dict]] = {}

    def decide(self, bucket: int) -> Optional[str]:
        """``"mesh"`` / ``"arena"`` when the history is warm enough to
        choose, else ``None`` (caller falls back to the threshold)."""
        medians = self._bucket_medians(bucket)
        mesh = medians.get("mesh")
        arena = medians.get("arena")
        if (mesh is None or arena is None
                or mesh["n"] < MIN_PROFILE_SAMPLES
                or arena["n"] < MIN_PROFILE_SAMPLES):
            return None
        return "mesh" if mesh["median"] < arena["median"] else "arena"

    def _bucket_medians(self, bucket: int) -> Dict[str, Dict]:
        path = perfdb.default_path()
        try:
            st = os.stat(path)
            stamp = (path, st.st_mtime_ns, st.st_size)
        except OSError:
            stamp = (path, None, None)
        with self._lock:
            if stamp != self._stamp:
                self._records = perfdb.load_records(
                    path, kind=perfdb.PLACEMENT_KIND
                )
                self._medians = {}
                self._stamp = stamp
            if bucket not in self._medians:
                self._medians[bucket] = perfdb.substrate_medians(
                    self._records, bucket
                )
            return self._medians[bucket]

    def reset(self) -> None:
        with self._lock:
            self._stamp = None
            self._records = []
            self._medians = {}


_PROFILES = _ProfileCache()


def reset_profile_cache() -> None:
    """Drop the cached placement-profile history (tests)."""
    _PROFILES.reset()


def record_outcome(substrate: str, n_reads: int, wall_s: float,
                   phases: Optional[Dict[str, float]] = None,
                   path: Optional[str] = None) -> str:
    """Append one ``placement_profile`` perfdb record for a finished
    job.  Call sites gate on :func:`learned_enabled` so the checked-in
    history is never dirtied by default runs; returns the db path."""
    extra: Dict = {
        "substrate": substrate,
        "n_reads": int(n_reads),
        "reads_bucket": perfdb.reads_bucket(n_reads),
    }
    if phases:
        extra["phases"] = {k: round(float(v), 6)
                           for k, v in phases.items()}
    record = perfdb.make_record(
        perfdb.PLACEMENT_KIND, f"job_wall_s_{substrate}",
        round(float(wall_s), 6), "s", **extra,
    )
    return perfdb.append_record(record, path)


@dataclasses.dataclass(frozen=True)
class PlacementPolicy:
    """Classify admitted jobs by read count and pick their substrate.

    * ``large_read_threshold`` — jobs with at least this many reads are
      mesh candidates; smaller jobs stay on the ragged-arena path.
    * ``mesh_shards`` — requested read-axis shard count for promoted
      jobs (clamped to the devices actually available at placement
      time, pow2-floored).
    """

    large_read_threshold: int = 64
    mesh_shards: int = 2

    def __post_init__(self) -> None:
        if self.large_read_threshold < 1:
            raise ValueError("large_read_threshold must be >= 1")
        if self.mesh_shards < 2:
            raise ValueError(
                "mesh_shards must be >= 2 (1 shard is just the "
                "unsharded engine; use placement=None instead)"
            )

    def classify(self, request: JobRequest) -> str:
        """``"mesh"`` or ``"arena"`` for one job.

        With ``WAFFLE_PLACEMENT_LEARNED`` on, the job's pow2 reads
        bucket is looked up in the perfdb placement profiles and the
        substrate with the lower rolling median decision seconds wins;
        cold history (either substrate under
        :data:`MIN_PROFILE_SAMPLES` samples) falls back to the static
        ``large_read_threshold``."""
        n_reads = len(request.reads)
        if learned_enabled():
            learned = _PROFILES.decide(perfdb.reads_bucket(n_reads))
            if learned is not None:
                return learned
        return (
            "mesh" if n_reads >= self.large_read_threshold
            else "arena"
        )

    def effective_shards(self, n_reads: int, available_devices: int) -> int:
        """Shard count a promoted job actually gets: the policy ask,
        clamped to the device pool and to the job's own read count,
        pow2-floored (so it divides the pow2-padded read axis).  < 2
        means no promotion."""
        return _pow2_floor(
            min(self.mesh_shards, available_devices, max(n_reads, 0))
        )

    def place(self, request: JobRequest,
              available_devices: int) -> Optional[JobRequest]:
        """Return the mesh-promoted request, or ``None`` to leave the
        job on the arena path.

        Declines when: the job is small, the backend is not jax
        (``mesh_shards`` is a jax-scorer feature), the caller already
        pinned an explicit shard count (explicit config wins), or the
        device pool yields fewer than 2 effective shards.
        """
        if self.classify(request) != "mesh":
            return None
        config = request.config
        if config is None or getattr(config, "backend", None) != "jax":
            return None
        if getattr(config, "mesh_shards", 0):
            return None
        shards = self.effective_shards(len(request.reads),
                                       available_devices)
        if shards < 2:
            return None
        return dataclasses.replace(
            request, config=dataclasses.replace(config, mesh_shards=shards)
        )
