"""Runtime lock-order checker: deadlock inversions caught without the
deadlock.

Every lock in the package is created through :func:`make_lock` /
:func:`make_rlock` (machine-enforced by lint rule WL005), each with a
stable creation-site *name* (``"serve.service._lock"``).  With
``WAFFLE_LOCKCHECK=1`` the factories return a :class:`_CheckedLock`
proxy; otherwise they return the plain ``threading`` primitive — the
checker is zero-cost when off, because the decision happens once at
lock *creation*, not per acquire.

The proxy maintains a per-thread stack of held locks and a global
directed graph over lock *names*: a blocking acquire of ``B`` while
holding ``A`` records the edge ``A -> B``.  Before a new edge is added,
a DFS asks whether ``B`` can already reach ``A`` — if so, some other
code path acquires these locks in the opposite order, which is a
potential deadlock even if the two paths never actually collided.  The
checker then dumps both acquisition stacks to the flight recorder and
raises :class:`LockOrderError`.

Design notes:

* Edges are name-level, so two *instances* of the same class lock (for
  example two jobs' ``serve.job._lock``) acquired nested record a
  self-edge ``A -> A``.  Self-edges are recorded but never flagged:
  instance-ordered acquisition of sibling locks is a legitimate
  pattern, and flagging it would be pure false positive.
* Non-blocking acquires (``blocking=False``) never record edges — a
  try-lock cannot participate in a deadlock cycle.
* RLock re-acquisition by the holding thread records nothing (the lock
  is already owned; no new wait-for relation exists).
* The graph's own mutex is a raw ``threading.Lock`` (self-exempt —
  this module is excluded from WL005).
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

from waffle_con_tpu.utils import envspec

__all__ = [
    "LockOrderError", "lockcheck_enabled", "enable_lockcheck",
    "make_lock", "make_rlock", "make_thread", "edges", "reset",
]


class LockOrderError(RuntimeError):
    """Two code paths acquire the same locks in conflicting order."""


#: test override: None -> honor WAFFLE_LOCKCHECK, True/False -> forced
_FORCED: Optional[bool] = None


def lockcheck_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    return envspec.flag("WAFFLE_LOCKCHECK")


def enable_lockcheck(on: bool = True) -> None:
    """Programmatic enable (tests).  Only affects locks created *after*
    the call — module-level locks resolve at import time."""
    global _FORCED
    _FORCED = bool(on)


def reset_enabled() -> None:
    global _FORCED
    _FORCED = None


# ---------------------------------------------------------------------
# global order graph

_graph_mu = threading.Lock()  # raw on purpose: guards the graph itself
#: name -> names acquired while it was held
_graph: Dict[str, Set[str]] = {}
#: (a, b) -> short formatted stack of the acquire that created the edge
_edge_sites: Dict[Tuple[str, str], str] = {}

_tls = threading.local()


def _held_stack() -> List["_CheckedLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _reaches(src: str, dst: str) -> bool:
    """DFS: is there a path src -> ... -> dst in the edge graph?
    Caller holds ``_graph_mu``."""
    seen: Set[str] = set()
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        if node in seen:
            continue
        seen.add(node)
        frontier.extend(_graph.get(node, ()))
    return False


def _acquire_site(skip: int = 3) -> str:
    frames = traceback.format_stack()[:-skip]
    return "".join(frames[-6:])


def edges() -> Set[Tuple[str, str]]:
    """Snapshot of the recorded order edges (test API)."""
    with _graph_mu:
        return {(a, b) for a, succs in _graph.items() for b in succs}


def reset() -> None:
    """Clear the global order graph (test API)."""
    with _graph_mu:
        _graph.clear()
        _edge_sites.clear()


def _record_edges(lock: "_CheckedLock") -> None:
    """Record held -> lock edges; raise on an order inversion."""
    held = _held_stack()
    if not held:
        return
    here: Optional[str] = None
    inversion: Optional[Tuple[str, str, str]] = None
    for prior in held:
        a, b = prior.name, lock.name
        if a == b:
            continue  # sibling instances: instance-ordered, not flagged
        if here is None:
            here = _acquire_site(skip=4)
        with _graph_mu:
            succs = _graph.setdefault(a, set())
            if b in succs:
                continue
            if _reaches(b, a):
                inversion = (a, b, _edge_sites.get((b, a)) or "")
                break
            succs.add(b)
            _edge_sites[(a, b)] = here
    if inversion is None:
        return
    # NOTE: _graph_mu is released here — the flight trigger below
    # acquires (checked) flight locks and must not nest under it
    a, b, other_site = inversion
    held_names = [p.name for p in held]
    message = (
        f"lock-order inversion: acquiring {b!r} while holding {a!r}, "
        f"but an established order already reaches {a!r} from {b!r}\n"
        f"--- established {b!r} -> ... -> {a!r} edge recorded at ---\n"
        f"{other_site}"
        f"--- conflicting acquire of {b!r} (holding {held_names}) "
        f"at ---\n{here}"
    )
    try:  # best-effort flight incident before raising
        from waffle_con_tpu.obs import flight

        flight.trigger(
            "lock_order_inversion",
            holding=a, acquiring=b, held=held_names,
        )
    except Exception:
        pass
    raise LockOrderError(message)


class _CheckedLock:
    """Order-checking proxy over ``threading.Lock``/``RLock``."""

    __slots__ = ("_lock", "name", "_reentrant")

    def __init__(self, lock, name: str, reentrant: bool) -> None:
        self._lock = lock
        self.name = name
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held_stack()
        if blocking and not (
            self._reentrant and any(p is self for p in held)
        ):
            _record_edges(self)
        if timeout == -1:
            ok = self._lock.acquire(blocking)
        else:
            ok = self._lock.acquire(blocking, timeout)
        if ok:
            held.append(self)
        return ok

    def release(self) -> None:
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<_CheckedLock {self.name!r} of {self._lock!r}>"


# ---------------------------------------------------------------------
# factories (the WL005-sanctioned seams)


def make_lock(name: str):
    """A ``threading.Lock``, order-checked when lockcheck is enabled.

    ``name`` is the stable creation-site identity (module.owner); all
    instances created at one site share it, so ordering is checked at
    the class/site level."""
    if lockcheck_enabled():
        return _CheckedLock(threading.Lock(), name, reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock``, order-checked when lockcheck is enabled
    (re-acquisition by the holding thread records no edges)."""
    if lockcheck_enabled():
        return _CheckedLock(threading.RLock(), name, reentrant=True)
    return threading.RLock()


def make_thread(**kwargs) -> threading.Thread:
    """The sanctioned ``threading.Thread`` seam (WL005).  Currently a
    passthrough — one place to hang future thread instrumentation
    (naming, crash funnels) without another tree-wide sweep."""
    return threading.Thread(**kwargs)
