"""AST-based invariant lint for the waffle_con_tpu tree.

The codebase runs on conventions that no generic linter knows about;
each rule here machine-enforces one of them:

=====  ================================================================
WL001  env-registry: every ``os.environ``/``getenv`` *read* of a
       literal ``WAFFLE_*`` key must go through
       ``waffle_con_tpu/utils/envspec.py`` (the registry), and the
       registry must stay doc-synced with the README reference table.
       Writes (``setdefault``/``pop``/assignment) stay direct — tests
       and benches legitimately mutate the environment.
WL002  sync-at-seam: no ``device_get`` / ``block_until_ready`` /
       ``.item()`` in ``models/*`` or the ``ops/ragged.py`` gang
       paths outside the sanctioned ``device_scope`` /
       ``transfer_scope`` / ``DeferredStats`` seams.
WL003  mutation-hook completeness: every method of a declared
       engine class that writes a slot-tracked field must call the
       ``_SpecInjected`` drop hook (deposit invalidation; the PR-10
       contract).
WL004  traced-purity: no ``time.*`` / ``random.*`` / ``print`` inside
       ``@jax.jit`` or ``while_loop``-family bodies in ``ops/``.
WL005  bare-thread/bare-lock: ``threading.Lock`` / ``RLock`` /
       ``Thread`` instances must come from the instrumented
       ``analysis.lockcheck`` factories, so the runtime lock-order
       checker sees every lock.
=====  ================================================================

Escape hatch: ``# waffle-lint: disable=WL00N(reason)`` on the
flagged line (comma-separate multiple rules; the reason is mandatory —
an empty reason does not suppress).  For WL003 the violation anchors at
the method's ``def`` line, so a disable there covers the whole method.

This module is deliberately stdlib-only (``ast``/``re``/``pathlib``)
so ``scripts/waffle_lint.py`` can load it standalone without importing
the package (and therefore without importing jax) — full-tree runtime
stays far under the 10 s CI budget.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Violation", "RULES", "lint_source", "lint_path", "lint_tree",
    "check_env_docs", "iter_python_files",
]

RULES = ("WL001", "WL002", "WL003", "WL004", "WL005")

#: inline escape hatch; reason is mandatory (empty -> no suppression)
_DISABLE_RE = re.compile(
    r"#\s*waffle-lint:\s*disable=([^#]*)"
)
_DISABLE_ITEM_RE = re.compile(r"(WL\d{3})\(([^()]*)\)")

#: WL003 declaration: (path suffix, class) -> (tracked fields, hooks).
#: A method that writes any tracked field must call one of the hooks
#: (``__init__`` is exempt: there is nothing deposited to drop yet).
SLOT_SPECS: Dict[Tuple[str, str], Tuple[Set[str], Set[str]]] = {
    ("ops/jax_scorer.py", "JaxScorer"): (
        {"_state", "_off_host", "_act_host"},
        {"_spec_drop", "_spec_consume"},
    ),
}

#: WL002 scope: models/* always; plus these specific ops files
_WL002_OPS_FILES = ("ops/ragged.py",)
_WL002_SYNC_ATTRS = {"device_get", "block_until_ready", "item"}
_WL002_SANCTIONED_SCOPES = {"device_scope", "transfer_scope"}
_WL002_SANCTIONED_CLASSES = {"DeferredStats"}

_WL004_LOOP_FUNCS = {"while_loop", "fori_loop", "scan", "cond", "switch"}

#: files that ARE the sanctioned seam a rule enforces
_WL001_EXEMPT_SUFFIXES = ("utils/envspec.py",)
_WL005_EXEMPT_SUFFIXES = ("analysis/lockcheck.py",)


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


# ---------------------------------------------------------------------
# disable-comment handling


def _disabled_rules(line_text: str) -> Dict[str, str]:
    """``{rule: reason}`` for a line's disable comment (empty-reason
    entries are dropped — a reason is mandatory)."""
    m = _DISABLE_RE.search(line_text)
    if not m:
        return {}
    return {
        rule: reason.strip()
        for rule, reason in _DISABLE_ITEM_RE.findall(m.group(1))
        if reason.strip()
    }


def _filter_disabled(
    violations: List[Violation], lines: Sequence[str]
) -> List[Violation]:
    out = []
    for v in violations:
        text = lines[v.line - 1] if 0 < v.line <= len(lines) else ""
        if v.rule in _disabled_rules(text):
            continue
        out.append(v)
    return out


# ---------------------------------------------------------------------
# shared AST helpers


def _parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _ancestors(node: ast.AST, parents: Dict[ast.AST, ast.AST]):
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def _call_name(func: ast.AST) -> str:
    """Trailing name of a call target: ``a.b.c(...)`` -> ``c``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _dotted(node: ast.AST) -> str:
    """``a.b.c`` for a Name/Attribute chain, else ``""``."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_environ(node: ast.AST) -> bool:
    return _dotted(node) in ("os.environ", "environ")


def _literal_waffle_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value.startswith("WAFFLE_"):
            return node.value
    return None


# ---------------------------------------------------------------------
# WL001 env-registry


def _check_wl001(path: str, tree: ast.AST,
                 parents: Dict[ast.AST, ast.AST]) -> List[Violation]:
    if path.endswith(_WL001_EXEMPT_SUFFIXES):
        return []
    out: List[Violation] = []

    def flag(node: ast.AST, key: str, how: str) -> None:
        out.append(Violation(
            "WL001", path, node.lineno,
            f"direct env read of {key} via {how}; use "
            f"waffle_con_tpu.utils.envspec (get_raw/flag/get_int/"
            f"get_float)",
        ))

    for node in ast.walk(tree):
        # os.environ.get("WAFFLE_X") / os.getenv("WAFFLE_X")
        if isinstance(node, ast.Call):
            func = node.func
            name = _call_name(func)
            if name == "get" and isinstance(func, ast.Attribute) \
                    and _is_environ(func.value) and node.args:
                key = _literal_waffle_key(node.args[0])
                if key:
                    flag(node, key, "environ.get")
            elif name == "getenv" and node.args:
                target = _dotted(func)
                if target in ("os.getenv", "getenv"):
                    key = _literal_waffle_key(node.args[0])
                    if key:
                        flag(node, key, "getenv")
        # os.environ["WAFFLE_X"] in Load context (reads only)
        elif isinstance(node, ast.Subscript) and _is_environ(node.value):
            if isinstance(node.ctx, ast.Load):
                key = _literal_waffle_key(node.slice)
                if key:
                    flag(node, key, "environ[...]")
        # "WAFFLE_X" in os.environ
        elif isinstance(node, ast.Compare):
            if any(isinstance(op, (ast.In, ast.NotIn))
                   for op in node.ops) and node.comparators \
                    and _is_environ(node.comparators[0]):
                key = _literal_waffle_key(node.left)
                if key:
                    flag(node, key, "membership test")
    return out


def check_env_docs(readme_text: str,
                   registered: Iterable[str],
                   readme_path: str = "README.md") -> List[Violation]:
    """WL001 doc-sync: registry <-> README, both directions."""
    registered = set(registered)
    mentioned = set(re.findall(r"\bWAFFLE_[A-Z0-9_]+", readme_text))
    out: List[Violation] = []
    for name in sorted(registered - mentioned):
        out.append(Violation(
            "WL001", readme_path, 1,
            f"registered knob {name} is missing from the README "
            f"reference table (run scripts/waffle_lint.py --env-table)",
        ))
    for name in sorted(mentioned - registered):
        out.append(Violation(
            "WL001", readme_path, 1,
            f"README documents {name} but it is not registered in "
            f"utils/envspec.py (stale doc, or register the knob)",
        ))
    return out


# ---------------------------------------------------------------------
# WL002 sync-at-seam


def _wl002_in_scope(path: str) -> bool:
    norm = path.replace("\\", "/")
    if "/models/" in norm and norm.endswith(".py"):
        return True
    return norm.endswith(_WL002_OPS_FILES)


def _wl002_sanctioned(node: ast.AST,
                      parents: Dict[ast.AST, ast.AST]) -> bool:
    for anc in _ancestors(node, parents):
        if isinstance(anc, ast.With):
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and \
                        _call_name(expr.func) in _WL002_SANCTIONED_SCOPES:
                    return True
        elif isinstance(anc, ast.ClassDef) and \
                anc.name in _WL002_SANCTIONED_CLASSES:
            return True
    return False


def _check_wl002(path: str, tree: ast.AST,
                 parents: Dict[ast.AST, ast.AST]) -> List[Violation]:
    if not _wl002_in_scope(path):
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name not in _WL002_SYNC_ATTRS:
            continue
        if _wl002_sanctioned(node, parents):
            continue
        out.append(Violation(
            "WL002", path, node.lineno,
            f"host sync `{name}` outside a sanctioned seam; wrap in "
            f"_phases.device_scope / _phases.transfer_scope (or defer "
            f"via DeferredStats)",
        ))
    return out


# ---------------------------------------------------------------------
# WL003 mutation-hook completeness


def _writes_tracked_field(fn: ast.AST, fields: Set[str]) -> Set[str]:
    written: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                base = target
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self" and base.attr in fields:
                    written.add(base.attr)
    return written


def _calls_hook(fn: ast.AST, hooks: Set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self" and \
                node.func.attr in hooks:
            return True
    return False


def _check_wl003(path: str, tree: ast.AST,
                 parents: Dict[ast.AST, ast.AST]) -> List[Violation]:
    norm = path.replace("\\", "/")
    specs = [(cls, spec) for (suffix, cls), spec in SLOT_SPECS.items()
             if norm.endswith(suffix)]
    if not specs:
        return []
    out: List[Violation] = []
    by_class = dict(specs)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) or node.name not in by_class:
            continue
        fields, hooks = by_class[node.name]
        for fn in node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue  # nothing deposited yet at construction
            written = _writes_tracked_field(fn, fields)
            if written and not _calls_hook(fn, hooks):
                out.append(Violation(
                    "WL003", path, fn.lineno,
                    f"{node.name}.{fn.name} writes slot-tracked "
                    f"{sorted(written)} without calling a deposit drop "
                    f"hook ({'/'.join(sorted(hooks))})",
                ))
    return out


# ---------------------------------------------------------------------
# WL004 traced-purity


def _is_jit_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        # partial(jax.jit, ...) / jax.jit(...) / jit(...)
        if _call_name(dec.func) in ("jit", "partial"):
            if _call_name(dec.func) == "partial":
                return bool(dec.args) and \
                    _call_name(dec.args[0]) == "jit"
            return True
        return False
    return _call_name(dec) == "jit" or _dotted(dec).endswith(".jit")


def _traced_roots(tree: ast.AST) -> List[ast.AST]:
    """jit-decorated defs plus functions handed to while_loop-family
    combinators (by local name or inline lambda)."""
    roots: List[ast.AST] = []
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                roots.append(node)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _call_name(node.func) in _WL004_LOOP_FUNCS:
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    roots.append(arg)
                elif isinstance(arg, ast.Name) and \
                        arg.id in defs_by_name:
                    roots.extend(defs_by_name[arg.id])
    return roots


def _check_wl004(path: str, tree: ast.AST,
                 parents: Dict[ast.AST, ast.AST]) -> List[Violation]:
    norm = path.replace("\\", "/")
    if "/ops/" not in norm and not norm.startswith("ops/"):
        return []
    out: List[Violation] = []
    seen: Set[int] = set()
    for root in _traced_roots(tree):
        for node in ast.walk(root):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            dotted = _dotted(node.func)
            bad = None
            if dotted.startswith("time.") or dotted.startswith("random."):
                bad = dotted
            elif isinstance(node.func, ast.Name) and \
                    node.func.id == "print":
                bad = "print"
            if bad:
                seen.add(id(node))
                out.append(Violation(
                    "WL004", path, node.lineno,
                    f"impure call `{bad}` inside a traced "
                    f"(jit/while_loop) body",
                ))
    return out


# ---------------------------------------------------------------------
# WL005 bare-thread/bare-lock


def _check_wl005(path: str, tree: ast.AST,
                 parents: Dict[ast.AST, ast.AST]) -> List[Violation]:
    if path.endswith(_WL005_EXEMPT_SUFFIXES):
        return []
    # names imported straight off threading ("from threading import X")
    bare: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            for alias in node.names:
                if alias.name in ("Lock", "RLock", "Thread"):
                    bare.add(alias.asname or alias.name)
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        kind = None
        if dotted in ("threading.Lock", "threading.RLock",
                      "threading.Thread"):
            kind = dotted.split(".")[1]
        elif isinstance(node.func, ast.Name) and node.func.id in bare:
            kind = node.func.id
        if kind:
            wrapper = {"Lock": "make_lock", "RLock": "make_rlock",
                       "Thread": "make_thread"}[kind]
            out.append(Violation(
                "WL005", path, node.lineno,
                f"bare threading.{kind}; use analysis.lockcheck."
                f"{wrapper} so the lock-order checker sees it",
            ))
    return out


# ---------------------------------------------------------------------
# drivers

_CHECKS = (_check_wl001, _check_wl002, _check_wl003, _check_wl004,
           _check_wl005)


def lint_source(source: str, path: str,
                rules: Optional[Iterable[str]] = None) -> List[Violation]:
    """Lint one source blob; ``path`` determines rule scoping."""
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Violation("WL000", path, exc.lineno or 1,
                          f"syntax error: {exc.msg}")]
    parents = _parents(tree)
    active = set(rules) if rules is not None else set(RULES)
    violations: List[Violation] = []
    for check in _CHECKS:
        rule = check.__name__[-5:].upper()
        if rule in active:
            violations.extend(check(path, tree, parents))
    violations = _filter_disabled(violations, source.splitlines())
    return sorted(violations, key=lambda v: (v.path, v.line, v.rule))


def lint_path(path: Path, root: Optional[Path] = None,
              rules: Optional[Iterable[str]] = None) -> List[Violation]:
    rel = str(path.relative_to(root)) if root else str(path)
    return lint_source(path.read_text(), rel, rules)


#: tree scan roots, relative to the repo root
SCAN_ROOTS = ("waffle_con_tpu", "scripts", "bench.py", "conftest.py")
#: pruned anywhere they appear
SKIP_PARTS = {"tests", "__pycache__", ".git", "evidence"}


def iter_python_files(root: Path) -> List[Path]:
    files: List[Path] = []
    for entry in SCAN_ROOTS:
        target = root / entry
        if target.is_file():
            files.append(target)
        elif target.is_dir():
            for p in sorted(target.rglob("*.py")):
                if not SKIP_PARTS.intersection(p.parts):
                    files.append(p)
    return files


def lint_tree(root: Path,
              rules: Optional[Iterable[str]] = None) -> List[Violation]:
    violations: List[Violation] = []
    for path in iter_python_files(root):
        violations.extend(lint_path(path, root=root, rules=rules))
    return violations
