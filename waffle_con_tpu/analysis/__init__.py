"""Static analysis (invariant lint) + runtime lock-order checking.

* :mod:`waffle_con_tpu.analysis.lint` — the AST rule engine behind
  ``scripts/waffle_lint.py`` (rules WL001-WL005).
* :mod:`waffle_con_tpu.analysis.lockcheck` — instrumented
  ``Lock``/``RLock``/``Thread`` factories; with ``WAFFLE_LOCKCHECK=1``
  they record per-thread acquisition chains and raise on a cyclic
  lock order (potential deadlock inversion).
"""
