"""Incremental ("dynamic") edit-distance wavefront alignment.

:class:`DWFALite` maintains the anti-diagonal wavefront of an edit-distance
WFA between a fixed ``baseline`` sequence (a read) and a growing ``other``
sequence (the consensus being built).  Appending one symbol to ``other``
re-extends the wavefront and raises the edit distance only when forced.

This is the capability-parity equivalent of the reference kernel
(``/root/reference/src/dynamic_wfa.rs:13-265``); it is also the executable
specification for the batched JAX scorer in
:mod:`waffle_con_tpu.ops.jax_scorer` and the C++ kernel in
``waffle_con_tpu/native`` — all three must agree exactly (integer edit
distances), which the parity tests assert.

Mental model: diagonals are indexed by ``k = (other consumed) - (baseline
consumed)``, with ``k`` ranging over ``[-e, +e]`` at edit distance ``e``.
The stored value per diagonal is the number of bases consumed in ``other``
(beyond ``offset``); the baseline position of a diagonal is then simply
``d - k``.  Both sequences live *outside* this object and must be passed
into every call; only appends to ``other`` are legal between calls.
"""

from __future__ import annotations

from typing import Dict, Optional


class DWFAError(Exception):
    """Raised on illegal state transitions (e.g. update after finalize)."""


class DWFALite:
    """Single-pair incremental WFA state.

    Parameters
    ----------
    wildcard:
        Optional byte value that matches anything when it appears in the
        *baseline* sequence.
    allow_early_termination:
        When true, ``update`` stops escalating edit distance once the
        wavefront reaches the end of the baseline, so consensus growth past
        a short read costs nothing.
    """

    __slots__ = (
        "edit_distance",
        "wavefront",
        "is_finalized",
        "wildcard",
        "allow_early_termination",
        "offset",
    )

    def __init__(
        self,
        wildcard: Optional[int] = None,
        allow_early_termination: bool = False,
    ) -> None:
        self.edit_distance: int = 0
        # wavefront[i] is the diagonal k = i - edit_distance; value = bases
        # consumed in `other` (beyond `offset`).  Always length 2e+1.
        self.wavefront = [0]
        self.is_finalized = False
        self.wildcard = wildcard
        self.allow_early_termination = allow_early_termination
        self.offset = 0

    # ------------------------------------------------------------------
    # lifecycle

    def set_offset(self, offset: int) -> None:
        """Ignore the first ``offset`` characters of ``other`` entirely, as
        if the alignment began there (late-starting reads)."""
        self.offset = offset

    def clone(self) -> "DWFALite":
        dup = DWFALite.__new__(DWFALite)
        dup.edit_distance = self.edit_distance
        dup.wavefront = list(self.wavefront)
        dup.is_finalized = self.is_finalized
        dup.wildcard = self.wildcard
        dup.allow_early_termination = self.allow_early_termination
        dup.offset = self.offset
        return dup

    def state_key(self):
        """Hashable full-state identity (used for search-node dedup)."""
        return (
            self.edit_distance,
            tuple(self.wavefront),
            self.is_finalized,
            self.offset,
        )

    def __eq__(self, rhs) -> bool:
        return (
            isinstance(rhs, DWFALite)
            and self.edit_distance == rhs.edit_distance
            and self.wavefront == rhs.wavefront
            and self.is_finalized == rhs.is_finalized
            and self.wildcard == rhs.wildcard
            and self.allow_early_termination == rhs.allow_early_termination
            and self.offset == rhs.offset
        )

    def __hash__(self) -> int:
        return hash(self.state_key())

    # ------------------------------------------------------------------
    # core updates

    def update(self, baseline: bytes, other: bytes) -> int:
        """Account for newly appended ``other`` symbols: greedily extend all
        diagonals, escalating edit distance until some diagonal consumes all
        of ``other`` (or, with early termination, the baseline is exhausted).

        Returns the current edit distance.
        """
        if self.is_finalized:
            raise DWFAError("Cannot push more bases after finalizing a DWFA")

        self._extend(baseline, other)
        target = len(other)
        while self.maximum_other_distance() < target and not (
            self.allow_early_termination and self.reached_baseline_end(baseline)
        ):
            self._increase_edit_distance(baseline, other)

        assert self.maximum_other_distance() == target or (
            self.allow_early_termination
            and self.maximum_baseline_distance() == len(baseline)
        )
        return self.edit_distance

    def _extend(self, baseline: bytes, other: bytes) -> None:
        """Greedy furthest-reaching extension of every diagonal."""
        wf = self.wavefront
        e = self.edit_distance
        off = self.offset
        blen = len(baseline)
        olen = len(other)
        wc = self.wildcard
        for i in range(len(wf)):
            d = wf[i]
            k = i - e  # diagonal: other-consumed minus baseline-consumed
            # baseline position for this diagonal is d - k
            bo = d - k
            oo = d + off
            while bo < blen and oo < olen:
                b = baseline[bo]
                if b != other[oo] and b != wc:
                    break
                d += 1
                bo += 1
                oo += 1
            wf[i] = d

    def _increase_edit_distance(self, baseline: bytes, other: bytes) -> None:
        """Grow the wavefront by one edit: each new diagonal takes the best
        of a baseline-skip (value unchanged, from diagonal ``k+1``), a
        mismatch (value+1, same ``k``) or an other-insertion (value+1, from
        ``k-1``); then re-extend."""
        if self.is_finalized:
            raise DWFAError("Cannot increase edit distance after finalizing a DWFA")
        old = self.wavefront
        n = len(old)
        self.edit_distance += 1
        new = [0] * (n + 2)
        for i, d in enumerate(old):
            # deletion of a baseline base: same other-consumption
            if d > new[i]:
                new[i] = d
            # mismatch: consume one of each
            if d + 1 > new[i + 1]:
                new[i + 1] = d + 1
            # insertion into baseline: consume one more of other
            if d + 1 > new[i + 2]:
                new[i + 2] = d + 1
        self.wavefront = new
        self._extend(baseline, other)

    def finalize(self, baseline: bytes, other: bytes) -> None:
        """Signal that ``other`` is complete: escalate until the wavefront
        reaches the end of the baseline, charging for any unmatched tail."""
        if self.is_finalized:
            raise DWFAError("Cannot finalize a DWFA twice.")
        blen = len(baseline)
        while self.maximum_baseline_distance() < blen:
            self._increase_edit_distance(baseline, other)

    # ------------------------------------------------------------------
    # queries

    def maximum_baseline_distance(self) -> int:
        """Farthest position reached in ``baseline`` over all diagonals."""
        e = self.edit_distance
        return max(d - (i - e) for i, d in enumerate(self.wavefront))

    def maximum_other_distance(self) -> int:
        """Farthest position reached in ``other`` (including the offset)."""
        return self.offset + max(self.wavefront)

    def reached_baseline_end(self, baseline: bytes) -> bool:
        return self.maximum_baseline_distance() == len(baseline)

    def get_extension_candidates(
        self, baseline: bytes, other: bytes
    ) -> Dict[int, int]:
        """Next-symbol votes: for every diagonal whose ``other`` consumption
        is exactly at the end, the baseline character it faces is a
        candidate; returns ``{byte: tip_count}``."""
        votes: Dict[int, int] = {}
        e = self.edit_distance
        off = self.offset
        olen = len(other)
        blen = len(baseline)
        for i, d in enumerate(self.wavefront):
            if d + off == olen:
                bo = d - (i - e)
                if bo < blen:
                    c = baseline[bo]
                    votes[c] = votes.get(c, 0) + 1
        return votes
