"""The ``WavefrontScorer`` seam between the host search engines and the
alignment kernels.

This is the boundary the north star mandates (see SURVEY.md §7): the
engines (``models/``) own the least-cost-first search — priority queue,
thresholds, candidate nomination, activation — and talk to per-*branch*
wavefront state only through this interface.  A branch is one consensus
hypothesis (one side of a dual node); its state is one incremental DWFA
per tracked read.

Implementations:

* :class:`PythonScorer` (here) — one :class:`~waffle_con_tpu.ops.dwfa.DWFALite`
  object per (branch, read); the executable-specification oracle.
* ``JaxScorer`` (:mod:`waffle_con_tpu.ops.jax_scorer`) — all branches and
  reads batched in device arrays, advanced by fused XLA kernels, reads
  shardable across a TPU mesh.
* ``NativeScorer`` (``waffle_con_tpu/native``) — C++ kernels, the fast
  serial-CPU path mirroring the reference's performance envelope.

All implementations must agree exactly: integer edit distances, integer
tip-vote counts (the engines do the fractional-vote arithmetic host-side
in read order so float summation order is identical on every backend —
cf. ``/root/reference/src/consensus.rs:546-552``).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.ops.alignment import wfa_ed_config
from waffle_con_tpu.ops.dwfa import DWFALite
from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec


class BranchStats:
    """Per-branch observation snapshot returned by scorer calls.

    Attributes (``R`` reads, ``A`` dense symbols):

    * ``eds`` — ``[R] int64`` current edit distance per read (0 if
      untracked).
    * ``occ`` — ``[R, A] int64`` tip votes: how many wavefront tips of
      read ``r`` nominate dense symbol ``a`` as the next consensus base.
    * ``split`` — ``[R] int64`` total tips per read (vote normalizer).
    * ``reached`` — ``[R] bool`` whether the read's wavefront has touched
      the end of its baseline (False if untracked).
    * ``fin`` — optional ``[R] int64`` finalized distances at this
      position, bundled by scorers whose snapshot dispatch can compute
      them for free (``None`` when unknown or out of band).
    """

    __slots__ = ("eds", "occ", "split", "reached", "fin")

    def __init__(self, eds, occ, split, reached, fin=None):
        self.eds = eds
        self.occ = occ
        self.split = split
        self.reached = reached
        self.fin = fin


class DeferredStats(BranchStats):
    """A :class:`BranchStats` whose bulk arrays have not crossed the
    device boundary yet — the async dispatch seam.

    Device run calls return two kinds of results: *control* scalars
    (steps, stop code, appended symbols) the engine needs immediately
    for its pop/constrict/insert bookkeeping, and *bulk* observation
    arrays (eds/occ/split/reached/fin) it only reads at the branch's
    NEXT pop.  Wrapping the bulk half in a ``DeferredStats`` lets the
    scorer skip that part of ``block_until_ready``/``device_get`` at
    dispatch time: the transfer + numpy conversion happen lazily on
    first field access, and everything the host did in between —
    bookkeeping for run *i*, queue work, even dispatching run *i+1* —
    overlapped with it.  The elapsed creation→resolution time is
    accounted as ``host_overlap_s`` (:func:`host_overlap_total`).

    Composition rules (the seam's safety contract):

    * the supervisor's dispatch validation touches ``eds``/``occ``/
      ``split``, so a supervised dispatch resolves INSIDE the policy
      boundary — timeouts, garbage injection, and demotion attribute to
      the right dispatch (resolution later than the boundary would blame
      the wrong one);
    * the serve-path ``CoalescingScorer`` calls :func:`resolve_stats`
      before results cross the dispatcher→worker thread hop, falling
      through to fully synchronous semantics when coalescing is active;
    * everything else duck-types as a plain :class:`BranchStats`
      (``isinstance`` included) and resolves transparently.
    """

    __slots__ = ("_fetch", "_value", "_t0", "_phase_rec")

    def __init__(self, fetch) -> None:
        # no super().__init__: the parent's slot storage stays unused and
        # every field access routes through the properties below
        self._fetch = fetch
        self._value: Optional[BranchStats] = None
        ph = _phases_mod()
        # the originating dispatch's phase record, so the eventual fetch
        # is attributed to IT as transfer time (possibly "late", after
        # the dispatch returned) — None whenever profiling is off
        self._phase_rec = ph.current() if ph.profiling_enabled() else None
        self._t0 = time.perf_counter()

    def resolve(self) -> BranchStats:
        """Force the device fetch; idempotent."""
        if self._value is None:
            _note_overlap(time.perf_counter() - self._t0)
            rec, self._phase_rec = self._phase_rec, None
            if rec is not None:
                t0 = time.perf_counter()
                self._value = self._fetch()
                rec.add_transfer(time.perf_counter() - t0, t0)
            else:
                self._value = self._fetch()
            self._fetch = None
        return self._value

    # field access resolves; assignment (the fault injector's garbage
    # payload mutates stats in place) resolves then writes through
    def _get(name):  # noqa: N805 - descriptor factory, not a method
        def getter(self):
            return getattr(self.resolve(), name)

        def setter(self, value):
            setattr(self.resolve(), name, value)

        return property(getter, setter)

    eds = _get("eds")
    occ = _get("occ")
    split = _get("split")
    reached = _get("reached")
    fin = _get("fin")
    del _get


#: lazily bound ``waffle_con_tpu.obs.phases`` module — a module-top
#: import would cycle (obs.report imports this module); the cached ref
#: keeps the per-DeferredStats cost at one global lookup
_PHASES = None


def _phases_mod():
    global _PHASES
    if _PHASES is None:
        from waffle_con_tpu.obs import phases

        _PHASES = phases
    return _PHASES


#: process-wide overlap accounting: seconds of host work that ran while
#: a deferred result was still un-fetched (see ``DeferredStats``)
_overlap_lock = lockcheck.make_lock("ops.scorer.OVERLAP")
_overlap_total = 0.0


def _note_overlap(seconds: float) -> None:
    global _overlap_total
    with _overlap_lock:
        _overlap_total += seconds
    try:  # metrics are optional; never let accounting break a dispatch
        from waffle_con_tpu.obs.metrics import metrics_enabled, registry

        if metrics_enabled():
            registry().counter("waffle_host_overlap_seconds_total").inc(
                seconds
            )
    except Exception:  # noqa: BLE001 - pure observability
        pass


def host_overlap_total() -> float:
    """Cumulative ``host_overlap_s``: how long deferred run results
    stayed un-fetched while the host did other work (bench evidence
    reads the delta around a run)."""
    with _overlap_lock:
        return _overlap_total


def resolve_stats(obj):
    """Force every :class:`DeferredStats` reachable in a dispatch result
    (returns ``obj`` unchanged otherwise).  The serve path calls this
    before a result crosses a thread boundary — deferral is only safe
    while the consumer is the dispatching thread."""
    if isinstance(obj, DeferredStats):
        obj.resolve()
    elif isinstance(obj, (list, tuple)):
        for x in obj:
            resolve_stats(x)
    return obj


def deferred_sync_enabled() -> bool:
    """Whether scorers may return :class:`DeferredStats`
    (``WAFFLE_ASYNC_SYNC``, default on; ``0`` forces the old eager
    fetch everywhere)."""
    return envspec.get_raw("WAFFLE_ASYNC_SYNC", "1") != "0"


def megastep_enabled() -> bool:
    """Whether the engines' pop loop engages the MEGASTEP run path
    (``WAFFLE_MEGASTEP``, default on; ``0`` restores plain
    ``run_extend`` stepping).  Read when a scorer's ``run_mega``
    capability property is resolved (each fresh engine / ``fast_paths``
    snapshot), so tests flipping it per-search see it; results are
    bit-identical either way — the knob trades per-pop host round
    trips against kernel variety (one extra compile per geometry)."""
    return envspec.get_raw("WAFFLE_MEGASTEP", "1") != "0"


#: counter names that each correspond to one blocking device dispatch;
#: the dispatch-evidence script and the regression tests sum these so
#: the budget they enforce is the same quantity the evidence records
DISPATCH_COUNTER_KEYS = (
    "push_calls", "run_calls", "stats_calls", "clone_calls",
    "clone_push_calls", "activate_calls", "finalize_calls",
    "arena_calls", "run_dual_calls",
)


def build_symbol_table(reads: Sequence[bytes], wildcard: Optional[int]) -> np.ndarray:
    """Dense symbol table: sorted distinct bytes over all reads (plus the
    wildcard if configured).  Index in this array == dense id."""
    symbols = set()
    for read in reads:
        symbols.update(read)
    if wildcard is not None:
        symbols.add(wildcard)
    return np.array(sorted(symbols), dtype=np.int64)


def find_activation_offset(
    consensus: bytes,
    sequence: bytes,
    offset_window: int,
    offset_compare_length: int,
    wildcard: Optional[int],
) -> int:
    """Search the tail window of ``consensus`` for the best starting offset
    of a late-activating read (parity with
    ``/root/reference/src/consensus.rs:413-448``): prefix-mode WFA of the
    read's head against every window position, first-best wins with the
    window midpoint as the incumbent."""
    cmp_len = min(offset_compare_length, len(sequence))
    con_len = len(consensus)
    start_position = max(0, con_len - (offset_window + cmp_len))
    end_position = max(0, con_len - cmp_len)

    best_offset = max(0, con_len - (cmp_len + offset_window // 2))
    head = sequence[:cmp_len]
    min_ed = wfa_ed_config(consensus[best_offset:], head, False, wildcard)
    for p in range(start_position, end_position):
        ed = wfa_ed_config(consensus[p:], head, False, wildcard)
        if ed < min_ed:
            min_ed = ed
            best_offset = p
    return best_offset


class WavefrontScorer:
    """Abstract branch-store interface. Handles are opaque integers."""

    def __init__(self, reads: Sequence[bytes], config: CdwfaConfig) -> None:
        self.reads = [bytes(r) for r in reads]
        self.config = config
        self.symtab = build_symbol_table(self.reads, config.wildcard)
        self.sym_id: Dict[int, int] = {
            int(s): i for i, s in enumerate(self.symtab)
        }
        #: dispatch accounting (see ``DISPATCH_COUNTER_KEYS``); device
        #: backends extend this with their own keys, and the runtime
        #: watchdog enforces budgets over it
        self.counters: Dict[str, int] = {}

    @property
    def num_reads(self) -> int:
        return len(self.reads)

    @property
    def num_symbols(self) -> int:
        return len(self.symtab)

    def best_activation_offset(
        self,
        consensus: bytes,
        seq_index: int,
        offset_window: int,
        offset_compare_length: int,
        wildcard: Optional[int],
    ) -> int:
        """Best starting offset for a late-activating read (see
        :func:`find_activation_offset`); device backends batch the whole
        window into one kernel call."""
        return find_activation_offset(
            consensus, self.reads[seq_index], offset_window,
            offset_compare_length, wildcard,
        )

    # -- branch lifecycle ------------------------------------------------
    def root(self, active: np.ndarray) -> int:
        raise NotImplementedError

    def clone(self, h: int) -> int:
        raise NotImplementedError

    def clone_many(self, hs: List[int]) -> List[int]:
        """Batched :meth:`clone`; backends override to fuse into one
        device call."""
        return [self.clone(h) for h in hs]

    def free(self, h: int) -> None:
        raise NotImplementedError

    # -- state evolution -------------------------------------------------
    def push(self, h: int, consensus: bytes) -> BranchStats:
        """``consensus`` must be the branch's previous consensus plus
        exactly one appended symbol; advances every tracked read."""
        raise NotImplementedError

    def push_many(
        self, specs: List[Tuple[int, bytes]]
    ) -> List[BranchStats]:
        """Batched :meth:`push` over ``(handle, consensus)`` pairs; backends
        override to fuse into one device call."""
        return [self.push(h, consensus) for h, consensus in specs]

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        """Recompute the snapshot without mutating state."""
        raise NotImplementedError

    def activate(self, h: int, read_index: int, offset: int, consensus: bytes) -> None:
        """Begin tracking ``read_index`` with the given consensus offset and
        catch its wavefront up to the current consensus."""
        raise NotImplementedError

    def deactivate(self, h: int, read_index: int) -> None:
        """Stop tracking a read (dual-mode divergence pruning)."""
        raise NotImplementedError

    def deactivate_many(self, pairs: List[Tuple[int, int]]) -> None:
        """Batched :meth:`deactivate` over ``(handle, read_index)`` pairs."""
        for h, read_index in pairs:
            self.deactivate(h, read_index)

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        """Edit distances after forcing every tracked read's wavefront to
        the end of its baseline — computed on a scratch copy, the branch
        itself is not mutated.  Untracked reads report 0."""
        raise NotImplementedError


class PythonScorer(WavefrontScorer):
    """Reference oracle: per-(branch, read) ``DWFALite`` objects."""

    def __init__(self, reads: Sequence[bytes], config: CdwfaConfig) -> None:
        super().__init__(reads, config)
        self._branches: Dict[int, List[Optional[DWFALite]]] = {}
        self._next = 0

    def _new_handle(self, dwfas: List[Optional[DWFALite]]) -> int:
        h = self._next
        self._next += 1
        self._branches[h] = dwfas
        return h

    def root(self, active: np.ndarray) -> int:
        cfg = self.config
        dwfas: List[Optional[DWFALite]] = [
            DWFALite(cfg.wildcard, cfg.allow_early_termination) if a else None
            for a in active
        ]
        return self._new_handle(dwfas)

    def clone(self, h: int) -> int:
        self._count("clone_calls")
        return self._new_handle(
            [dw.clone() if dw is not None else None for dw in self._branches[h]]
        )

    def free(self, h: int) -> None:
        self._branches.pop(h, None)

    def live_handles(self) -> Tuple[int, Optional[int]]:
        """(live handle count, slot capacity); the oracle's handle store
        is an unbounded dict, so capacity is ``None``."""
        return len(self._branches), None

    def _count(self, key: str) -> None:
        self.counters[key] = self.counters.get(key, 0) + 1

    def push(self, h: int, consensus: bytes) -> BranchStats:
        self._count("push_calls")
        dwfas = self._branches[h]
        for read, dw in zip(self.reads, dwfas):
            if dw is not None:
                dw.update(read, consensus)
        return self._snapshot(dwfas, consensus)

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        self._count("stats_calls")
        return self._snapshot(self._branches[h], consensus)

    def activate(self, h: int, read_index: int, offset: int, consensus: bytes) -> None:
        self._count("activate_calls")
        dwfas = self._branches[h]
        assert dwfas[read_index] is None
        cfg = self.config
        dw = DWFALite(cfg.wildcard, cfg.allow_early_termination)
        dw.set_offset(offset)
        dw.update(self.reads[read_index], consensus)
        dwfas[read_index] = dw

    def deactivate(self, h: int, read_index: int) -> None:
        self._branches[h][read_index] = None

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        self._count("finalize_calls")
        eds = np.zeros(self.num_reads, dtype=np.int64)
        for r, dw in enumerate(self._branches[h]):
            if dw is not None:
                scratch = dw.clone()
                scratch.finalize(self.reads[r], consensus)
                eds[r] = scratch.edit_distance
        return eds

    # -----------------------------------------------------------------
    def _snapshot(
        self, dwfas: List[Optional[DWFALite]], consensus: bytes
    ) -> BranchStats:
        n = self.num_reads
        a = self.num_symbols
        eds = np.zeros(n, dtype=np.int64)
        occ = np.zeros((n, a), dtype=np.int64)
        split = np.zeros(n, dtype=np.int64)
        reached = np.zeros(n, dtype=bool)
        for r, dw in enumerate(dwfas):
            if dw is None:
                continue
            read = self.reads[r]
            eds[r] = dw.edit_distance
            reached[r] = dw.reached_baseline_end(read)
            votes = dw.get_extension_candidates(read, consensus)
            total = 0
            for sym, count in votes.items():
                occ[r, self.sym_id[sym]] = count
                total += count
            split[r] = total
        return BranchStats(eds, occ, split, reached)


class SubsetScorer(WavefrontScorer):
    """View of a shared base scorer restricted to a subset of its reads.

    The priority engine re-runs the dual engine once per worklist group
    over subsets of the same level's sequences
    (``/root/reference/src/priority_consensus.rs:172-341`` re-creates the
    whole engine per group).  Building a fresh device scorer per group
    would re-upload the reads and re-compile every kernel for the group's
    geometry; this adapter instead maps a group onto a scorer built ONCE
    over the full read set — group membership is just the root
    activation mask, and per-read observations are gathered back to the
    group's local index space with numpy fancy indexing.

    Device-state semantics are unchanged: untracked (non-member) reads
    are inactive lanes, exactly as pruned reads already are, so results
    are bit-identical to a per-group scorer.
    """

    def __init__(self, base: WavefrontScorer, indices: Sequence[int]) -> None:
        self.base = base
        self.indices = np.asarray(list(indices), dtype=np.int64)
        self.reads = [base.reads[i] for i in self.indices]
        self.config = base.config
        self.symtab = base.symtab
        self.sym_id = base.sym_id

    @property
    def ARENA_CAP(self):
        return self.base.ARENA_CAP

    @property
    def ARENA_K(self):
        return self.base.ARENA_K

    @property
    def ARENA_CRE_PER_EVENT(self):
        return getattr(self.base, "ARENA_CRE_PER_EVENT", 0)

    @property
    def ARENA_TAKE_MAX(self):
        return getattr(self.base, "ARENA_TAKE_MAX", self.base.ARENA_K - 1)

    @property
    def counters(self):
        return getattr(self.base, "counters", {})

    @property
    def fastpath_gen(self):
        # forwarded so a supervised base's demotion invalidates any
        # fast_paths() snapshot taken over this view (see fast_paths)
        return getattr(self.base, "fastpath_gen", 0)

    def ragged_run_probe(self, h: int):
        # handles ARE base handles (run_extend forwards them verbatim),
        # so ragged/frontier ganging hops straight through the view
        inner = getattr(self.base, "ragged_run_probe", None)
        return inner(h) if inner is not None else None

    def _slice(self, stats: BranchStats) -> BranchStats:
        idx = self.indices
        return BranchStats(
            stats.eds[idx],
            stats.occ[idx],
            stats.split[idx],
            stats.reached[idx],
            stats.fin[idx] if stats.fin is not None else None,
        )

    # -- branch lifecycle ----------------------------------------------
    def root(self, active: np.ndarray) -> int:
        full = np.zeros(self.base.num_reads, dtype=bool)
        full[self.indices] = np.asarray(active, dtype=bool)
        return self.base.root(full)

    def clone(self, h: int) -> int:
        return self.base.clone(h)

    def clone_many(self, hs: List[int]) -> List[int]:
        return self.base.clone_many(hs)

    def free(self, h: int) -> None:
        self.base.free(h)

    # -- state evolution -----------------------------------------------
    def push(self, h: int, consensus: bytes) -> BranchStats:
        return self._slice(self.base.push(h, consensus))

    def push_many(
        self, specs: List[Tuple[int, bytes]]
    ) -> List[BranchStats]:
        return [self._slice(s) for s in self.base.push_many(specs)]

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        return self._slice(self.base.stats(h, consensus))

    @property
    def clone_push_many(self):
        # engines feature-test the fast paths with getattr(..., None)
        # EVERY pop; forwarding dynamically (rather than shadowing at
        # construction) keeps this view correct when a supervised base
        # changes backend mid-search
        if getattr(self.base, "clone_push_many", None) is None:
            return None
        return self._clone_push_many

    def _clone_push_many(self, specs):
        return [
            (h, self._slice(s) if s is not None else None)
            for h, s in self.base.clone_push_many(specs)
        ]

    def activate(
        self, h: int, read_index: int, offset: int, consensus: bytes
    ) -> None:
        self.base.activate(
            h, int(self.indices[read_index]), offset, consensus
        )

    def deactivate(self, h: int, read_index: int) -> None:
        self.base.deactivate(h, int(self.indices[read_index]))

    def deactivate_many(self, pairs: List[Tuple[int, int]]) -> None:
        self.base.deactivate_many(
            [(h, int(self.indices[r])) for h, r in pairs]
        )

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        return self.base.finalized_eds(h, consensus)[self.indices]

    def best_activation_offset(
        self, consensus, seq_index, offset_window, offset_compare_length,
        wildcard,
    ) -> int:
        return self.base.best_activation_offset(
            consensus, int(self.indices[seq_index]), offset_window,
            offset_compare_length, wildcard,
        )

    # -- device fast paths (None when the current base lacks them)
    @property
    def run_extend(self):
        if getattr(self.base, "run_extend", None) is None:
            return None
        return self._run_extend

    @property
    def run_extend_dual(self):
        if getattr(self.base, "run_extend_dual", None) is None:
            return None
        return self._run_extend_dual

    @property
    def run_arena(self):
        if getattr(self.base, "run_arena", None) is None:
            return None
        return self._run_arena

    @property
    def run_mega(self):
        # megastep twin of run_extend: same contract, so the same
        # sliced-view adapter applies (the base property is also the
        # WAFFLE_MEGASTEP gate — None propagates through the view)
        if getattr(self.base, "run_mega", None) is None:
            return None
        return self._run_mega

    def _run_extend(self, h, consensus, *args, **kwargs):
        steps, code, appended, stats, records = self.base.run_extend(
            h, consensus, *args, **kwargs
        )
        idx = self.indices
        return (
            steps,
            code,
            appended,
            self._slice(stats),
            [(j, fin[idx]) for j, fin in records],
        )

    def _run_mega(self, h, consensus, *args, **kwargs):
        steps, code, appended, stats, records = self.base.run_mega(
            h, consensus, *args, **kwargs
        )
        idx = self.indices
        return (
            steps,
            code,
            appended,
            self._slice(stats),
            [(j, fin[idx]) for j, fin in records],
        )

    def _run_extend_dual(self, h1, h2, consensus1, consensus2, *args, **kwargs):
        (steps, code, app1, app2, stats1, stats2, act1, act2, records) = (
            self.base.run_extend_dual(h1, h2, consensus1, consensus2, *args, **kwargs)
        )
        idx = self.indices
        return (
            steps,
            code,
            app1,
            app2,
            self._slice(stats1),
            self._slice(stats2),
            act1[idx],
            act2[idx],
            [
                (j, f1[idx], f2[idx], a1[idx], a2[idx])
                for j, f1, f2, a1, a2 in records
            ],
        )

    def _run_arena(self, *args, **kwargs):
        (events, nsteps, code, stop_node, node_steps, appended,
         sides_stats, sides_act, alive, creations) = self.base.run_arena(
            *args, **kwargs
        )
        idx = self.indices
        sides_stats = [
            self._slice(s) if s is not None else None for s in sides_stats
        ]
        sides_act = [a[idx] if a is not None else None for a in sides_act]
        return (
            events, nsteps, code, stop_node, node_steps, appended,
            sides_stats, sides_act, alive, creations,
        )


class FastPaths:
    """The resolved optional-capability surface of a scorer: one probe
    walk of the proxy stack (SubsetScorer / CoalescingScorer /
    TimedScorer / BackendSupervisor all forward these dynamically),
    snapshotted so the engines' per-pop feature tests don't re-walk it.

    ``gen`` is the ``fastpath_gen`` the snapshot was taken at; see
    :func:`fast_paths`.
    """

    __slots__ = (
        "gen", "run_extend", "run_extend_dual", "run_arena", "run_mega",
        "clone_push_many", "arena_cap", "arena_k", "arena_cre_per_event",
        "arena_take_max",
    )

    def __init__(self, scorer, gen: int) -> None:
        self.gen = gen
        self.run_extend = getattr(scorer, "run_extend", None)
        self.run_extend_dual = getattr(scorer, "run_extend_dual", None)
        self.run_arena = getattr(scorer, "run_arena", None)
        self.run_mega = getattr(scorer, "run_mega", None)
        self.clone_push_many = getattr(scorer, "clone_push_many", None)
        self.arena_cap = getattr(scorer, "ARENA_CAP", 0)
        self.arena_k = getattr(scorer, "ARENA_K", 1)
        self.arena_cre_per_event = getattr(scorer, "ARENA_CRE_PER_EVENT", 0)
        self.arena_take_max = getattr(
            scorer, "ARENA_TAKE_MAX", self.arena_k - 1
        )


def fast_paths(scorer) -> FastPaths:
    """Cached :class:`FastPaths` for ``scorer``, re-resolved only when
    its ``fastpath_gen`` changes.

    The engines feature-test the device fast paths on EVERY pop; on the
    full proxy stack each ``getattr`` walks several ``__getattr__`` /
    property hops and binds fresh methods, which at hot-loop pop rates
    is measurable host overhead.  The resolved surface is stable —
    proxies forward dynamically only so a supervised base swapping
    backends stays visible — so it is cached on the scorer instance and
    keyed by the supervisor's demotion/promotion generation counter
    (``fastpath_gen``, 0 for unsupervised stacks, forwarded by every
    proxy).  The cache lives in the instance ``__dict__`` directly:
    delegating proxies would otherwise serve the INNER scorer's cache
    through their catch-all ``__getattr__``.
    """
    gen = getattr(scorer, "fastpath_gen", 0)
    d = getattr(scorer, "__dict__", None)
    if d is not None:
        cached = d.get("_fastpath_cache")
        if cached is not None and cached.gen == gen:
            return cached
    fp = FastPaths(scorer, gen)
    if d is not None:
        d["_fastpath_cache"] = fp
    return fp


def construct_backend(
    reads: Sequence[bytes], config: CdwfaConfig, backend: str
) -> WavefrontScorer:
    """Instantiate one concrete backend scorer (the supervisor calls
    this directly to build fallback scorers mid-search).

    This is the single choke point where every concrete scorer is born
    (including supervisor-built mid-search fallbacks), so it is also
    where dispatch instrumentation is installed: when observability is
    active, the scorer is wrapped in an obs ``TimedScorer`` proxy that
    records per-(backend, op) latency histograms and tracer spans."""
    if backend == "python":
        scorer = PythonScorer(reads, config)
    elif backend == "jax":
        from waffle_con_tpu.ops.jax_scorer import JaxScorer

        scorer = JaxScorer(reads, config)
        if config.mesh_shards:
            from waffle_con_tpu.parallel import shard_for_config

            shard_for_config(scorer, config)
    elif backend == "native":
        from waffle_con_tpu.native import NativeScorer

        scorer = NativeScorer(reads, config)
    else:
        raise ValueError(f"unknown backend {backend!r}")
    from waffle_con_tpu.obs.audit import maybe_tap
    from waffle_con_tpu.obs.instrument import maybe_instrument

    return maybe_tap(maybe_instrument(scorer, backend), backend)


#: thread-local scorer decoration (see :func:`set_scorer_decorator`)
_SCORER_HOOK = threading.local()


def set_scorer_decorator(decorator):
    """Install a *thread-local* decorator applied to every scorer that
    :func:`make_scorer` builds on this thread; returns the previous
    decorator so callers can restore it (``None`` = none installed).

    This is the serve layer's injection point: a worker thread installs
    ``lambda s: CoalescingScorer(s, dispatcher, job)`` around an
    engine's ``consensus()`` call, and every scorer the engine
    constructs — including the priority engine's per-level shared base
    scorers — transparently routes its dispatches through the cross-job
    batching dispatcher.  Thread-locality keeps concurrent jobs from
    seeing each other's wrappers.  Note the decorator applies only in
    :func:`make_scorer`, never in :func:`construct_backend`: fallback
    scorers the supervisor builds mid-search live *inside* an already
    routed dispatch and must not be re-routed.
    """
    previous = getattr(_SCORER_HOOK, "decorator", None)
    _SCORER_HOOK.decorator = decorator
    return previous


def make_scorer(reads: Sequence[bytes], config: CdwfaConfig) -> WavefrontScorer:
    """Instantiate the scorer selected by ``config.backend``, wrapped in
    the fault-tolerant supervisor when the config asks for one, then in
    the calling thread's scorer decorator when one is installed (see
    :func:`set_scorer_decorator`)."""
    if config.supervised or config.backend_chain is not None:
        from waffle_con_tpu.runtime.supervisor import BackendSupervisor

        scorer: WavefrontScorer = BackendSupervisor(reads, config)
    else:
        scorer = construct_backend(reads, config, config.backend)
    decorator = getattr(_SCORER_HOOK, "decorator", None)
    if decorator is not None:
        scorer = decorator(scorer)
    return scorer
