"""One-shot WFA edit distance between two byte strings.

Capability parity with ``/root/reference/src/sequence_alignment.rs:18-87``:
plain edit distance via expanding wavefronts of furthest-reaching
``(i, j)`` pairs, with an optional prefix mode (``require_both_end=False``)
that only requires ``v2`` to be fully consumed — used by the engines'
offset-activation search — and a wildcard that matches on *either* side.

>>> wfa_ed(bytes([0, 1, 2, 4, 5]), bytes([0, 1, 3, 4, 5]))
1
>>> wfa_ed_config(bytes([0, 1, 2, 4, 5]), bytes([0, 1, 2, 4]), False, ord('*'))
0
>>> wfa_ed_config(bytes([0, 1, 2, 4, 5]), bytes([0, 1, 2, 4]), True, ord('*'))
1
"""

from __future__ import annotations

from typing import Optional


def wfa_ed(v1: bytes, v2: bytes) -> int:
    """Full end-to-end edit distance with the default ``*`` wildcard."""
    return wfa_ed_config(v1, v2, True, ord("*"))


def wfa_ed_config(
    v1: bytes,
    v2: bytes,
    require_both_end: bool = True,
    wildcard: Optional[int] = None,
) -> int:
    """Edit distance between ``v1`` and ``v2``.

    When ``require_both_end`` is false, the alignment may stop at any
    position of ``v1`` once ``v2`` is exhausted (prefix semantics).  A
    ``wildcard`` byte matches anything on either side.
    """
    l1 = len(v1)
    l2 = len(v2)

    # furthest-reaching (i, j) per diagonal; wavefront index w at edit
    # distance e spans diagonals j - i = w - e.
    curr = [(0, 0)]
    edits = 0
    while True:
        nxt = [(0, 0)] * (2 * edits + 3)
        for w, (i, j) in enumerate(curr):
            while i < l1 and j < l2 and (
                v1[i] == v2[j] or v1[i] == wildcard or v2[j] == wildcard
            ):
                i += 1
                j += 1
            if j == l2 and (i == l1 or not require_both_end):
                return edits
            if i == l1:
                # only j may advance
                a, b, c = (i, j), (i, j + 1), (i, j + 1)
            elif j == l2:
                # only i may advance
                a, b, c = (i + 1, j), (i + 1, j), (i, j)
            else:
                # deletion / mismatch / insertion (of v2 relative to v1)
                a, b, c = (i + 1, j), (i + 1, j + 1), (i, j + 1)
            if a > nxt[w]:
                nxt[w] = a
            if b > nxt[w + 1]:
                nxt[w + 1] = b
            if c > nxt[w + 2]:
                nxt[w + 2] = c
        edits += 1
        curr = nxt
