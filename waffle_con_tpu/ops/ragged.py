"""Ragged cross-job device batching over a paged band-state arena.

The serve layer's :class:`~waffle_con_tpu.serve.dispatcher.BatchingDispatcher`
only coalesces jobs that share an exact compiled shape bucket, so realistic
heterogeneous traffic — mixed read counts, read lengths, band widths —
fragments into occupancy-1 dispatches and pays a per-shape recompile tax.
This module is the Ragged-Paged-Attention answer (arXiv:2604.15464; the
same packing gpuPairHMM applies to DP alignment batches): ONE kernel
instance steps *all* active jobs' reads in a single call, with per-read
band state living in fixed-size pages of one preallocated device pool
behind a host-managed page table.

Shape of the thing:

* :class:`PageTable` — host-side alloc/free lists over ``ROWS`` pool rows
  quantized to ``PAGE``-row pages, per-job page runs.  Exhaustion raises
  the typed :class:`ArenaExhausted` (the dispatcher then falls back to
  the bucketed path — backpressure, never corruption).
* :class:`BandArena` — the device pool: persistent staged reads
  (``[ROWS, L] int16`` + lengths) plus the one compiled ragged kernel.
  Pool geometry (``ROWS x PAGE x W x C``) is fixed at construction, so
  exactly ONE kernel compilation serves every job shape.
* ``probe()`` — resolves a parked ``run_extend`` dispatch down the proxy
  stack (``CoalescingScorer`` → supervisor → ``JaxScorer``) via the
  duck-typed ``ragged_run_probe`` hop, checks geometry eligibility, and
  lazily admits the job's reads into the pool.
* ``run_group()`` — gathers each member's band state into the pool
  layout (per-row ``(job, read)`` descriptors replace the padded
  ``[R, ...]`` batch), runs the ragged kernel once, scatters the
  results back into each scorer's own slot, and deposits a consume-once
  *injected result* per member; the member's ordinary ``run_extend``
  dispatch then returns it instantly, so supervision, fault injection,
  validation, and tracing all compose unchanged.

Byte-identity with the serial path:

* the kernel is the single-column (K=1) ``_j_run`` body with every
  per-branch reduction replaced by a segment-reduce keyed by job — the
  speculative-K contract already guarantees K=1 ≡ any K;
* pages are **width-agnostic**: each pool row carries its member's band
  width as a per-row stride (``wrow``), the kernel masks every column
  past it to the ``INF`` sentinel before any reduce, and the band-index
  arithmetic uses the per-row half-width — so one compiled pool
  geometry serves members of *different* band widths and a row's
  columns ``[0, wrow)`` compute exactly what the member's own solo
  kernel at width ``wrow`` would (columns past it stay inert).  State
  moves by width-sliced row copy — no re-centering, no value changes.
  ``WAFFLE_RAGGED_MIXED_W=0`` restores the historical band-width
  equality gate (A/B lever; the stride path is the default);
* a member whose band grows mid-run (E doubles on overflow) is
  **re-centered in pool** (:func:`recenter_scorer`): its page run and
  staged reads are untouched — only its now-stale deposits drop — so a
  long-running job stays gang-eligible for its whole life while its
  new width still fits the pool's;
* record absorption is force-disabled (``allow_records=0`` semantics:
  reached states stop with code 2, which the engine already handles),
  trading extra dispatches for exactness;
* f32 vote sums segment-reduce in a different order than the solo
  stack-sum, but every decision is either taken on exact dyadic values
  or guarded by the ``VOTE_EPS`` margin (near-ties go dirty → host f64
  arbitration), so decisions are identical.

Disabled with ``WAFFLE_RAGGED=0`` (bucketed path untouched).  Pool
sizing: ``WAFFLE_RAGGED_ROWS`` / ``WAFFLE_RAGGED_PAGE`` /
``WAFFLE_RAGGED_E`` / ``WAFFLE_RAGGED_L`` / ``WAFFLE_RAGGED_C`` /
``WAFFLE_RAGGED_GANG``.

This module imports jax lazily (inside the arena) so the serve layer can
import it unconditionally, python-backend-only stacks included.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from waffle_con_tpu.obs import metrics as obs_metrics
from waffle_con_tpu.obs import phases as _phases
from waffle_con_tpu.analysis import lockcheck
from waffle_con_tpu.utils import envspec

logger = logging.getLogger(__name__)

#: params-row layout of the per-member ``jp [G+1, 10] int32`` array
_JP_COLS = 10

_RUN_ARGS = (
    "h", "consensus", "me_budget", "other_cost", "other_len",
    "min_count", "l2", "max_steps", "first_sym", "allow_records",
)


class ArenaExhausted(RuntimeError):
    """Typed backpressure: the page table cannot hold another job's
    reads.  Callers fall back to the bucketed dispatch path — this must
    never surface as a corrupted result."""


def enabled() -> bool:
    """Ragged dispatch master switch (``WAFFLE_RAGGED``, default on)."""
    return envspec.get_raw("WAFFLE_RAGGED", "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def mixed_w_enabled() -> bool:
    """Width-agnostic pages (``WAFFLE_RAGGED_MIXED_W``, default on):
    members of different band widths share one gang via the per-row W
    stride.  Off restores the historical W-equality eligibility gate."""
    raw = envspec.get_raw("WAFFLE_RAGGED_MIXED_W", "1")
    return raw.strip().lower() not in ("0", "false", "off", "no")


# ======================================================================
# serve-scope geometry hint.  Constant-compile-count story: every serve
# job built inside the scope floors its scorer geometry up to the pool's
# (R/L/E/C), so ALL jobs share one compiled kernel set for their own
# solo dispatches too — compile count is bounded by the pool geometry
# (plus the log-bounded branch-slot growth ladder), NOT by the number of
# distinct job shapes.  Naturally-larger jobs keep their natural shapes
# (still correct, just bucketed/solo when the band width mismatches).


@dataclass(frozen=True)
class GeometryHint:
    band: int    # floor for the scorer's band half-width E (pool E)
    rows: int    # floor for the read-slot axis R
    length: int  # floor for the reads axis L
    cons: int    # floor for the consensus axis C


_TLS = threading.local()


@contextlib.contextmanager
def serve_scope():
    """Marks the current thread as building/running a served job: scorer
    constructors consult :func:`geometry_hint` while it is active."""
    prev = getattr(_TLS, "serving", 0)
    _TLS.serving = prev + 1
    try:
        yield
    finally:
        _TLS.serving = prev


def geometry_hint() -> Optional[GeometryHint]:
    """The serve-scope geometry floor, or None outside a served job (or
    with ragged dispatch disabled — the bucketed baseline keeps its
    natural per-shape geometry, recompiles and all).

    Only the consensus axis is always floored (and the band half-width
    when mixed-width ganging is disabled).  C is floored because
    eligibility demands ``len(consensus) + max_steps + 2 < C`` *at
    probe time* — the solo wrapper grows C lazily mid-run, so a natural
    C of 512 against step budgets in the hundreds would veto nearly
    every gang; the cons axis is O(C) scatter work per step, not
    [R, W] row work, so the floor is cheap.  R/L stay natural — the
    gather/scatter handles any per-member R/L, and flooring them was
    measured to cost far more on every SOLO dispatch of small jobs (4x
    row work at R 16->64) than it saved in compile-key sharing: pow2
    quantization inside the pool envelope already bounds the distinct
    kernel keys by a pool-determined constant, not by the number of
    distinct job shapes.

    E follows the same logic since the width-agnostic arena: the
    per-row W stride makes any ``W <= pool W`` gang-eligible, so
    flooring E would only inflate every solo dispatch's [R, W] row work
    (quadratic in E for the replay) with nothing bought.  Jobs keep
    their natural band; pow2 E growth ladders through a handful of solo
    compile keys bounded by ``log2(pool E)``.  Only with
    ``WAFFLE_RAGGED_MIXED_W=0`` — where W equality is back to being the
    gang gate — is E floored to the pool's."""
    if not getattr(_TLS, "serving", 0) or not enabled():
        return None
    cfg = ArenaConfig.from_env()
    band = 0 if mixed_w_enabled() else cfg.band_e
    return GeometryHint(band=band, rows=0, length=0, cons=cfg.cons_len)


# ======================================================================
# configuration + page table


def _env_int(name: str, default: int, lo: int, hi: int) -> int:
    return envspec.get_int(name, default, lo, hi)


@dataclass(frozen=True)
class ArenaConfig:
    """Pool geometry, snapshotted from the environment at arena build."""

    rows: int = 256       # total pool rows (reads across all jobs)
    page_rows: int = 8    # rows per page (residency quantum)
    band_e: int = 32      # pool band half-width; W = 2E + 2
    read_len: int = 512   # staged read columns
    cons_len: int = 2048  # per-member consensus capacity
    gang: int = 8         # max members per ragged kernel call
    alphabet: int = 8     # dense vote width (matches JaxScorer.MIN_A)

    @staticmethod
    def from_env() -> "ArenaConfig":
        return ArenaConfig(
            rows=_env_int("WAFFLE_RAGGED_ROWS", 256, 16, 1 << 16),
            page_rows=_env_int("WAFFLE_RAGGED_PAGE", 8, 1, 256),
            band_e=_env_int("WAFFLE_RAGGED_E", 32, 8, 512),
            read_len=_env_int("WAFFLE_RAGGED_L", 512, 64, 1 << 15),
            cons_len=_env_int("WAFFLE_RAGGED_C", 2048, 256, 1 << 16),
            gang=_env_int("WAFFLE_RAGGED_GANG", 8, 2, 64),
        )


class PageTable:
    """Host-side fixed-page allocator over the arena's row pool.

    Pages are the residency quantum: a job's ``num_reads`` rows round up
    to whole pages, so the pool upload scatter only ever sees
    page-multiple row counts (bounded distinct shapes regardless of job
    geometry).  Free pages recycle LIFO."""

    def __init__(self, n_pages: int, page_rows: int) -> None:
        if n_pages < 1 or page_rows < 1:
            raise ValueError("page table needs >= 1 page of >= 1 row")
        self.n_pages = n_pages
        self.page_rows = page_rows
        self._free: List[int] = list(range(n_pages - 1, -1, -1))
        self._held: Dict[int, List[int]] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.n_pages - len(self._free)

    def alloc(self, key: int, rows_needed: int) -> np.ndarray:
        """Allocate the page run covering ``rows_needed`` rows under
        ``key``; returns the (page-quantized) pool row indices.  Raises
        :class:`ArenaExhausted` when the pool cannot hold them."""
        if rows_needed < 1:
            raise ValueError("rows_needed must be >= 1")
        pages = -(-rows_needed // self.page_rows)
        if pages > len(self._free):
            raise ArenaExhausted(
                f"band-state pool exhausted: need {pages} pages "
                f"({rows_needed} rows), {len(self._free)} free of "
                f"{self.n_pages}"
            )
        got = [self._free.pop() for _ in range(pages)]
        self._held[key] = got
        return np.concatenate([
            np.arange(p * self.page_rows, (p + 1) * self.page_rows)
            for p in sorted(got)
        ]).astype(np.int64)

    def release(self, key: int) -> bool:
        pages = self._held.pop(key, None)
        if pages is None:
            return False
        self._free.extend(pages)
        return True


# ======================================================================
# dispatch-time records


@dataclass
class RunSpec:
    """One probed-and-admitted gang member: the resolved ``JaxScorer``
    endpoint plus the normalized ``run_extend`` call args."""

    scorer: object
    h: int
    vals: Dict
    ticket: object = None
    job_id: Optional[int] = None


@dataclass
class _Injected:
    """A consume-once precomputed ``run_extend`` result deposited by
    :meth:`BandArena.run_group`; the member's own dispatch returns it."""

    len0: int
    steps: int
    code: int
    ids: np.ndarray          # appended dense symbol ids (length >= steps)
    stats: tuple             # 6-tuple feeding JaxScorer._stats_np
    iters: int


@dataclass
class _SpecInjected(_Injected):
    """A *speculative* frontier-gang deposit (:class:`FrontierGang`).

    Unlike the serving-path injections, the member's slot was NOT
    advanced at gang time: the post-run band state rides along as host
    rows (``post``) and is scattered into the slot only if the serial
    pop order actually reaches the node with compatible call arguments
    (validated in ``JaxScorer.run_extend``).  A mismatch simply
    discards the deposit — the slot still holds the pristine pre-gang
    state, so the solo run is trivially exact."""

    speculative: bool = True
    #: forced first symbol the speculation assumed (-1 = unforced)
    first_sym: int = -1
    #: total cost of the advanced state under the member's cost model —
    #: costs are nondecreasing over a run, so this single value bounds
    #: every in-run budget/wins check the real call would have made
    final_cost: int = 0
    #: speculated min_count / l2 (search constants; guarded for safety)
    min_count: int = 0
    l2: bool = False
    #: the call arguments the speculation ran with — when they equal
    #: the real pop's, the kernel's stop decisions were identical and
    #: consumption is exact with no cost bounds at all (the bounds are
    #: only needed to prove a MISpredicted gate never over-committed)
    me_budget: int = 2**31 - 1
    other_cost: int = 2**31 - 1
    other_len: int = 0
    #: held post-run slot rows ``(D, e, rmin, er, cons, clen)``
    post: tuple = ()


@dataclass
class _Residency:
    scorer: object           # strong ref: keyed by id() while resident
    rows: np.ndarray
    job_id: Optional[int] = None
    keys: List[Tuple[int, int]] = field(default_factory=list)


def _normalize_run_args(args, kwargs) -> Optional[Dict]:
    """Positional/keyword ``run_extend`` call -> named dict (None when
    the shape is unrecognized — then the call just runs solo)."""
    if len(args) > len(_RUN_ARGS):
        return None
    vals: Dict = {"first_sym": -1, "allow_records": True}
    vals.update(zip(_RUN_ARGS, args))
    for k, v in kwargs.items():
        if k not in _RUN_ARGS:
            return None
        vals[k] = v
    if any(k not in vals for k in _RUN_ARGS[:8]):
        return None
    return vals


# ======================================================================
# the arena


class BandArena:
    """Device-resident paged band-state pool + the one ragged kernel.

    All host bookkeeping (page table, residency, injections, counters)
    is guarded by one lock; device work happens on the dispatcher thread
    (``run_group``) with ``release_job`` the only cross-thread caller.
    """

    def __init__(self, cfg: ArenaConfig) -> None:
        self.cfg = cfg
        self.rows = cfg.rows
        self.page_rows = cfg.page_rows
        self.E = cfg.band_e
        self.W = 2 * cfg.band_e + 2
        self.L = cfg.read_len
        self.C = cfg.cons_len
        self.gang = cfg.gang
        self.A = cfg.alphabet
        self.pages = PageTable(cfg.rows // cfg.page_rows, cfg.page_rows)
        self._lock = lockcheck.make_rlock("ops.ragged.BandArena")
        self._resident: Dict[int, _Residency] = {}
        self._injected: Dict[Tuple[int, int], _Injected] = {}
        self._counters = {
            "groups": 0, "members": 0, "occupancy_max": 0,
            "admits": 0, "releases": 0, "exhausted": 0,
            "injected_consumed": 0, "injected_dropped": 0,
            "member_store_failures": 0,
            # width-agnostic-page accounting: gangs whose members span
            # >= 2 distinct band widths, total active rows stepped, and
            # in-pool band re-centerings (grown members kept resident)
            "mixed_w_groups": 0, "gang_rows": 0, "recenters": 0,
        }
        self._reads = None   # [ROWS, L] int16 device, staged lazily
        self._rlen = None    # [ROWS] int32 device
        self._kernel = None

    # -- device pool ---------------------------------------------------

    def _ensure_pool(self) -> None:
        if self._reads is not None:
            return
        import jax

        self._reads = jax.device_put(
            np.full((self.rows, self.L), -1, dtype=np.int16)
        )
        self._rlen = jax.device_put(np.zeros(self.rows, dtype=np.int32))

    def _publish_pages(self) -> None:
        if not obs_metrics.metrics_enabled():
            return
        reg = obs_metrics.registry()
        reg.gauge("waffle_ragged_pool_pages_used").set(self.pages.used_pages)
        reg.gauge("waffle_ragged_pool_pages_free").set(self.pages.free_pages)

    # -- eligibility + residency ---------------------------------------

    def eligible(self, scorer, vals: Dict) -> bool:
        """Geometry gate for one probed member.  With width-agnostic
        pages (the default) the pool band width is a *cap*, not an
        equality: any member with ``W <= pool W`` gangs, its rows
        running at their own per-row stride inside the pool envelope
        (byte-identity holds because the kernel masks every column past
        the stride to INF before any reduce).  With
        ``WAFFLE_RAGGED_MIXED_W=0`` the historical equality gate is
        back.  The consensus-capacity check mirrors the solo wrapper's
        grow condition so an injected run never needed a grow."""
        try:
            n = scorer.num_reads
            if n < 1 or n > self.rows:
                return False
            if getattr(scorer, "_shardings", None) is not None:
                return False
            if mixed_w_enabled():
                if scorer._W > self.W:
                    return False
            elif scorer._W != self.W:
                return False
            if scorer.num_symbols > self.A:
                return False
            if scorer._max_rlen > self.L:
                return False
            need = len(vals["consensus"]) + int(vals["max_steps"]) + 2
            if need >= min(scorer._C, self.C):
                return False
        except (AttributeError, TypeError):
            return False
        return True

    def try_admit(self, scorer, job_id: Optional[int]) -> Optional[np.ndarray]:
        """Lazy admission on first probe: allocate this scorer's page
        run and stage its reads into the pool.  Returns the pool rows,
        or None on exhaustion (graceful bucketed fallback)."""
        with self._lock:
            key = id(scorer)
            res = self._resident.get(key)
            if res is not None:
                if res.job_id is None:
                    res.job_id = job_id
                return res.rows
            try:
                rows = self.pages.alloc(key, scorer.num_reads)
            except ArenaExhausted:
                self._counters["exhausted"] += 1
                if obs_metrics.metrics_enabled():
                    obs_metrics.registry().counter(
                        "waffle_ragged_exhausted_total"
                    ).inc()
                return None
            self._ensure_pool()
            block = np.full((len(rows), self.L), -1, dtype=np.int16)
            rlen = np.zeros(len(rows), dtype=np.int32)
            sym_id = scorer.sym_id
            for i, r in enumerate(scorer.reads):
                block[i, : len(r)] = [sym_id[b] for b in r]
                rlen[i] = len(r)
            self._reads = self._reads.at[rows].set(block)
            self._rlen = self._rlen.at[rows].set(rlen)
            self._resident[key] = _Residency(scorer, rows, job_id)
            self._counters["admits"] += 1
            self._publish_pages()
            return rows

    def _release_key(self, key: int) -> None:
        res = self._resident.pop(key, None)
        if res is None:
            return
        self.pages.release(key)
        self._counters["releases"] += 1
        # pending injections for the departing scorer are stale by
        # definition (a rebuilt backend replays from the ledger)
        for k in [k for k in self._injected if k[0] == key]:
            self._injected.pop(k, None)
            self._counters["injected_dropped"] += 1
        self._publish_pages()

    def release_scorer(self, scorer) -> None:
        with self._lock:
            self._release_key(id(scorer))

    def release_job(self, job_id) -> None:
        if job_id is None:
            return
        with self._lock:
            for key in [
                k for k, r in self._resident.items() if r.job_id == job_id
            ]:
                self._release_key(key)

    def recenter_scorer(self, scorer) -> bool:
        """In-pool band re-centering: the scorer's band just grew (E
        doubled on overflow) or otherwise re-centered, so any held
        deposits were computed at the old width and are stale — but its
        page run and staged reads are untouched by a band change, so
        residency survives and the member gangs again on its next probe
        at the new per-row stride.  Only a width outgrowing the pool's
        evicts (the stride is a cap); returns True while the scorer is
        still resident."""
        with self._lock:
            key = id(scorer)
            res = self._resident.get(key)
            if res is None:
                return False
            stale = [k for k in self._injected if k[0] == key]
            for k in stale:
                self._injected.pop(k, None)
                self._counters["injected_dropped"] += 1
            try:
                if scorer._W > self.W or not mixed_w_enabled():
                    # the pool can no longer express this band (or the
                    # equality gate is back on): classic eviction
                    self._release_key(key)
                    return False
            except AttributeError:
                self._release_key(key)
                return False
            self._counters["recenters"] += 1
            if obs_metrics.metrics_enabled():
                obs_metrics.registry().counter(
                    "waffle_ragged_recenter_total"
                ).inc()
            return True

    # -- injections ----------------------------------------------------

    def take_injected(self, scorer, h: int) -> Optional[_Injected]:
        with self._lock:
            inj = self._injected.pop((id(scorer), int(h)), None)
            if inj is not None:
                self._counters["injected_consumed"] += 1
            return inj

    def discard_injected(self, keys) -> None:
        """Drop injections deposited for a batch that were never
        consumed (e.g. the member's dispatch raised before reaching the
        scorer) — a stale injection must never survive into a later
        call."""
        with self._lock:
            for k in keys:
                if self._injected.pop(k, None) is not None:
                    self._counters["injected_dropped"] += 1

    # -- the ragged kernel ---------------------------------------------

    def _build_kernel(self):
        """The one compiled geometry: ``_j_run``'s K=1 body with every
        per-branch fold replaced by a segment-reduce keyed by the
        per-row job id (``seg``).  Static shapes are pool-only
        (``ROWS x W x C x (G+1) x A``), so exactly one compilation
        serves every member mix.

        Width-agnostic pages: ``wrow`` carries each row's member band
        width (a traced ``[ROWS] int32`` — no new compile keys), the
        per-row half-width ``erow = (wrow - 2) // 2`` replaces the old
        pool-wide scalar in the band-index arithmetic and the overflow
        checks, and every column at or past a row's stride is forced to
        the ``INF`` sentinel *before* the column-min / row-end reduces.
        With that forcing, a row's columns ``[0, wrow)`` compute
        exactly the member's own solo kernel at width ``wrow``: the
        delete shift at column ``wrow - 1`` reads the forced INF
        (matching the solo kernel's appended INF fill), the insertion
        prefix-min only ever flows left-to-right so junk in the padding
        columns cannot reach a valid column, and the reduces see INF
        from padding — members of different widths share one gang
        byte-identically."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from waffle_con_tpu.ops.jax_scorer import (
            INF, VOTE_EPS, _cummin_rows,
        )

        @partial(jax.jit, static_argnames=("A", "cols"))
        def _j_run_ragged(reads, rlen, D0, e0, rmin0, er0, off, act, seg,
                          wrow, cons0, clen0, jp, A, cols=1):
            ROWS, W = D0.shape
            L = reads.shape[1]
            G1, C = cons0.shape
            EPS = VOTE_EPS

            in_group = jp[:, 0].astype(bool)
            me_budget = jp[:, 1]
            other_cost = jp[:, 2]
            other_len = jp[:, 3]
            min_count_f = jp[:, 4].astype(jnp.float32)
            l2 = jp[:, 5].astype(bool)
            max_steps = jp[:, 6]
            first_sym = jp[:, 7]
            wc = jp[:, 8]
            et = jp[:, 9].astype(bool)

            l2_r = l2[seg]
            wc_r = wc[seg]
            et_r = et[seg]
            t = jnp.arange(W, dtype=jnp.int32)[None, :]
            gi = jnp.arange(G1, dtype=jnp.int32)
            # per-row band stride: half-width for the index arithmetic,
            # column mask for the sentinel forcing
            erow = (wrow - 2) // 2
            wmask = t < wrow[:, None]

            def seg_sum(x):
                return jnp.zeros(
                    (G1,) + x.shape[1:], x.dtype
                ).at[seg].add(x)

            def seg_any(x):
                return jnp.zeros((G1,), jnp.int32).at[seg].max(
                    x.astype(jnp.int32)
                ) > 0

            def seg_max0(x):  # folds over non-negative int32 values
                return jnp.zeros((G1,), x.dtype).at[seg].max(x)

            def col_step(D, e, rmin, er, jnew_r, sym_r):
                # row-wise _col_step_w: identical formulas with the
                # per-branch scalars (sym/wc/et/jnew) per-row vectors
                # and the per-row band half-width replacing the pool's
                i_new = jnew_r[:, None] - off[:, None] - erow[:, None] + t
                bchar = jnp.take_along_axis(
                    reads, jnp.clip(i_new - 1, 0, L - 1), axis=1
                )
                sub = (
                    (bchar != sym_r[:, None]) & (bchar != wc_r[:, None])
                ).astype(jnp.int32)
                diag = D + sub
                dele = jnp.concatenate(
                    [D[:, 1:], jnp.full_like(D[:, :1], INF)], axis=1
                ) + 1
                base = jnp.minimum(diag, dele)
                invalid = (i_new < 0) | (i_new > rlen[:, None]) | ~wmask
                base = jnp.where(invalid, jnp.int32(INF), base)
                chain = _cummin_rows(base - t)
                Dn = jnp.minimum(
                    jnp.minimum(base, chain + t), jnp.int32(INF)
                )
                # force the columns past a row's stride back to the
                # sentinel BEFORE any reduce: the insertion chain puts
                # finite values there (chain + t), and the row-end
                # reduce below would otherwise absorb them into rmin
                Dn = jnp.where(wmask, Dn, jnp.int32(INF))
                colmin = Dn.min(axis=1)
                rend = jnp.where(
                    i_new == rlen[:, None], Dn, jnp.int32(INF)
                ).min(axis=1)
                rmin_n = jnp.minimum(rmin, rend)
                e_unc = jnp.maximum(e, colmin)
                e_cap = jnp.where(
                    er < INF, e,
                    jnp.maximum(
                        e, jnp.minimum(colmin, jnp.maximum(e, rmin_n))
                    ),
                )
                e_n = jnp.where(et_r, e_cap, e_unc)
                er_n = jnp.where(
                    er < INF, er,
                    jnp.where(rmin_n <= e_n, jnp.maximum(e, rmin_n), INF),
                )
                D = jnp.where(act[:, None], Dn, D)
                e = jnp.where(act, e_n, e)
                rmin = jnp.where(act, rmin_n, rmin)
                er = jnp.where(act, er_n, er)
                return D, e, rmin, er

            def stats_rows(D, e, rmin, er, clen):
                # row-wise _stats_core at the full pool vote width (the
                # columns past a member's real alphabet are structurally
                # zero — inert for every decision below)
                clen_r = clen[seg]
                i = clen_r[:, None] - off[:, None] - erow[:, None] + t
                vchar = jnp.take_along_axis(
                    reads, jnp.clip(i, 0, L - 1), axis=1
                )
                tip = (
                    act[:, None] & (D <= e[:, None]) & wmask
                    & (i >= 0) & (i < rlen[:, None])
                )
                onehot = (
                    vchar[:, :, None] == jnp.arange(A)[None, None, :]
                ) & tip[:, :, None]
                occ = onehot.sum(axis=1, dtype=jnp.int32)
                split = occ.sum(axis=1)
                reached = act & (er < INF) & (e == er)
                eds = jnp.where(act, e, 0)
                return eds, occ, split, reached

            def substep(carry):
                D, e, rmin, er, cons, clen, steps, code, iters = carry
                live = in_group & (code == 0)
                eds, occ, split, reached = stats_rows(D, e, rmin, er, clen)
                fin_j = jnp.where(
                    act, jnp.minimum(jnp.maximum(e, rmin), INF), 0
                )
                costs = jnp.where(l2_r, eds * eds, eds)
                total = seg_sum(costs)
                nonexact = jnp.where(
                    split > 0, (split & (split - 1)) != 0, False
                )
                eds_max = seg_max0(eds)
                fin_max = seg_max0(fin_j)
                all_exact = ~seg_any(nonexact)
                cost_overflow = l2 & (eds_max > 2048)
                # reached fold mirrors _j_run's conservative semantics:
                # inactive lanes count as done under early termination
                reached_here = jnp.where(
                    et, ~seg_any(act & ~reached), seg_any(reached)
                )
                frac = jnp.where(
                    split[:, None] > 0,
                    occ.astype(jnp.float32)
                    / jnp.maximum(split, 1)[:, None].astype(jnp.float32),
                    0.0,
                )
                counts = seg_sum(frac)                      # [G1, A]
                has_votes = seg_sum((occ > 0).astype(jnp.float32)) > 0
                n_cands = has_votes.sum(axis=1)
                wc_col = jnp.maximum(wc, 0)
                drop_wc = (wc >= 0) & (n_cands > 1)
                a_idx = jnp.arange(A, dtype=jnp.int32)[None, :]
                wc_mask = drop_wc[:, None] & (a_idx == wc_col[:, None])
                has_votes = has_votes & ~wc_mask
                counts = jnp.where(wc_mask, 0.0, counts)
                maxc = jnp.where(has_votes, counts, -1.0).max(axis=1)
                thr = jnp.minimum(min_count_f, maxc)
                passing = has_votes & (counts >= thr[:, None])
                npass = passing.sum(axis=1)
                near_tie = (jnp.abs(maxc - min_count_f) < EPS) | (
                    (has_votes & (jnp.abs(counts - thr[:, None]) < EPS))
                    .any(axis=1)
                )
                ambiguous = ~all_exact & near_tie
                dirty = (
                    ambiguous | (npass != 1) | (n_cands == 0)
                    | cost_overflow
                )
                # allow_records is force-disabled on the ragged path, so
                # _j_run's rec_blocked is identically True: a reached
                # state always stops with code 2
                wins_pop = (total < other_cost) | (
                    (total == other_cost) & (clen > other_len)
                )
                code_new = jnp.where(
                    (total > me_budget) | ~wins_pop, 3,
                    jnp.where(
                        reached_here, 2,
                        jnp.where(
                            dirty, 1,
                            jnp.where(steps >= max_steps, 4, 0),
                        ),
                    ),
                )
                sym = jnp.argmax(
                    jnp.where(passing, counts, -1.0), axis=1
                ).astype(jnp.int32)
                clen2 = clen + 1
                D2, e2, rmin2, er2 = col_step(
                    D, e, rmin, er, clen2[seg], sym[seg]
                )
                ovf = seg_any(act & (e2 >= erow))
                commit = live & (code_new == 0) & ~ovf
                code = jnp.where(
                    ~live, code,
                    jnp.where(
                        code_new != 0, code_new, jnp.where(ovf, 5, 0)
                    ),
                )
                cpos = jnp.clip(clen, 0, C - 1)
                cons = cons.at[gi, cpos].set(
                    jnp.where(commit, sym, cons[gi, cpos])
                )
                cm = commit[seg]
                D = jnp.where(cm[:, None], D2, D)
                e = jnp.where(cm, e2, e)
                rmin = jnp.where(cm, rmin2, rmin)
                er = jnp.where(cm, er2, er)
                clen = clen + commit.astype(jnp.int32)
                steps = steps + commit.astype(jnp.int32)
                iters = iters + live.astype(jnp.int32)
                return (D, e, rmin, er, cons, clen, steps, code, iters)

            # forced first push per member (host-nominated child): only
            # band overflow refuses it — same contract as _j_run
            force = in_group & (first_sym >= 0)
            Df, ef, rminf, erf = col_step(
                D0, e0, rmin0, er0, (clen0 + 1)[seg], first_sym[seg]
            )
            fovf = seg_any(act & (ef >= erow))
            fcommit = force & ~fovf
            code_init = jnp.where(force & fovf, 5, 0).astype(jnp.int32)
            cpos0 = jnp.clip(clen0, 0, C - 1)
            cons1 = cons0.at[gi, cpos0].set(
                jnp.where(fcommit, first_sym, cons0[gi, cpos0])
            )
            fm = fcommit[seg]
            D1 = jnp.where(fm[:, None], Df, D0)
            e1 = jnp.where(fm, ef, e0)
            rmin1 = jnp.where(fm, rminf, rmin0)
            er1 = jnp.where(fm, erf, er0)
            clen1 = clen0 + fcommit.astype(jnp.int32)
            steps0 = fcommit.astype(jnp.int32)

            init = (
                D1, e1, rmin1, er1, cons1, clen1, steps0, code_init,
                jnp.zeros((G1,), jnp.int32),
            )
            if cols == 1:
                body = substep
            else:
                # K-column speculation composed with the gang: attempt
                # ``cols`` column sub-steps per device iteration.  The
                # ``live = in_group & (code == 0)`` mask freezes every
                # member past its stop code, so any ``cols`` is
                # byte-identical to cols=1 (see _j_run's K contract)
                def body(carry):
                    return lax.fori_loop(
                        0, cols, lambda _i, c: substep(c), carry
                    )
            (D, e, rmin, er, cons, clen, steps, code,
             iters) = lax.while_loop(
                lambda c: jnp.any(in_group & (c[7] == 0)), body, init
            )
            eds, occ, split, reached = stats_rows(D, e, rmin, er, clen)
            fin = jnp.maximum(e, rmin)
            fin_ovf = seg_any(act & (fin >= erow))
            fin_r = jnp.where(act, jnp.minimum(fin, INF), 0)
            return (D, e, rmin, er, cons, clen, steps, code, iters,
                    eds, occ, split, reached, fin_r, fin_ovf)

        return _j_run_ragged

    # -- gang execution ------------------------------------------------

    def run_group(self, specs: List[RunSpec]) -> List[Tuple[int, int]]:
        """Step every gang member in ONE ragged kernel call.

        Per member: gather its slot's band state into the pool layout,
        run, scatter the advanced state back into its own slot, THEN
        deposit the injected result — deposit strictly after a
        successful store, so a member whose store fails simply runs solo
        from its unmutated state (crash consistency).  Returns the
        deposited injection keys (the dispatcher discards leftovers
        after the batch).  Never raises: any failure degrades the
        affected members to the solo path."""
        # gang steps happen on the dispatcher thread, outside any
        # TimedScorer dispatch, so they open their own phase record
        rec = _phases.begin("ragged_group", "jax")
        try:
            return self._run_group(specs)
        except Exception:  # noqa: BLE001 - ragged must never fail a job
            logger.warning(
                "ragged group of %d failed; members fall back to solo",
                len(specs), exc_info=True,
            )
            return []
        finally:
            _phases.end(rec)

    def _run_group(self, specs: List[RunSpec]) -> List[Tuple[int, int]]:
        import jax

        from waffle_con_tpu.ops import jax_scorer as js

        G = self.gang
        G1 = G + 1
        members = []
        with self._lock:
            for spec in specs[:G]:
                res = self._resident.get(id(spec.scorer))
                slot = spec.scorer._slot_of.get(spec.h)
                if res is None or slot is None:
                    continue
                members.append((spec, res.rows, slot))
        if len(members) < 2:
            return []

        # LIFO page allocation keeps runs packed low, so the dispatch
        # only steps the pow2 row-prefix covering every member's run —
        # compile keys gain a log2(rows)-bounded ladder, the kernel
        # skips the pool's idle tail entirely
        hi = 1 + max(int(rows[-1]) for _, rows, _ in members)
        P = 1
        while P < hi:
            P *= 2
        P = min(P, self.rows)

        rec = _phases.current()
        if rec is not None:
            rec.annotate(
                kernel="ragged", k=1, geom=f"P{P}W{self.W}G{self.gang}"
            )

        # one device_get per member: its slot's full band-state rows
        loaded = []
        with _phases.transfer_scope(rec):
            for spec, rows, slot in members:
                st = spec.scorer._state
                loaded.append(jax.device_get((
                    st["D"][slot], st["e"][slot], st["rmin"][slot],
                    st["er"][slot], st["cons"][slot], st["clen"][slot],
                )))

        D = np.full((P, self.W), int(js.INF), np.int32)
        e = np.zeros(P, np.int32)
        rmin = np.full(P, int(js.INF), np.int32)
        er = np.full(P, int(js.INF), np.int32)
        off = np.zeros(P, np.int32)
        act = np.zeros(P, bool)
        seg = np.full(P, G, np.int32)
        wrow = np.full(P, self.W, np.int32)
        cons = np.zeros((G1, self.C), np.int32)
        clen = np.zeros(G1, np.int32)
        jp = np.zeros((G1, _JP_COLS), np.int32)

        live = []
        for (spec, rows, slot), ld in zip(members, loaded):
            scorer, vals = spec.scorer, spec.vals
            if int(ld[5]) != len(vals["consensus"]):
                continue  # engine/ledger desync: solo path decides
            wm = int(scorer._W)
            if wm > self.W:
                continue  # grew past the pool since probe: solo decides
            ns = min(len(rows), scorer._R)
            rs = rows[:ns]
            # width-sliced gather: the member's [ns, wm] state lands in
            # the pool rows' first wm columns; the padding columns keep
            # the INF fill the kernel's stride mask re-asserts each step
            D[rs, :wm] = ld[0][:ns]
            e[rs] = ld[1][:ns]
            rmin[rs] = ld[2][:ns]
            er[rs] = ld[3][:ns]
            off[rs] = scorer._off_host[slot][:ns]
            act[rs] = scorer._act_host[slot][:ns]
            wrow[rs] = wm
            g = len(live)
            seg[rows] = g
            cc = min(scorer._C, self.C)
            cons[g, :cc] = ld[4][:cc]
            clen[g] = int(ld[5])
            cfg = scorer.config
            wc_int = (
                scorer.sym_id.get(cfg.wildcard, -2)
                if cfg.wildcard is not None else -2
            )
            jp[g] = (
                1,
                min(int(vals["me_budget"]), 2**31 - 1),
                min(int(vals["other_cost"]), 2**31 - 1),
                int(vals["other_len"]),
                int(vals["min_count"]),
                int(bool(vals["l2"])),
                int(vals["max_steps"]),
                int(vals["first_sym"]),
                int(wc_int),
                int(bool(cfg.allow_early_termination)),
            )
            live.append(((spec, rows, slot), ld, ns, wm))
        if len(live) < 2:
            return []

        self._ensure_pool()
        if self._kernel is None:
            self._kernel = _shared_kernel(self)
        js._note_compile(
            "j_run_ragged", (P, self.W, self.L, self.C, G1, self.A)
        )
        with _phases.device_scope(rec):
            out_dev = self._kernel(
                self._reads[:P], self._rlen[:P], D, e, rmin, er, off,
                act, seg, wrow, cons, clen, jp, A=self.A,
            )
            if rec is not None:
                # profiling fences the async dispatch so the device_get
                # below measures pure transfer
                out_dev = jax.block_until_ready(out_dev)
        with _phases.transfer_scope(rec):
            out = jax.device_get(out_dev)
        (oD, oe, ormin, oer, ocons, oclen, osteps, ocode, oiters,
         oeds, oocc, osplit, oreached, ofin, ofovf) = out

        keys: List[Tuple[int, int]] = []
        n_members = len(live)
        n_rows = sum(ns for _m, _ld, ns, _wm in live)
        widths = {wm for _m, _ld, _ns, wm in live}
        for g, ((spec, rows, slot), ld, ns, wm) in enumerate(live):
            scorer = spec.scorer
            rs = rows[:ns]
            try:
                # store back: the kernel rows' first wm columns (the
                # member's stride) overwrite the member's first ns
                # state rows, the tail keeps its loaded values
                Dn = np.array(ld[0])
                Dn[:ns] = oD[rs, :wm]
                en = np.array(ld[1]); en[:ns] = oe[rs]
                rn = np.array(ld[2]); rn[:ns] = ormin[rs]
                ern = np.array(ld[3]); ern[:ns] = oer[rs]
                cn = np.array(ld[4])
                cc = min(scorer._C, self.C)
                cn[:cc] = ocons[g, :cc]
                js._note_compile("j_slot_put", tuple(
                    scorer._state[k].shape for k in
                    ("D", "e", "rmin", "er", "cons", "clen")
                ))
                scorer._state = js._j_slot_put(
                    scorer._state, np.int32(slot), Dn, en, rn, ern, cn,
                    np.int32(oclen[g]),
                )
            except Exception:  # noqa: BLE001 - degrade this member only
                with self._lock:
                    self._counters["member_store_failures"] += 1
                logger.warning(
                    "ragged store-back failed for member %d; solo "
                    "fallback", g, exc_info=True,
                )
                state_lost = any(
                    getattr(leaf, "is_deleted", lambda: False)()
                    for leaf in jax.tree_util.tree_leaves(scorer._state)
                )
                if state_lost:
                    raise  # unrecoverable: supervisor machinery handles
                continue
            len0 = len(spec.vals["consensus"])
            steps = int(osteps[g])
            inj = _Injected(
                len0=len0,
                steps=steps,
                code=int(ocode[g]),
                ids=np.asarray(ocons[g, len0:len0 + max(steps, 0)]),
                stats=(
                    oeds[rs], oocc[rs], osplit[rs], oreached[rs],
                    ofin[rs], not bool(ofovf[g]),
                ),
                iters=int(oiters[g]),
            )
            key = (id(scorer), int(spec.h))
            with self._lock:
                self._injected[key] = inj
            keys.append(key)

        with self._lock:
            self._counters["groups"] += 1
            self._counters["members"] += n_members
            self._counters["occupancy_max"] = max(
                self._counters["occupancy_max"], n_members
            )
            self._counters["gang_rows"] += n_rows
            if len(widths) > 1:
                self._counters["mixed_w_groups"] += 1
        if obs_metrics.metrics_enabled():
            reg = obs_metrics.registry()
            reg.histogram(
                "waffle_ragged_occupancy",
                buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
            ).observe(n_members)
            # stride-mixed gangs: occupancy alone under-reports device
            # utilization when member row counts differ, so publish the
            # actual rows stepped and the width mix alongside it
            reg.histogram(
                "waffle_ragged_gang_rows",
                buckets=obs_metrics.DEFAULT_COUNT_BUCKETS,
            ).observe(n_rows)
            reg.gauge("waffle_ragged_gang_widths").set(len(widths))
        return keys

    # -- introspection -------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            c = dict(self._counters)
        groups = c["groups"]
        return {
            "active": True,
            "enabled": enabled(),
            "mixed_w": mixed_w_enabled(),
            "rows": self.rows,
            "page_rows": self.page_rows,
            "pages_total": self.pages.n_pages,
            "pages_used": self.pages.used_pages,
            "pages_free": self.pages.free_pages,
            "band_e": self.E,
            "gang": self.gang,
            "mean_occupancy": (c["members"] / groups) if groups else 0.0,
            "mean_gang_rows": (c["gang_rows"] / groups) if groups else 0.0,
            **c,
        }


# ======================================================================
# frontier gang: same-search speculation through the ragged kernel


@dataclass
class GangMember:
    """One branch's speculated ``run_extend`` call for a frontier gang:
    the in-hand node carries its real arguments; peers carry the
    engine's *prediction* of the arguments their own future pop will
    use (prediction quality only affects the commit rate — consumption
    is validated against the real arguments, so any prediction is
    byte-safe)."""

    h: int
    consensus: bytes
    me_budget: int
    other_cost: int
    other_len: int
    max_steps: int
    first_sym: int = -1


class FrontierGang:
    """Same-search speculative ganging: advance the top-M branches of
    ONE search through the shared ragged kernel in a single dispatch.

    Branches of one search share the scorer — hence band width — so the
    per-row W stride is uniform here (the kernel's stride mask
    degenerates to all-true and the self-gang is byte-identical to the
    pre-stride kernel by construction); scorers of *different* natural
    widths still share the one process-wide kernel closure, each
    compiling only its own ``W`` axis.  Member ``g`` occupies
    pool rows ``g*R .. g*R+R-1`` over the scorer's reads tiled ``P/R``
    times, so the exact segment-reduce kernel the serving arena
    compiles also serves the self-gang (one extra specialization per
    pow2 member count).  Results deposit as consume-once
    :class:`_SpecInjected` records holding the post-run state as host
    rows; no slot is touched at gang time, so a mispredicted member's
    solo fallback runs from pristine state (see ``_SpecInjected``).

    Single-threaded by design: the gang belongs to one search loop and
    frontier ganging is disabled under ``serve_scope`` (the coalescing
    dispatcher owns cross-job batching there)."""

    #: fixed member-group capacity: jp/cons group shapes stay constant
    #: so adaptive M only ladders the pow2 row-prefix compile key
    G = 8

    _build_kernel = BandArena._build_kernel

    def __init__(self, scorer) -> None:
        self.scorer = scorer
        self._kernel = None
        self._tiles: Dict[int, tuple] = {}   # P -> (reads_dev, rlen_dev)
        self._reads_host = None              # (np reads, np rlen)
        self._injected: Dict[int, _SpecInjected] = {}
        self.counters = {
            "groups": 0, "members": 0, "deposits": 0, "dropped": 0,
            "occupancy_max": 0,
        }

    # -- consume-once deposits -----------------------------------------

    def take(self, h: int) -> Optional[_SpecInjected]:
        return self._injected.pop(int(h), None)

    def pending(self, h: int) -> bool:
        return int(h) in self._injected

    def drop(self, h: int) -> None:
        """Invalidate a branch's deposit: its slot mutated (push /
        activate / arena / free) so the held post-state is stale."""
        if self._injected.pop(int(h), None) is not None:
            self.counters["dropped"] += 1

    def drop_all(self) -> None:
        """Invalidate everything: a geometry grow or supervisor
        demotion obsoleted every held post-state at once."""
        n = len(self._injected)
        if n:
            self._injected.clear()
            self.counters["dropped"] += n

    # -- staging -------------------------------------------------------

    def _tile(self, P: int):
        """Reads pool for row-prefix ``P``: the scorer's reads tiled to
        fill every member block (cached per P; reads never change)."""
        t = self._tiles.get(P)
        if t is None:
            import jax

            sc = self.scorer
            if self._reads_host is None:
                # one-time staging fetch; attributed to the active
                # dispatch record when there is one (NULL_SCOPE when not)
                with _phases.transfer_scope(_phases.current()):
                    self._reads_host = (
                        np.asarray(jax.device_get(sc._reads)),
                        np.asarray(jax.device_get(sc._rlen)),
                    )
            reads_np, rlen_np = self._reads_host
            reps = P // reads_np.shape[0]
            t = (
                jax.device_put(np.tile(reads_np, (reps, 1))),
                jax.device_put(np.tile(rlen_np, reps)),
            )
            self._tiles[P] = t
        return t

    # -- gang execution ------------------------------------------------

    def run(self, members: List[GangMember], min_count: int, l2: bool,
            cols: int = 1) -> int:
        """One gang dispatch over ``members``; deposits a speculative
        injection per member (the engine consumes the in-hand member's
        immediately, peers' wait for their pops).  Returns the deposit
        count.  Never raises: any failure leaves every slot untouched
        and the affected members simply run solo."""
        rec = _phases.begin("frontier_gang", "jax")
        try:
            return self._run(members, min_count, l2, cols)
        except Exception:  # noqa: BLE001 - speculation must never fail
            logger.warning(
                "frontier gang of %d failed; members fall back to solo",
                len(members), exc_info=True,
            )
            return 0
        finally:
            _phases.end(rec)

    def _run(self, members: List[GangMember], min_count: int, l2: bool,
             cols: int) -> int:
        import jax

        from waffle_con_tpu.ops import jax_scorer as js

        sc = self.scorer
        if getattr(sc, "_shardings", None) is not None:
            return 0  # mesh-sharded state: slot gather spans shards
        R, W, C, A = sc._R, sc._W, sc._C, sc._A
        G, G1 = self.G, self.G + 1
        live0 = []
        for m in members[:G]:
            slot = sc._slot_of.get(m.h)
            if slot is None or int(m.h) in self._injected:
                continue
            if len(m.consensus) + int(m.max_steps) + 2 >= C:
                continue  # the solo wrapper would grow; don't speculate
            live0.append((m, slot))
        if len(live0) < 2:
            return 0
        nrows = len(live0) * R
        P = 1
        while P < nrows:
            P *= 2

        rec = _phases.current()
        if rec is not None:
            rec.annotate(
                kernel="frontier", k=int(cols),
                geom=f"P{P}W{W}G{len(live0)}",
            )

        # one bundled device_get: every member's full band-state rows
        slots = np.asarray([slot for _m, slot in live0], np.int64)
        st = sc._state
        with _phases.transfer_scope(rec):
            gD, ge, grmin, ger, gcons, gclen = jax.device_get((
                st["D"][slots], st["e"][slots], st["rmin"][slots],
                st["er"][slots], st["cons"][slots], st["clen"][slots],
            ))

        INF = int(js.INF)
        D = np.full((P, W), INF, np.int32)
        e = np.zeros(P, np.int32)
        rmin = np.full(P, INF, np.int32)
        er = np.full(P, INF, np.int32)
        off = np.zeros(P, np.int32)
        act = np.zeros(P, bool)
        seg = np.full(P, G, np.int32)
        # one scorer, one band width: the stride axis is uniform
        wrow = np.full(P, W, np.int32)
        cons = np.zeros((G1, C), np.int32)
        clen = np.zeros(G1, np.int32)
        jp = np.zeros((G1, _JP_COLS), np.int32)
        cfg = sc.config
        wc_int = (
            sc.sym_id.get(cfg.wildcard, -2)
            if cfg.wildcard is not None else -2
        )
        et_int = int(bool(cfg.allow_early_termination))
        live = []
        for i, (m, slot) in enumerate(live0):
            if int(gclen[i]) != len(m.consensus):
                continue  # engine/slot desync: solo path decides
            g = len(live)
            rs = slice(g * R, (g + 1) * R)
            D[rs] = gD[i]
            e[rs] = ge[i]
            rmin[rs] = grmin[i]
            er[rs] = ger[i]
            off[rs] = sc._off_host[slot]
            act[rs] = sc._act_host[slot]
            seg[rs] = g
            cons[g] = gcons[i]
            clen[g] = int(gclen[i])
            jp[g] = (
                1,
                min(int(m.me_budget), 2**31 - 1),
                min(int(m.other_cost), 2**31 - 1),
                int(m.other_len),
                int(min_count),
                int(bool(l2)),
                int(m.max_steps),
                int(m.first_sym),
                int(wc_int),
                et_int,
            )
            live.append(m)
        if len(live) < 2:
            return 0

        if self._kernel is None:
            self._kernel = _shared_kernel(self)
        reads_t, rlen_t = self._tile(P)
        js._note_compile(
            "j_run_ragged", (P, W, sc._L, C, G1, A, int(cols))
        )
        with _phases.device_scope(rec):
            out_dev = self._kernel(
                reads_t, rlen_t, D, e, rmin, er, off, act, seg, wrow,
                cons, clen, jp, A=A, cols=int(cols),
            )
            if rec is not None:
                out_dev = jax.block_until_ready(out_dev)
        with _phases.transfer_scope(rec):
            out = jax.device_get(out_dev)
        (oD, oe, ormin, oer, ocons, oclen, osteps, ocode, oiters,
         oeds, oocc, osplit, oreached, ofin, ofovf) = out

        for g, m in enumerate(live):
            rs = slice(g * R, (g + 1) * R)
            len0 = len(m.consensus)
            steps = int(osteps[g])
            eds_g = np.array(oeds[rs])
            cost_rows = eds_g.astype(np.int64)
            if l2:
                cost_rows = cost_rows * cost_rows
            # inactive rows carry eds 0, so a plain sum IS the kernel's
            # segment total at the stopped state
            final_cost = min(int(cost_rows.sum()), 2**31 - 1)
            self._injected[int(m.h)] = _SpecInjected(
                len0=len0,
                steps=steps,
                code=int(ocode[g]),
                ids=np.array(ocons[g, len0:len0 + max(steps, 0)]),
                stats=(
                    eds_g, np.array(oocc[rs]), np.array(osplit[rs]),
                    np.array(oreached[rs]), np.array(ofin[rs]),
                    not bool(ofovf[g]),
                ),
                iters=int(oiters[g]),
                first_sym=int(m.first_sym),
                final_cost=final_cost,
                min_count=int(min_count),
                l2=bool(l2),
                me_budget=min(int(m.me_budget), 2**31 - 1),
                other_cost=min(int(m.other_cost), 2**31 - 1),
                other_len=int(m.other_len),
                post=(
                    np.array(oD[rs]), np.array(oe[rs]),
                    np.array(ormin[rs]), np.array(oer[rs]),
                    np.array(ocons[g]), int(oclen[g]),
                ),
            )
        n = len(live)
        self.counters["groups"] += 1
        self.counters["members"] += n
        self.counters["deposits"] += n
        self.counters["occupancy_max"] = max(
            self.counters["occupancy_max"], n
        )
        scc = getattr(sc, "counters", None)
        if scc is not None:
            scc["gang_groups"] = scc.get("gang_groups", 0) + 1
            scc["gang_members"] = scc.get("gang_members", 0) + n
        if obs_metrics.metrics_enabled():
            reg = obs_metrics.registry()
            reg.gauge("waffle_frontier_gang_occupancy").set(n)
            reg.counter("waffle_frontier_gang_deposits_total").inc(n)
        return n

    def stats(self) -> Dict:
        c = dict(self.counters)
        groups = c["groups"]
        return {
            "pending": len(self._injected),
            "mean_occupancy": (c["members"] / groups) if groups else 0.0,
            **c,
        }


def frontier_gang_for(scorer) -> FrontierGang:
    """The scorer's lazily created frontier gang (one per scorer; lives
    and dies with it)."""
    gang = getattr(scorer, "_frontier_gang", None)
    if gang is None:
        gang = FrontierGang(scorer)
        scorer._frontier_gang = gang
    return gang


def serving_active() -> bool:
    """True inside a ``serve_scope`` — the coalescing dispatcher owns
    batching there, so engines must not self-gang (a frontier dispatch
    would race the cross-job ragged pass over the same slots).  The
    nesting counter (not mere attribute presence — an exited scope
    leaves it at 0) decides, so a thread that once served a job gets
    its self-ganging back afterwards."""
    return bool(getattr(_TLS, "serving", 0))


# ======================================================================
# shared ragged kernel
#
# _build_kernel's jitted body closes over nothing per-instance — every
# geometry input arrives as a (shape-keyed) argument — so one jit
# closure serves every arena in the process.  Replicated serving spins
# up one arena per replica; without this cache each would recompile the
# identical kernel ladder.

_KERNEL_LOCK = lockcheck.make_lock("ops.ragged.KERNEL_CACHE")
_RAGGED_KERNEL = None


def _shared_kernel(arena: "BandArena"):
    global _RAGGED_KERNEL
    with _KERNEL_LOCK:
        if _RAGGED_KERNEL is None:
            _RAGGED_KERNEL = arena._build_kernel()
        return _RAGGED_KERNEL


# ======================================================================
# process-wide arena registry + module-level API (what the serve layer
# calls).  The DEFAULT arena backs the single-service path exactly as
# before; replicas create NAMED arenas (one per replica) so residency,
# paging, and gang formation stay replica-local.  Scorer-keyed lookups
# (take_injected / release_scorer / discard_injected) search every
# arena — id(scorer) is process-unique, so at most one arena answers —
# which keeps the call sites inside jax_scorer.py arena-agnostic.
# Job-id-keyed release is arena-scoped: job ids are per-service
# counters and WOULD collide across replicas.

_ARENA: Optional[BandArena] = None
_ARENA_LOCK = lockcheck.make_lock("ops.ragged.PROCESS_ARENA")
_NAMED_ARENAS: Dict[str, BandArena] = {}


def get_arena() -> BandArena:
    global _ARENA
    with _ARENA_LOCK:
        if _ARENA is None:
            _ARENA = BandArena(ArenaConfig.from_env())
        return _ARENA


def peek_arena() -> Optional[BandArena]:
    return _ARENA


def new_arena(name: str, config: Optional[ArenaConfig] = None) -> BandArena:
    """Create (or replace) the named arena — one per serve replica."""
    arena = BandArena(config or ArenaConfig.from_env())
    with _ARENA_LOCK:
        _NAMED_ARENAS[name] = arena
    return arena


def drop_arena(name: str) -> None:
    with _ARENA_LOCK:
        _NAMED_ARENAS.pop(name, None)


def _all_arenas() -> List[BandArena]:
    with _ARENA_LOCK:
        out = [] if _ARENA is None else [_ARENA]
        out.extend(_NAMED_ARENAS.values())
        return out


def reset_arena() -> None:
    """Drop the process arena and any named replica arenas (tests
    re-read the env knobs; any device pool memory is released with
    them)."""
    global _ARENA
    with _ARENA_LOCK:
        _ARENA = None
        _NAMED_ARENAS.clear()


def gang_width(arena: Optional[BandArena] = None) -> int:
    return (arena or get_arena()).gang


def probe(payload, ticket=None,
          arena: Optional[BandArena] = None) -> Optional[RunSpec]:
    """Resolve one parked ``run_extend`` dispatch into a gang member.

    ``payload`` is ``(probe_attr, args, kwargs)`` captured by the
    coalescing proxy; ``probe_attr`` hops the proxy/supervisor stack to
    the live ``JaxScorer`` endpoint (or None when the current backend
    cannot take part).  ``arena`` pins admission to one replica's
    arena (default: the process arena).  Returns None — bucketed/solo
    fallback — on any ineligibility, including pool exhaustion."""
    if not enabled():
        return None
    probe_fn, args, kwargs = payload
    vals = _normalize_run_args(args, kwargs)
    if vals is None:
        return None
    try:
        endpoint = probe_fn(vals["h"])
    except Exception:  # noqa: BLE001 - a dead handle just runs solo
        return None
    if endpoint is None:
        return None
    scorer, bh = endpoint
    arena = arena if arena is not None else get_arena()
    if not arena.eligible(scorer, vals):
        return None
    job_id = getattr(ticket, "job_id", None)
    if arena.try_admit(scorer, job_id) is None:
        return None
    return RunSpec(
        scorer=scorer, h=int(bh), vals=vals, ticket=ticket, job_id=job_id
    )


def run_group(specs: List[RunSpec],
              arena: Optional[BandArena] = None) -> List[Tuple[int, int]]:
    return (arena if arena is not None else get_arena()).run_group(specs)


def take_injected(scorer, h: int):
    # frontier-gang deposits first: they are search-local (same thread)
    # and mutually exclusive with serving-path deposits by construction
    gang = getattr(scorer, "_frontier_gang", None)
    if gang is not None:
        inj = gang.take(h)
        if inj is not None:
            return inj
    for a in _all_arenas():
        inj = a.take_injected(scorer, h)
        if inj is not None:
            return inj
    return None


def discard_injected(keys, arena: Optional[BandArena] = None) -> None:
    if arena is not None:
        arena.discard_injected(keys)
        return
    for a in _all_arenas():
        a.discard_injected(keys)


def release_scorer(scorer) -> None:
    # supervisor demotion / backend swap: every held speculative state
    # is stale by definition (the rebuilt backend replays its ledger)
    gang = getattr(scorer, "_frontier_gang", None)
    if gang is not None:
        gang.drop_all()
    for a in _all_arenas():
        a.release_scorer(scorer)


def recenter_scorer(scorer) -> bool:
    """Band geometry changed (E doubled / re-centered): drop the
    scorer's stale deposits everywhere but KEEP its arena residency —
    the page run holds reads, which a band change does not touch, so
    the member re-gangs at its new per-row stride on the next probe
    instead of paying release + re-admission (or, pre-stride, falling
    solo forever).  Returns True while the scorer is still resident in
    some arena (False: evicted — the new width outgrew the pool)."""
    gang = getattr(scorer, "_frontier_gang", None)
    if gang is not None:
        gang.drop_all()
    resident = False
    for a in _all_arenas():
        if a.recenter_scorer(scorer):
            resident = True
    return resident


def release_job(job_id, arena: Optional[BandArena] = None) -> None:
    if arena is not None:
        arena.release_job(job_id)
        return
    a = _ARENA
    if a is not None:
        a.release_job(job_id)


def arena_stats(arena: Optional[BandArena] = None) -> Dict:
    a = arena if arena is not None else _ARENA
    if a is None:
        return {"active": False, "enabled": enabled()}
    return a.stats()
