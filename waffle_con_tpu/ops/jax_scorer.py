"""Batched JAX/TPU scorer: banded edit-distance column DP.

The TPU-native implementation of the
:class:`~waffle_con_tpu.ops.scorer.WavefrontScorer` seam.  Where the
reference maintains one incremental wavefront object per read and mutates
it serially per appended consensus symbol
(``/root/reference/src/consensus.rs:455-463``,
``/root/reference/src/dynamic_wfa.rs:75-191``), this scorer re-derives
every DWFA observable from a *banded Levenshtein column* and advances all
(branch, read) lanes in one fused, fixed-shape XLA step per symbol — no
data-dependent control flow, no per-lane gathers, nothing that fights the
TPU's vector unit.

Equivalence (proved against the oracle by the parity suite): let
``D[j, i]`` be the edit distance between ``cons[off:j]`` and ``read[:i]``.
For a band of half-width ``E`` around the main diagonal:

* ``DWFALite.edit_distance`` after ``update`` == the running column
  minimum ``colmin_j = min_i D[j, i]`` (monotone in ``j``), except under
  early termination where it freezes (below).
* tip votes (``get_extension_candidates``,
  ``/root/reference/src/dynamic_wfa.rs:241-255``) == the multiset of
  ``read[i]`` over band cells with ``D[j, i] <= e`` and ``i < len(read)``
  — each wavefront diagonal maps to exactly one column cell.
* ``finalize`` == ``max(e, rmin)`` where ``rmin = min_{j' <= j} D[j',
  len(read)]`` is a running minimum over the read-end row.
* ``reached_baseline_end`` has the reference's overshoot semantics
  (``max_base == blen`` with out-of-bounds deletion entries): the
  wavefront first touches the read end at cost ``er = max(e, rmin)`` and
  every later escalation pushes ``max_base`` past the end, so
  ``reached == (e == er)`` with ``er`` latched at first touch.
* early termination stops escalation once reached:
  ``e' = min(colmin, max(e, rmin))`` while unlatched, frozen afterwards.

Each column step costs ~30 vector ops on ``[R, W]`` lanes (the insertion
chain is a ``cummin`` prefix scan), so whole unambiguous consensus runs
execute on device via ``lax.while_loop`` with one host round-trip per
*event* — the design target that makes the search loop TPU-viable.

Band growth: values are exact wherever ``D < E``; when a reported
quantity would reach ``E`` the kernel refuses to commit and the host
doubles ``E`` and *replays* the columns from the recorded per-branch
consensus (the band holds only a window, so unlike a wavefront it cannot
be re-padded in place).  Growth is geometric, replays are rare and run as
one device scan.

Sharding: all state is ``[B, R, W]`` with reads as the embarrassingly
parallel axis; :mod:`waffle_con_tpu.parallel` places these arrays over a
``jax.sharding.Mesh`` so the same kernels run 1-chip or N-chip.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.obs import phases as _phases
from waffle_con_tpu.obs.trace import span as _obs_span
from waffle_con_tpu.utils import envspec
from waffle_con_tpu.ops.scorer import (
    BranchStats,
    DeferredStats,
    WavefrontScorer,
    deferred_sync_enabled,
    megastep_enabled,
)

#: Numpy (not jnp) module constants: a ``jnp`` scalar here would (a) force
#: backend init at import time and (b) on this platform every eagerly
#: dispatched tiny op costs 60-350ms wall even on compile-cache hits, so
#: everything outside ``jit`` stays numpy and becomes a traced literal.
INF = np.int32(1 << 20)

#: f32-vs-f64 vote-sum comparison margin for the device run loops: decisions
#: with margins under this are host events.  Conservatively above the worst
#: accumulated f32 error for thousands of reads (exact one-hot integer votes
#: bypass it entirely, so clean stretches never false-stop).
VOTE_EPS = np.float32(1e-2)

#: capacity of the run loops' record-absorption buffers (finalized
#: snapshots of reached states committed through); a run needing more
#: records than this stops with code 2 and the host continues normally
REC_CAP = 256

#: int16 band-state "infinity" (mirrors ``pallas_run.DINF16``): large
#: enough that no reachable finite cell cost can touch it under the
#: ``_xla_i16_ok`` geometry bound, small enough that ``DINF16 + 1`` (a
#: deletion out of an invalid cell) cannot wrap int16.
DINF16 = np.int32(30000)

logger = logging.getLogger(__name__)

#: default speculative block width (columns per device ``while_loop``
#: iteration) per JAX backend, chosen by ``scripts/ubench_jrun.py --sweep``
#: measurement: on XLA:CPU the per-iteration fixed cost (loop condition,
#: buffer rotation, per-op launch latency of the body's small fused
#: kernels) dominates the [R, W] column math, so unrolling K columns into
#: one iteration amortizes it almost linearly until compile time and
#: masked-tail waste push back.  The north-star sweep measured a
#: plateau from K=4 (951 -> 1063 steps/s at K=4; 1053 at K=8; 1056 at
#: K=16) with compile time still doubling per octave, so the default
#: sits at the knee.  Override with ``WAFFLE_RUN_COLS``.
_RUN_COLS_DEFAULT = {"cpu": 4, "tpu": 4, "gpu": 4}

_RUN_COLS_MAX = 64


def _run_cols() -> int:
    """Speculative columns per device loop iteration (the K knob).

    Read per run call so tests can flip ``WAFFLE_RUN_COLS`` at runtime
    (each distinct K is a static argument — its own compiled kernel).
    K=1 compiles to the pre-speculation single-column kernel."""
    env = envspec.get_raw("WAFFLE_RUN_COLS")
    if env:
        try:
            return max(1, min(_RUN_COLS_MAX, int(env)))
        except ValueError:
            return 1
    return _RUN_COLS_DEFAULT.get(jax.default_backend(), 1)


#: default megastep composition M (blocks of K columns per while-loop
#: iteration).  Unlike raising K — whose unrolled body doubles compile
#: time per octave and measurably LOSES throughput past the K=4 knee
#: (see ``_RUN_COLS_DEFAULT``) — the M blocks run through one traced
#: ``fori_loop`` body, so M*K columns amortize the loop-condition /
#: carry-rotation overhead at the compile cost of the K-column body.
_MEGA_BLOCKS_DEFAULT = 8

_MEGA_BLOCKS_MAX = 64

_MEGA_SYMS_MAX = 1 << 20


def _mega_blocks() -> int:
    """Megastep blocks M per device loop iteration (the
    ``WAFFLE_MEGA_BLOCKS`` knob, clamped 1..64).  Read per run call so
    tests can flip it at runtime; each distinct M is a static argument
    of ``_j_run_mega`` (its own compiled kernel)."""
    env = envspec.get_raw("WAFFLE_MEGA_BLOCKS")
    if env:
        try:
            return max(1, min(_MEGA_BLOCKS_MAX, int(env)))
        except ValueError:
            return 1
    return _MEGA_BLOCKS_DEFAULT


def _mega_syms() -> int:
    """Per-dispatch commit budget of a megastep run (the
    ``WAFFLE_MEGA_SYMS`` knob): caps the caller's ``max_steps``.
    Capping is always exact — the committed prefix is identical and a
    budget-capped run stops with code 4, which the engines already
    treat as "re-engage from here"."""
    env = envspec.get_raw("WAFFLE_MEGA_SYMS")
    if env:
        try:
            return max(1, min(_MEGA_SYMS_MAX, int(env)))
        except ValueError:
            return _MEGA_SYMS_MAX
    return 65536


def _xla_i16_ok(L: int, C: int, W: int) -> bool:
    """True when every finite cell cost the banded DP can produce fits
    strictly under :data:`DINF16` (same bound as ``pallas_run.i16_ok``),
    so narrowing ``D`` to int16 is value-exact."""
    return max(L, C) + W + 4 < int(DINF16)


#: band width from which megastep dispatches turn int16 band state on
#: even on CPU (see ``JaxScorer._xla_i16``): the W=98 fixture sweep
#: measured i16 neutral-to-slightly-worse there, while the W=434
#: north-star geometry measured +17% — the crossover is where the
#: ``[R, W]`` column math stops fitting cache and goes memory-bound
_MEGA_I16_MIN_W = 256


def _next_pow2(n: int, minimum: int = 1) -> int:
    return max(minimum, 1 << max(0, (n - 1).bit_length()))


#: first-seen static geometry keys of the heavy jitted entry points.
#: Every new (kernel, static-shape-key) pair is a fresh XLA compilation
#: (modulo the on-disk compile cache, which still costs a trace +
#: deserialize), so the set size is the process's recompile count — the
#: number the serve bench and CI assert stays constant under ragged
#: dispatch no matter how many distinct job geometries arrive.
_COMPILE_SEEN: set = set()


def _note_compile(kernel: str, key: tuple) -> None:
    """Record a (kernel, static-shape-key) pair the first time it is
    dispatched; backs ``waffle_compile_total`` and ``compile_count``."""
    k = (kernel,) + tuple(key)
    if k in _COMPILE_SEEN:
        return
    _COMPILE_SEEN.add(k)
    from waffle_con_tpu.obs import metrics as obs_metrics

    if obs_metrics.metrics_enabled():
        obs_metrics.registry().counter(
            "waffle_compile_total", kernel=kernel
        ).inc()


def compile_count() -> int:
    """Distinct (kernel, geometry) compilations seen this process."""
    return len(_COMPILE_SEEN)


@partial(jax.jit, donate_argnums=(0,))
def _j_slot_put(state, h, D, e, rmin, er, cons, clen):
    """Store a full band-state row set back into slot ``h`` (the ragged
    arena's scatter-back after a gang step); donation keeps it a cheap
    in-place update of the state dict's big buffers."""
    return dict(
        state,
        D=state["D"].at[h].set(D),
        e=state["e"].at[h].set(e),
        rmin=state["rmin"].at[h].set(rmin),
        er=state["er"].at[h].set(er),
        cons=state["cons"].at[h].set(cons),
        clen=state["clen"].at[h].set(clen),
    )


# ======================================================================
# column kernels.  A "row" is one branch: D [R, W] plus per-read scalars.
# All dense symbol ids; `wc` is the wildcard dense id or -2; `et` is
# allow_early_termination as a traced bool.


def _init_col(off, act, rlen, E, W):
    """Fresh DP column at ``j == off`` (nothing of the consensus consumed):
    cost of read prefix ``i`` is ``i``.  Returns (D, e, rmin, er)."""
    t = jnp.arange(W, dtype=jnp.int32)[None, :]
    i0 = t - E  # j - off - E + t with j == off
    D = jnp.where((i0 >= 0) & (i0 <= rlen[:, None]), i0, INF)
    D = jnp.where(act[:, None], D, INF)
    e = jnp.zeros(off.shape, jnp.int32)
    rmin = jnp.where(act & (rlen <= E + 1), rlen, INF)
    er = jnp.where(rmin <= 0, 0, INF)
    return D, e, rmin, er


def _read_window(reads_pad, start, R, W):
    """One ``[R, W]`` window of the W-left-padded reads array whose row
    ``r`` holds ``reads[r, x - W]``: a single ``dynamic_slice`` — the TPU
    fast path replacing per-lane ``take_along_axis`` gathers (measured
    ~2.7 ms/step vs ~0 for the slice at north-star shapes).  Clipping the
    start is safe: it only engages when every in-band read position is
    already out of range, and those lanes are masked invalid."""
    Lp = reads_pad.shape[1]
    return lax.dynamic_slice(
        reads_pad, (0, jnp.clip(start, 0, Lp - W)), (R, W)
    )


#: block width of the two-level prefix-min scan; 8 measured fastest on
#: CPU at band shapes (the scan is memory-bound: 3 masked shift passes
#: plus one carry combine beat the 10-pass log-shift lowering)
_CUMMIN_BLOCK = 8


def _cummin_rows(x):
    """Exact row-wise prefix min (``lax.cummin(x, axis=1)``) via a
    two-level masked-shift scan: per-block local prefix mins (shift-min
    passes that never cross block boundaries), a tiny prefix min over the
    per-block tails, then one combine pass.  Roughly halves the memory
    passes of the stock log-shift lowering on CPU; other backends keep
    the stock scan (XLA:TPU lowers ``cummin`` through its own blocked
    reduce-window path already)."""
    if jax.default_backend() != "cpu":
        return lax.cummin(x, axis=1)
    R, W = x.shape
    G = _CUMMIN_BLOCK
    if W <= 2 * G:
        return lax.cummin(x, axis=1)
    t = jnp.arange(W, dtype=jnp.int32)
    within = t[None, :] % G
    blk = t // G
    nb = (W + G - 1) // G
    big = jnp.iinfo(x.dtype).max
    y = x
    k = 1
    while k < G:
        shifted = jnp.concatenate(
            [jnp.full((R, k), big, x.dtype), y[:, :-k]], axis=1
        )
        y = jnp.where(within >= k, jnp.minimum(y, shifted), y)
        k *= 2
    tails = y[:, G - 1 :: G]
    if tails.shape[1] < nb:  # partial last block: its tail is column W-1
        tails = jnp.concatenate([tails, y[:, -1:]], axis=1)
    carry = lax.cummin(tails, axis=1)
    cprev = jnp.take(carry, jnp.maximum(blk - 1, 0), axis=1)
    return jnp.where(blk[None, :] == 0, y, jnp.minimum(y, cprev))


def _col_step_w(D, e, rmin, er, off, act, rlen, bchar, jnew, sym, wc, et, E):
    """Advance one branch's banded columns from ``jnew-1`` to ``jnew`` by
    consuming consensus symbol ``sym``, with the read window ``bchar``
    (``bchar[r, t] == reads[r, i_new - 1]`` wherever ``i_new`` is in
    range) already fetched; returns updated (D, e, rmin, er) with
    inactive reads passed through unchanged.

    Dtype-polymorphic over ``D``: with int16 band state (the narrowed
    path gated by :func:`_xla_i16_ok`) the invalid sentinel is
    :data:`DINF16` instead of :data:`INF` and all column arithmetic
    stays int16 — value-exact because the geometry bound keeps every
    finite cell strictly under the sentinel.  The per-read running folds
    (``e``/``rmin``/``er``) always stay int32: they latch ``INF``."""
    R, W = D.shape
    dt = D.dtype
    narrowed = dt != jnp.int32
    big = jnp.asarray(DINF16 if narrowed else INF, dt)
    t = jnp.arange(W, dtype=jnp.int32)[None, :]
    i_new = jnew - off[:, None] - E + t

    sub = ((bchar != sym) & (bchar != wc)).astype(dt)

    diag = D + sub
    dele = (
        jnp.concatenate([D[:, 1:], jnp.full_like(D[:, :1], big)], axis=1)
        + jnp.asarray(1, dt)
    )
    base = jnp.minimum(diag, dele)
    invalid = (i_new < 0) | (i_new > rlen[:, None])
    base = jnp.where(invalid, big, base)
    # insertion chain within the column: prefix-min of (base - t) + t
    tt = t.astype(dt)
    chain = _cummin_rows(base - tt)
    Dn = jnp.minimum(jnp.minimum(base, chain + tt), big)

    colmin = Dn.min(axis=1).astype(jnp.int32)
    rend = (
        jnp.where(i_new == rlen[:, None], Dn, big).min(axis=1)
        .astype(jnp.int32)
    )
    if narrowed:  # restore the INF sentinel for the int32 latch folds
        colmin = jnp.where(colmin >= DINF16, INF, colmin)
        rend = jnp.where(rend >= DINF16, INF, rend)
    rmin_n = jnp.minimum(rmin, rend)
    e_uncapped = jnp.maximum(e, colmin)
    e_capped = jnp.where(
        er < INF, e, jnp.maximum(e, jnp.minimum(colmin, jnp.maximum(e, rmin_n)))
    )
    e_n = jnp.where(et, e_capped, e_uncapped)
    er_n = jnp.where(
        er < INF, er, jnp.where(rmin_n <= e_n, jnp.maximum(e, rmin_n), INF)
    )

    keep = act
    D = jnp.where(keep[:, None], Dn, D)
    e = jnp.where(keep, e_n, e)
    rmin = jnp.where(keep, rmin_n, rmin)
    er = jnp.where(keep, er_n, er)
    return D, e, rmin, er


def _col_step(D, e, rmin, er, off, act, rlen, reads, jnew, sym, wc, et, E):
    """Gather-sourced :func:`_col_step_w` (per-lane window positions; the
    general path for branches with non-uniform per-read offsets)."""
    W = D.shape[1]
    L = reads.shape[1]
    t = jnp.arange(W, dtype=jnp.int32)[None, :]
    i_new = jnew - off[:, None] - E + t
    bchar = jnp.take_along_axis(reads, jnp.clip(i_new - 1, 0, L - 1), axis=1)
    return _col_step_w(
        D, e, rmin, er, off, act, rlen, bchar, jnew, sym, wc, et, E
    )


def _col_step_u(
    D, e, rmin, er, off, act, rlen, reads_pad, jnew, off0, sym, wc, et, E
):
    """Slice-sourced :func:`_col_step_w` for branches whose ACTIVE reads
    all share offset ``off0``: the window start is lane-independent, so
    one ``dynamic_slice`` replaces the gather (inactive lanes read
    misaligned bytes, which the active-mask discards)."""
    R, W = D.shape
    bchar = _read_window(reads_pad, W + jnew - 1 - off0 - E, R, W)
    return _col_step_w(
        D, e, rmin, er, off, act, rlen, bchar, jnew, sym, wc, et, E
    )


def _stats_core_w(
    D, e, rmin, er, off, act, rlen, vchar, clen, num_symbols, E,
    a_real=None, pad=True,
):
    """Snapshot of one branch: per-read edit distance, tip votes over dense
    symbols, reached flags (reference overshoot semantics).  ``vchar`` is
    the read window at the tip column (``vchar[r, t] == reads[r, i]``
    wherever ``i`` is in range).

    ``a_real`` (static) bounds the one-hot vote fold to the engine's real
    dense alphabet: reads only ever hold ids below it, so the occupancy
    columns in ``[a_real, num_symbols)`` are structurally zero — skipping
    them halves the ``[R, W, A]`` reduce for a 4-symbol alphabet padded
    to the shared ``A = 8`` shape.  With ``pad`` the result is
    zero-padded back to ``[R, num_symbols]`` (the host-visible stats
    shape); the run loops pass ``pad=False`` and vote at ``a_real``."""
    R, W = D.shape
    ar = num_symbols if a_real is None else min(a_real, num_symbols)
    t = jnp.arange(W, dtype=jnp.int32)[None, :]
    i = clen - off[:, None] - E + t
    # with int16 band state the tip compare stays int16 (e is clamped to
    # the sentinel, which only engages on dead lanes where D == DINF16
    # matches the widened compare anyway)
    e_c = (
        e[:, None]
        if D.dtype == jnp.int32
        else jnp.minimum(e, DINF16)[:, None].astype(D.dtype)
    )
    tip = act[:, None] & (D <= e_c) & (i >= 0) & (i < rlen[:, None])
    if jax.default_backend() == "cpu" and W < (1 << 15):
        # bit-packed occupancy: two 15-bit per-symbol counters per int32
        # lane, one [R, W] fused select+reduce per symbol PAIR — ~6x
        # cheaper than the [R, W, A] one-hot reduce on CPU (counts are
        # bounded by W < 2^15, so the fields cannot carry).  Non-tip
        # lanes contribute nothing regardless of their window bytes
        # (pad bytes are -1: ``-1 >> 1 == -1`` never matches a pair id).
        w32 = vchar.astype(jnp.int32)
        accs = [
            jnp.where(
                tip & ((w32 >> 1) == k),
                jnp.int32(1) << (15 * (w32 & 1)),
                0,
            ).sum(axis=1)
            for k in range((ar + 1) // 2)
        ]
        occ = jnp.stack(
            [(accs[a // 2] >> (15 * (a & 1))) & 0x7FFF for a in range(ar)],
            axis=1,
        )
    else:
        onehot = (
            vchar[:, :, None] == jnp.arange(ar)[None, None, :]
        ) & tip[:, :, None]
        occ = onehot.sum(axis=1, dtype=jnp.int32)
    split = occ.sum(axis=1)
    if pad and ar < num_symbols:
        occ = jnp.pad(occ, ((0, 0), (0, num_symbols - ar)))
    reached = act & (er < INF) & (e == er)
    eds = jnp.where(act, e, 0)
    return eds, occ, split, reached


def _stats_core(
    D, e, rmin, er, off, act, rlen, reads, clen, num_symbols, E,
    a_real=None, pad=True,
):
    """Gather-sourced :func:`_stats_core_w` (general offsets path)."""
    W = D.shape[1]
    L = reads.shape[1]
    t = jnp.arange(W, dtype=jnp.int32)[None, :]
    i = clen - off[:, None] - E + t
    vchar = jnp.take_along_axis(reads, jnp.clip(i, 0, L - 1), axis=1)
    return _stats_core_w(
        D, e, rmin, er, off, act, rlen, vchar, clen, num_symbols, E,
        a_real=a_real, pad=pad,
    )


def _stats_core_u(
    D, e, rmin, er, off, act, rlen, reads_pad, clen, off0, num_symbols, E,
    a_real=None, pad=True,
):
    """Slice-sourced :func:`_stats_core_w` (uniform active offsets)."""
    R, W = D.shape
    vchar = _read_window(reads_pad, W + clen - off0 - E, R, W)
    return _stats_core_w(
        D, e, rmin, er, off, act, rlen, vchar, clen, num_symbols, E,
        a_real=a_real, pad=pad,
    )


def _finalized(e, rmin, act, E):
    """Finalized per-read distances (reference ``finalize`` semantics:
    ``max(e, rmin)``) plus the out-of-band flag — the ONE copy shared by
    ``_j_finalize`` and the bundled-``fin`` fast paths."""
    fin = jnp.maximum(e, rmin)
    ovf = (act & (fin >= E)).any()
    return jnp.where(act, jnp.minimum(fin, INF), 0), ovf


# ======================================================================
# whole-state jitted entry points.  state = dict of arrays; all donate the
# state buffers (every overflowing op masks its commit, so the returned
# state is unchanged when the host must re-bucket and retry).


@partial(jax.jit, static_argnames=("num_symbols",), donate_argnums=(0,))
def _j_root(state, reads, rlen, h, act, num_symbols):
    """Root a branch at the empty consensus; also returns the root's
    stats snapshot (the engines request it immediately, so bundling it
    here saves the separate stats dispatch+fetch)."""
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    off = jnp.zeros_like(state["off"][h])
    D, e, rmin, er = _init_col(off, act, rlen, E, W)
    stats = _stats_core(
        D, e, rmin, er, off, act, rlen, reads, jnp.int32(0), num_symbols, E
    )
    out = dict(state)
    out["D"] = state["D"].at[h].set(D)
    out["e"] = state["e"].at[h].set(e)
    out["rmin"] = state["rmin"].at[h].set(rmin)
    out["er"] = state["er"].at[h].set(er)
    out["off"] = state["off"].at[h].set(0)
    out["act"] = state["act"].at[h].set(act)
    out["clen"] = state["clen"].at[h].set(0)
    return out, stats


@partial(jax.jit, donate_argnums=(0,))
def _j_clone(state, src, dst):
    out = dict(state)
    for name in ("D", "e", "rmin", "er", "off", "act", "cons", "clen"):
        out[name] = state[name].at[dst].set(state[name][src])
    return out


@partial(jax.jit, donate_argnums=(0,))
def _j_clone_batch(state, srcs_dsts):
    """Copy a batch of branch slots (``srcs_dsts`` is ``[2, npad] int32``
    — source row then destination row; ``dsts`` padded with repeats of
    ``dsts[0]`` are fine: duplicate writes carry identical rows)."""
    srcs = srcs_dsts[0]
    dsts = srcs_dsts[1]
    out = dict(state)
    for name in ("D", "e", "rmin", "er", "off", "act", "cons", "clen"):
        out[name] = state[name].at[dsts].set(state[name][srcs])
    return out


@partial(jax.jit, donate_argnums=(0,))
def _j_deactivate(state, h, read_index):
    out = dict(state)
    out["act"] = state["act"].at[h, read_index].set(False)
    return out


@partial(jax.jit, donate_argnums=(0,))
def _j_deactivate_batch(state, hs_ridx):
    out = dict(state)
    out["act"] = state["act"].at[hs_ridx[0], hs_ridx[1]].set(False)
    return out


@partial(jax.jit, static_argnames=("B", "R", "W", "C"))
def _j_blank(B: int, R: int, W: int, C: int):
    """Blank branch store built ON DEVICE: one fused dispatch instead of
    a multi-MB host upload through the transfer tunnel."""
    return {
        "D": jnp.full((B, R, W), INF, jnp.int32),
        "e": jnp.zeros((B, R), jnp.int32),
        "rmin": jnp.full((B, R), INF, jnp.int32),
        "er": jnp.full((B, R), INF, jnp.int32),
        "off": jnp.zeros((B, R), jnp.int32),
        "act": jnp.zeros((B, R), bool),
        "cons": jnp.zeros((B, C), jnp.int32),
        "clen": jnp.zeros((B,), jnp.int32),
    }


@partial(jax.jit, static_argnames=("W",))
def _j_mkpad(reads, W: int):
    """W-left/right-padded reads copy, built on device from the staged
    reads array (saves re-uploading a second multi-MB array)."""
    R = reads.shape[0]
    fill = jnp.full((R, W), -1, reads.dtype)
    return jnp.concatenate([fill, reads, fill], axis=1)


#: per-dispatch step cap of the fused pallas run (SMEM symbol buffer
#: rows); a longer run stops with code 4 and the engine re-engages
_PALLAS_MS_CAP = 32768


@partial(jax.jit, static_argnames=("W", "rows"))
def _j_mkpad_T(reads, W: int, rows: int):
    """Transposed ``[rows, R]`` staging of the reads for the fused
    pallas kernel (band position on sublanes — Mosaic only allows
    dynamic slicing there): ``W`` rows of ``-1`` filler, then the read
    symbols, then filler to ``rows`` (see ``pallas_run.staging_rows``
    for the sizing argument — the pow2-padded storage tail is NOT
    materialized)."""
    R, L = reads.shape
    n = min(L, max(rows - W, 0))
    out = jnp.full((rows, R), -1, reads.dtype)
    return lax.dynamic_update_slice(out, reads.T[:n], (W, 0))


@partial(jax.jit, static_argnames=("new_b",))
def _j_grow_slots(state, new_b: int):
    """Double the branch-slot axis in one fused dispatch (the eager
    per-array ``at[].set`` path would cost 8 separate device ops)."""
    out = {}
    for name, arr in state.items():
        pad_shape = (new_b - arr.shape[0],) + arr.shape[1:]
        fill = INF if name in ("D", "rmin", "er") else 0
        pad = jnp.full(pad_shape, fill, dtype=arr.dtype)
        out[name] = jnp.concatenate([arr, pad], axis=0)
    return out


@partial(jax.jit, static_argnames=("new_c",))
def _j_grow_cons(state, new_c: int):
    """Double the consensus-capacity axis in one fused dispatch."""
    cons = state["cons"]
    pad = jnp.zeros((cons.shape[0], new_c - cons.shape[1]), dtype=cons.dtype)
    return dict(state, cons=jnp.concatenate([cons, pad], axis=1))


@partial(jax.jit, static_argnames=("num_symbols",), donate_argnums=(0,))
def _j_push_batch(state, reads, rlen, hs_syms, wc, et, num_symbols):
    """Advance a batch of branch slots by one symbol each (``hs_syms`` is
    ``[2, npad] int32`` — slot row then symbol row, packed into one host
    upload; duplicate padding slots are fine as long as their symbols
    agree).  Returns (state, stats-per-branch, overflow)."""
    hs = hs_syms[0]
    syms = hs_syms[1]
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    C = state["cons"].shape[1]

    def one(D, e, rmin, er, off, act, clen, sym):
        jnew = clen + 1
        Dn, en, rminn, ern = _col_step(
            D, e, rmin, er, off, act, rlen, reads, jnew, sym, wc, et, E
        )
        ovf = (act & (en >= E)).any()
        stats = _stats_core(
            Dn, en, rminn, ern, off, act, rlen, reads, jnew, num_symbols, E
        )
        fin, fin_ovf = _finalized(en, rminn, act, E)
        return Dn, en, rminn, ern, ovf, stats + (fin, ~fin_ovf)

    Dn, en, rminn, ern, ovfs, stats = jax.vmap(one)(
        state["D"][hs],
        state["e"][hs],
        state["rmin"][hs],
        state["er"][hs],
        state["off"][hs],
        state["act"][hs],
        state["clen"][hs],
        syms,
    )
    overflow = ovfs.any()
    out = dict(state)

    def commit(new, old):
        return jnp.where(overflow, old, new)

    out["D"] = state["D"].at[hs].set(commit(Dn, state["D"][hs]))
    out["e"] = state["e"].at[hs].set(commit(en, state["e"][hs]))
    out["rmin"] = state["rmin"].at[hs].set(commit(rminn, state["rmin"][hs]))
    out["er"] = state["er"].at[hs].set(commit(ern, state["er"][hs]))
    cons_rows = state["cons"][hs]
    clen_rows = state["clen"][hs]
    cons_upd = cons_rows.at[
        jnp.arange(hs.shape[0]), jnp.clip(clen_rows, 0, C - 1)
    ].set(syms)
    out["cons"] = state["cons"].at[hs].set(commit(cons_upd, cons_rows))
    out["clen"] = state["clen"].at[hs].set(commit(clen_rows + 1, clen_rows))
    return out, stats, overflow


@partial(jax.jit, static_argnames=("num_symbols",), donate_argnums=(0,))
def _j_clone_push_batch(state, reads, rlen, rows, wc, et, num_symbols):
    """Fused expansion: clone each ``src`` row into ``dst`` and advance
    the copy by one symbol, in ONE dispatch (``rows`` is ``[3, npad]
    int32`` — src slot, dst slot, symbol; symbol ``-1`` = clone only,
    ``src == dst`` = in-place push).  Replaces the engines' separate
    clone_many + push_many round trips — on the tunneled TPU each
    dispatch costs ~65-90ms, which dwarfs the fused kernel's work.
    Returns (state, per-branch stats incl. bundled fin, overflow);
    commits nothing on overflow (so in-place sources stay pristine for
    the host's grow-and-retry)."""
    srcs = rows[0]
    dsts = rows[1]
    syms = rows[2]
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    C = state["cons"].shape[1]

    def one(D, e, rmin, er, off, act, cons, clen, sym):
        push = sym >= 0
        jnew = clen + 1
        Dn, en, rminn, ern = _col_step(
            D, e, rmin, er, off, act, rlen, reads, jnew, sym, wc, et, E
        )
        sel = lambda new, old: jnp.where(push, new, old)  # noqa: E731
        Dn = sel(Dn, D)
        en = sel(en, e)
        rminn = sel(rminn, rmin)
        ern = sel(ern, er)
        consn = sel(cons.at[jnp.clip(clen, 0, C - 1)].set(sym), cons)
        clenn = sel(clen + 1, clen)
        ovf = push & (act & (en >= E)).any()
        stats = _stats_core(
            Dn, en, rminn, ern, off, act, rlen, reads, clenn, num_symbols, E
        )
        fin, fin_ovf = _finalized(en, rminn, act, E)
        return (
            Dn, en, rminn, ern, off, act, consn, clenn, ovf,
            stats + (fin, ~fin_ovf),
        )

    (Dn, en, rminn, ern, offn, actn, consn, clenn, ovfs, stats) = jax.vmap(
        one
    )(
        state["D"][srcs],
        state["e"][srcs],
        state["rmin"][srcs],
        state["er"][srcs],
        state["off"][srcs],
        state["act"][srcs],
        state["cons"][srcs],
        state["clen"][srcs],
        syms,
    )
    overflow = ovfs.any()
    out = dict(state)

    def commit(new, name):
        return jnp.where(overflow, state[name][dsts], new)

    out["D"] = state["D"].at[dsts].set(commit(Dn, "D"))
    out["e"] = state["e"].at[dsts].set(commit(en, "e"))
    out["rmin"] = state["rmin"].at[dsts].set(commit(rminn, "rmin"))
    out["er"] = state["er"].at[dsts].set(commit(ern, "er"))
    out["off"] = state["off"].at[dsts].set(commit(offn, "off"))
    out["act"] = state["act"].at[dsts].set(commit(actn, "act"))
    out["cons"] = state["cons"].at[dsts].set(commit(consn, "cons"))
    out["clen"] = state["clen"].at[dsts].set(commit(clenn, "clen"))
    return out, stats, overflow


@partial(jax.jit, static_argnames=("num_symbols",))
def _j_stats(state, reads, rlen, h, num_symbols):
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    return _stats_core(
        state["D"][h],
        state["e"][h],
        state["rmin"][h],
        state["er"][h],
        state["off"][h],
        state["act"][h],
        rlen,
        reads,
        state["clen"][h],
        num_symbols,
        E,
    )


@partial(jax.jit, donate_argnums=(0,))
def _j_activate(state, reads, rlen, params, wc, et):
    """Begin tracking one read at consensus offset ``offset``: fresh column
    at ``j == offset``, then catch up to the branch's current length.
    ``params`` is ``[3] int32``: (slot, read_index, offset) — one host
    upload."""
    h = params[0]
    read_index = params[1]
    offset = params[2]
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    clen = state["clen"][h]
    cons = state["cons"][h]

    off1 = jnp.full((1,), offset, jnp.int32)
    act1 = jnp.ones((1,), bool)
    rlen1 = rlen[read_index][None]
    reads1 = reads[read_index][None]
    D1, e1, rmin1, er1 = _init_col(off1, act1, rlen1, E, W)

    def body(j, carry):
        D, e, rmin, er = carry
        return _col_step(
            D, e, rmin, er, off1, act1, rlen1, reads1, j + 1, cons[j], wc, et, E
        )

    D1, e1, rmin1, er1 = lax.fori_loop(offset, clen, body, (D1, e1, rmin1, er1))
    overflow = e1[0] >= E

    out = dict(state)

    def commit(field, new):
        old = state[field][h, read_index]
        return state[field].at[h, read_index].set(
            jnp.where(overflow, old, new)
        )

    out["D"] = commit("D", D1[0])
    out["e"] = commit("e", e1[0])
    out["rmin"] = commit("rmin", rmin1[0])
    out["er"] = commit("er", er1[0])
    out["off"] = commit("off", jnp.where(overflow, state["off"][h, read_index], offset))
    out["act"] = state["act"].at[h, read_index].set(
        jnp.where(overflow, state["act"][h, read_index], True)
    )
    return out, overflow


@jax.jit
def _j_finalize(state, h):
    """Finalized per-read edit distances (reference semantics:
    ``max(e, rmin)`` — escalate only until the wavefront touches the
    baseline end).  Non-mutating."""
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    return _finalized(state["e"][h], state["rmin"][h], state["act"][h], E)



def _nominate_side(occ, split, w, wc, weighted, mc_tab, mc_dyn):
    """Per-side vote fold + nomination decision — THE shared copy for
    the dual run loop and the arena (their dirty/stop decisions must
    stay bit-identical or the two fast paths diverge on the same node).

    The integer mc-table index is only the host's arithmetic when the
    surviving-vote total IS integer (wildcard-tip drops can leave
    fractional totals) — with a dynamic table (``mc_dyn``) those
    decisions bounce to the host.  Returns ``(dirty, sym, counts,
    has_votes, exactable, mc, near_tie)``."""
    counts, has_votes, n_cands, exactable = _dual_votes(
        occ, split, w, wc, weighted
    )
    EPS = VOTE_EPS
    MCN = mc_tab.shape[0]
    n_vote_f = counts.sum()
    n_vote = jnp.round(n_vote_f).astype(jnp.int32)
    int_ok = jnp.abs(n_vote_f - jnp.round(n_vote_f)) < EPS
    tab_bad = mc_dyn & ~int_ok
    exactable = exactable & ~tab_bad
    mc = mc_tab[jnp.clip(n_vote, 0, MCN - 1)]
    mc_f = mc.astype(jnp.float32)
    maxc = jnp.where(has_votes, counts, -1.0).max()
    thr = jnp.minimum(mc_f, maxc)
    passing = has_votes & (counts >= thr)
    npass = passing.sum()
    near_tie = (
        (jnp.abs(maxc - mc_f) < EPS)
        | (has_votes & (jnp.abs(counts - thr) < EPS)).any()
    )
    ambiguous = ~exactable & near_tie
    dirty = ambiguous | (npass != 1) | (n_cands == 0) | tab_bad
    sym = jnp.argmax(jnp.where(passing, counts, -1.0)).astype(jnp.int32)
    return dirty, sym, counts, has_votes, exactable, mc, near_tie


def _run_impl(state, reads, reads_pad, rlen, params, wc, et, num_symbols,
              uniform, a_real, i16, cols, blocks):
    """Device-resident multi-symbol extension: keep appending the unique
    passing candidate while the votes are exactly reproducible host-side
    (one tip symbol per read → integer counts), stopping at any event the
    host search must arbitrate.

    ``uniform`` (static) selects the window-sourcing path: True when the
    host's offset mirror shows every ACTIVE read of the branch at the
    same offset ``off0`` (``params[7]``) — read windows then come from
    one ``dynamic_slice`` of ``reads_pad`` per step instead of per-lane
    gathers (the dominant cost at north-star scale on TPU).

    The run continues only while the node would keep winning pops against
    the best other queued entry ``(other_cost, other_len)`` under the
    host's ``(-cost, len)`` priority — strictly cheaper, or equal cost
    with a strictly longer consensus (full ties pop the earlier-inserted
    queue entry first, so they stop the run) — and while the cost stays
    within ``me_budget`` (the best finalized result so far).

    Stop codes: 1 = votes need host arbitration (non-one-hot, wildcard
    votes, or #passing != 1), 2 = a read reached its baseline end AND
    the record cannot be absorbed (finalized distances out of band, an
    L2 overflow, or the record buffer is full), 3 = node would lose the
    next pop (budget/priority), 4 = step limit, 5 = band overflow (last
    push not committed).

    RECORD ABSORPTION: a reached state no longer stops the run by
    itself.  The host's pop at such a state records a finalized result
    (budget/result-list updates) and then extends normally; the kernel
    does the same — each committed step through a reached state appends
    ``(step, finalized_eds)`` to a bounded record buffer and updates its
    running ``me_budget`` exactly as an accepted record would
    (``fin_total < budget``), and the host replays the buffered records
    afterwards.  The STOPPED state is never buffered: the host re-pops
    it and records it through the normal completion path.

    ``params[8]`` is an optional FORCED first symbol (or -1): the host
    has already nominated this node's unique passing child exactly (the
    device f32 fold was too close to call, or the host simply knows the
    expansion), so step 0 pushes it without vote or pop-priority checks
    — the child exists either way; if it then loses the next pop the
    loop stops and the host re-queues it, bit-identical to the expand
    path but without the separate clone+push dispatches.  Band overflow
    on the forced push returns (steps=0, code=5) uncommitted.

    The returned ``fin_eds``/``fin_ovf`` mirror ``_j_finalize`` at the
    stopped position, so a reached-end stop needs no follow-up finalize
    dispatch (``fin_ovf`` falls back to the real finalize after band
    growth).

    This is the TPU answer to the reference's symbol-at-a-time host loop:
    for clean stretches the consensus grows entirely on device, with one
    host round-trip per *event* instead of per base.

    ``params`` is ``[10] int32`` — (slot, me_budget, other_cost,
    other_len, min_count, l2, max_steps, off0, first_sym,
    allow_records) — packed into a single host upload.
    ``allow_records`` is 0 when the host's record condition cannot hold
    mid-run (early termination with a not-yet-activated read: the
    kernel's conservative reached fold counts inactive lanes as done,
    but the host's require-all check never would) — absorption is then
    disabled and reached states stop with code 2 as before.  Returns
    ``(state, steps, code, stats, cons, fin_eds, fin_ovf, rec_count,
    rec_steps, rec_fins)``.

    ``a_real`` (static) is the engine's real dense alphabet size: the
    per-step vote fold runs at that width instead of the padded
    ``num_symbols`` shape (only the FINAL host-visible stats snapshot is
    padded back).  ``i16`` (static, see :func:`_xla_i16_ok`) narrows the
    band state to int16 for the whole loop — converted once at loop
    entry/exit, never per step — halving the hot ``[R, W]`` traffic.
    Both are value-exact: results are bit-identical to the wide path.

    ``cols`` (static, the ``WAFFLE_RUN_COLS`` knob) is the SPECULATIVE
    BLOCK WIDTH: each ``while_loop`` iteration runs ``cols`` copies of
    the single-column sub-step back to back, re-verifying the vote after
    every column.  Sub-column 0 is exactly the K=1 body; sub-columns
    1..K-1 carry the running stop code and mask their commit on it, so a
    stop anywhere in the block freezes the remaining columns into
    no-ops — the committed prefix, the sticking stop code, the record
    buffer, and the band state are bit-identical to stepping one column
    at a time (rollback is free: uncommitted column state is simply
    never selected).  The win is amortization: loop-condition
    evaluation, carry rotation, and the per-iteration launch overhead of
    the body's many tiny fused kernels are paid once per K columns
    instead of once per column.  ``cols=1`` compiles to the
    pre-speculation kernel.  The extra return value ``iters`` counts
    loop iterations so the host can report speculated columns
    (``iters * cols``) vs committed (``steps``).

    ``blocks`` (static, the MEGASTEP composition M — see
    :func:`_j_run_mega`) nests the K-column block inside a
    ``lax.fori_loop`` running M blocks per ``while_loop`` iteration.
    The nested body is ALL-masked sub-columns: a masked sub-column with
    a running stop code of 0 is behaviorally identical to the unmasked
    one, and the while condition guarantees code 0 at iteration entry,
    so the composition is bit-identical to ``blocks=1`` — while the
    fori body is traced ONCE, keeping compile cost at the K-column
    body instead of doubling per unrolled octave like raising K does.
    ``iters`` then counts M*K-column iterations (speculated columns =
    ``iters * cols * blocks``).
    """
    h = params[0]
    me_budget = params[1]
    other_cost = params[2]
    other_len = params[3]
    min_count = params[4]
    l2 = params[5].astype(bool)
    max_steps = params[6]
    off0 = params[7]
    allow_records = params[9].astype(bool)
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    C = state["cons"].shape[1]
    off = state["off"][h]
    act = state["act"][h]

    av = num_symbols if a_real is None else min(a_real, num_symbols)

    def stats_at(D, e, rmin, er, clen, pad=True):
        if uniform:
            return _stats_core_u(
                D, e, rmin, er, off, act, rlen, reads_pad, clen, off0,
                num_symbols, E, a_real=a_real, pad=pad,
            )
        return _stats_core(
            D, e, rmin, er, off, act, rlen, reads, clen, num_symbols, E,
            a_real=a_real, pad=pad,
        )

    def col_at(D, e, rmin, er, jnew, sym):
        if uniform:
            return _col_step_u(
                D, e, rmin, er, off, act, rlen, reads_pad, jnew, off0, sym,
                wc, et, E,
            )
        return _col_step(
            D, e, rmin, er, off, act, rlen, reads, jnew, sym, wc, et, E
        )

    def substep(carry, masked):
        (D, e, rmin, er, cons, clen, steps, budget,
         rec_count, rec_steps, rec_fins, _code) = carry
        # note: the stats snapshot at ``clen`` and the push to ``clen + 1``
        # read the SAME [R, W] read window (stats index ``clen - off - E + t``
        # equals the push's ``i_new - 1``); XLA CSEs the duplicate fetch, so
        # the two helper calls cost one gather/slice per column
        eds, occ, split, reached = stats_at(D, e, rmin, er, clen, pad=False)
        # finalized snapshot of THIS (pre-push) state: the host records it
        # at this pop; absorbing the record needs it in-band.  Inlined
        # ``_finalized`` so its folds ride the packed reductions below.
        fin_j = jnp.where(act, jnp.minimum(jnp.maximum(e, rmin), INF), 0)

        # ---- packed per-read folds: the loop body's eight [R]-sized
        # reductions collapse into ONE fused sum and ONE fused max pass
        # (each separate tiny reduction costs ~1-3us of launch latency per
        # step, which dominated the measured per-step time).
        # int32-safe cost totals: with L2 and huge per-read distances the
        # squared sum could wrap, so treat that regime as a host event.
        costs = jnp.where(l2, eds * eds, eds)
        fin_costs = jnp.where(l2, fin_j * fin_j, fin_j)
        sums = jnp.stack([costs, fin_costs]).sum(axis=1)
        total, fin_total = sums[0], sums[1]

        nonexact = jnp.where(split > 0, (split & (split - 1)) != 0, False)
        maxes = jnp.stack([
            eds,                         # L2 overflow probe (masked)
            fin_j,                       # fin band-overflow + L2 probe
            nonexact.astype(jnp.int32),  # vote exactness fold
            (act & ~reached).astype(jnp.int32),  # early-term completion
            reached.astype(jnp.int32),   # any-reached fold
        ]).max(axis=1)
        cost_overflow = l2 & (maxes[0] > 2048)
        fin_ovf_j = maxes[1] >= E
        fin_cost_ovf = l2 & (maxes[1] > 2048)
        all_exact = maxes[2] == 0

        # fractional votes, mirroring the host's candidate nomination: each
        # read splits one unit across its tip symbols.  The host sums in
        # f64 read order; device f32 reductions agree on every >=-decision
        # whenever the comparison margin exceeds EPS, so we continue only
        # on clear margins (exact when all reads are single-tip).
        EPS = VOTE_EPS
        frac = jnp.where(
            split[:, None] > 0,
            occ.astype(jnp.float32)
            / jnp.maximum(split, 1)[:, None].astype(jnp.float32),
            0.0,
        )
        vsums = jnp.stack(
            [frac, (occ > 0).astype(jnp.float32)]
        ).sum(axis=1)  # [2, A]
        counts = vsums[0]  # [A]
        has_votes = vsums[1] > 0
        n_cands = has_votes.sum()
        # wildcard removal (host drops it whenever another candidate exists)
        wc_col = jnp.maximum(wc, 0)
        drop_wc = (wc >= 0) & (n_cands > 1)
        has_votes = jnp.where(
            drop_wc, has_votes.at[wc_col].set(False), has_votes
        )
        counts = jnp.where(drop_wc, counts.at[wc_col].set(0.0), counts)

        maxc = jnp.where(has_votes, counts, -1.0).max()
        min_count_f = min_count.astype(jnp.float32)
        thr = jnp.minimum(min_count_f, maxc)
        passing = has_votes & (counts >= thr)
        npass = passing.sum()

        # exactness (maxes[2] fold above): dyadic tip splits make the f32
        # fold bit-equal to the host f64 fold (see _dual_votes); only
        # 3-tip reads break it
        near_tie = (
            (jnp.abs(maxc - min_count_f) < EPS)
            | (has_votes & (jnp.abs(counts - thr) < EPS)).any()
        )
        ambiguous = ~all_exact & near_tie
        dirty = ambiguous | (npass != 1) | (n_cands == 0) | cost_overflow

        # early-termination runs freeze a reached read rather than ending
        # the search, so only stop when the node as a whole may be
        # complete.  CONSERVATIVE fold: inactive lanes count as done, so
        # the run stops at (or before) every host-recordable state — the
        # kernel cannot tell a padding/non-member lane (must not block)
        # from a real inactive read (blocks recording host-side); the
        # host re-checks the real condition at the stop pop.
        reached_here = jnp.where(et, maxes[3] == 0, maxes[4] > 0)
        rec_blocked = (
            ~allow_records
            | fin_ovf_j
            | fin_cost_ovf
            | (rec_count >= REC_CAP)
        )

        wins_pop = (total < other_cost) | (
            (total == other_cost) & (clen > other_len)
        )
        code = jnp.where(
            (total > budget) | ~wins_pop,
            3,
            jnp.where(
                reached_here & rec_blocked,
                2,
                jnp.where(
                    dirty,
                    1,
                    jnp.where(steps >= max_steps, 4, 0),
                ),
            ),
        )

        sym = jnp.argmax(jnp.where(passing, counts, -1.0)).astype(jnp.int32)
        cons2 = cons.at[jnp.clip(clen, 0, C - 1)].set(sym)
        clen2 = clen + 1
        D2, e2, rmin2, er2 = col_at(D, e, rmin, er, clen2, sym)
        ovf = (act & (e2 >= E)).any()
        commit = (code == 0) & ~ovf
        code = jnp.where(code != 0, code, jnp.where(ovf, 5, 0))
        if masked:
            # speculative sub-column: a stop earlier in the block turns
            # this column into a no-op — nothing commits and the FIRST
            # stop code sticks, so the block is bit-identical to K=1
            commit = commit & (_code == 0)
            code = jnp.where(_code != 0, _code, code)
        # record of the popped state, buffered only when the step commits
        # (a stopped state is recorded by the host's own completion path)
        do_rec = commit & reached_here
        ri = jnp.clip(rec_count, 0, REC_CAP - 1)
        # row-scatter (select inside the updated row) instead of a
        # whole-buffer select: the [REC_CAP, R] plane stays out of the
        # per-step write set on non-record steps
        rec_steps = rec_steps.at[ri].set(
            jnp.where(do_rec, steps, rec_steps[ri])
        )
        rec_fins = rec_fins.at[ri].set(jnp.where(do_rec, fin_j, rec_fins[ri]))
        rec_count = rec_count + do_rec.astype(jnp.int32)
        # accepted records shrink the running budget exactly as the host
        # does (strictly-better totals only; appends don't change it)
        budget = jnp.where(
            do_rec & (fin_total < budget), fin_total, budget
        )
        D = jnp.where(commit, D2, D)
        e = jnp.where(commit, e2, e)
        rmin = jnp.where(commit, rmin2, rmin)
        er = jnp.where(commit, er2, er)
        cons = jnp.where(commit, cons2, cons)
        clen = jnp.where(commit, clen2, clen)
        steps = steps + commit.astype(steps.dtype)
        return (D, e, rmin, er, cons, clen, steps, budget,
                rec_count, rec_steps, rec_fins, code)

    def body(carry):
        if blocks == 1:
            # speculative K-column block: sub-column 0 is the exact K=1
            # body (the loop condition guarantees code==0 here); the
            # rest verify the running code before committing
            sub = substep(carry[:-1], masked=False)
            for _ in range(cols - 1):
                sub = substep(sub, masked=True)
        else:
            # megastep: M blocks of K ALL-masked sub-columns through one
            # traced fori body — masked with running code 0 is identical
            # to unmasked (the while condition guarantees code 0 here),
            # so this is bit-identical to blocks=1 at the compile cost
            # of a single K-column block
            def block(_, c):
                for _ in range(cols):
                    c = substep(c, masked=True)
                return c

            sub = lax.fori_loop(0, blocks, block, carry[:-1])
        return sub + (carry[-1] + 1,)

    D0 = state["D"][h]
    if i16:
        # narrow ONCE for the whole loop: finite cells are exact under
        # the _xla_i16_ok bound, INF clamps to the DINF16 sentinel
        D0 = jnp.minimum(D0, DINF16).astype(jnp.int16)
    e0 = state["e"][h]
    rmin0 = state["rmin"][h]
    er0 = state["er"][h]
    cons0 = state["cons"][h]
    clen0 = state["clen"][h]

    # forced first push (host-nominated child), vote/priority checks
    # bypassed; only band overflow can refuse it.  Under lax.cond the
    # unforced common case skips the extra column step entirely.
    first_sym = params[8]

    def forced(_):
        Df, ef, rminf, erf = col_at(D0, e0, rmin0, er0, clen0 + 1, first_sym)
        fovf = (act & (ef >= E)).any()
        sel0 = lambda new, old: jnp.where(~fovf, new, old)  # noqa: E731
        return (
            sel0(Df, D0),
            sel0(ef, e0),
            sel0(rminf, rmin0),
            sel0(erf, er0),
            sel0(cons0.at[jnp.clip(clen0, 0, C - 1)].set(first_sym), cons0),
            sel0(clen0 + 1, clen0),
            (~fovf).astype(jnp.int32),
            jnp.where(fovf, 5, 0).astype(jnp.int32),
        )

    def unforced(_):
        return (D0, e0, rmin0, er0, cons0, clen0, jnp.int32(0), jnp.int32(0))

    (D1, e1, rmin1, er1, cons1, clen1, steps0, code0) = lax.cond(
        first_sym >= 0, forced, unforced, None
    )
    R = rlen.shape[0]
    init = (
        D1, e1, rmin1, er1, cons1, clen1, steps0,
        me_budget,
        jnp.int32(0),
        jnp.zeros((REC_CAP,), jnp.int32),
        jnp.zeros((REC_CAP, R), jnp.int32),
        code0,
        jnp.int32(0),
    )
    (D, e, rmin, er, cons, clen, steps, _budget,
     rec_count, rec_steps, rec_fins, code, iters) = lax.while_loop(
        lambda c: c[11] == 0, body, init
    )
    if i16:  # widen back, restoring the INF sentinel
        Dw = D.astype(jnp.int32)
        D = jnp.where(Dw >= DINF16, INF, Dw)
    stats = stats_at(D, e, rmin, er, clen)
    fin_eds, fin_ovf = _finalized(e, rmin, act, E)
    out = dict(state)
    out["D"] = state["D"].at[h].set(D)
    out["e"] = state["e"].at[h].set(e)
    out["rmin"] = state["rmin"].at[h].set(rmin)
    out["er"] = state["er"].at[h].set(er)
    out["cons"] = state["cons"].at[h].set(cons)
    out["clen"] = state["clen"].at[h].set(clen)
    return (
        out, steps, code, stats, cons, fin_eds, fin_ovf,
        rec_count, rec_steps, rec_fins, iters,
    )


@partial(
    jax.jit,
    static_argnames=("num_symbols", "uniform", "a_real", "i16", "cols"),
    donate_argnums=(0,),
)
def _j_run(state, reads, reads_pad, rlen, params, wc, et, num_symbols,
           uniform, a_real=None, i16=False, cols=1):
    """Plain run entry: the K-column speculative loop (``blocks=1``).
    See :func:`_run_impl` for the full contract."""
    return _run_impl(
        state, reads, reads_pad, rlen, params, wc, et, num_symbols,
        uniform, a_real, i16, cols, 1,
    )


@partial(
    jax.jit,
    static_argnames=(
        "num_symbols", "uniform", "a_real", "i16", "cols", "blocks"
    ),
    donate_argnums=(0,),
)
def _j_run_mega(state, reads, reads_pad, rlen, params, wc, et, num_symbols,
                uniform, a_real=None, i16=False, cols=1,
                blocks=_MEGA_BLOCKS_DEFAULT):
    """MEGASTEP run entry: the outer ``while_loop`` advances the branch
    ``blocks`` (M) blocks of ``cols`` (K) columns per iteration, folding
    tip votes at the real alphabet width and committing the winning
    symbol on device whenever it is unambiguous — the host sees the run
    only at genuine decision points (fork/near-tie arbitration, reached
    end, losing the next pop, band growth) or when the
    ``WAFFLE_MEGA_SYMS`` dispatch budget caps it.  Bit-identical to
    ``_j_run`` by the masked-block argument in :func:`_run_impl`; the
    stop-code/record/forced-first-symbol contracts are unchanged."""
    return _run_impl(
        state, reads, reads_pad, rlen, params, wc, et, num_symbols,
        uniform, a_real, i16, cols, blocks,
    )


def _dual_votes(occ, split, w, wc, weighted):
    """Per-side fractional vote fold for the dual run loop, mirroring the
    host's ``candidates_from_stats`` with per-read weights: each voting
    read (weight > 0, any tips) splits ``w`` across its tip symbols; the
    wildcard column is dropped whenever another candidate exists.

    Returns ``(counts[A] f32, has_votes[A], n_cands, exactable)`` where
    ``exactable`` means every voting read's tip split is a power of two
    (``1/split`` then dyadic, so the unweighted f32 sums are EXACT and
    bit-equal to the host's f64 fold — equality decisions included;
    only 3-tip reads break this)."""
    voting = (w > 0) & (split > 0)
    voters = (occ > 0) & voting[:, None]
    frac = jnp.where(
        split[:, None] > 0,
        occ.astype(jnp.float32)
        / jnp.maximum(split, 1)[:, None].astype(jnp.float32),
        0.0,
    ) * w[:, None]
    counts = jnp.where(voters, frac, 0.0).sum(axis=0)
    has_votes = voters.any(axis=0)
    n_cands = has_votes.sum()
    wc_col = jnp.maximum(wc, 0)
    drop_wc = (wc >= 0) & (n_cands > 1)
    has_votes = jnp.where(drop_wc, has_votes.at[wc_col].set(False), has_votes)
    counts = jnp.where(drop_wc, counts.at[wc_col].set(0.0), counts)
    n_cands = has_votes.sum()
    dyadic = (split & (split - 1)) == 0
    exactable = jnp.where(voting, dyadic, True).all() & ~weighted
    return counts, has_votes, n_cands, exactable


@partial(
    jax.jit,
    static_argnames=(
        "num_symbols", "uniform", "a_real", "i16", "cols", "blocks"
    ),
    donate_argnums=(0,),
)
def _j_run_dual(state, reads, reads_pad, rlen, params, mc_tab, imb_tab,
                wc, et, num_symbols, uniform, a_real=None, i16=False,
                cols=1, blocks=1):
    """Device-resident extension of a *dual* node: both branches advance
    one symbol per iteration while each side's nomination is unambiguous,
    with divergence pruning (``dual_max_ed_delta``) applied on device
    exactly as the host would (integer compares on post-push distances).

    ``mc_tab`` (``[R+1] int32``) and ``imb_tab`` (``[T] int32``) carry
    the host's exact dynamic-min-count arithmetic for ``min_af != 0``
    (``/root/reference/src/dual_consensus.rs:326-336,497-513``):
    ``mc_tab[n]`` is ``max(min_count, ceil(min_af * n))`` for a side
    with ``n`` voting reads (the per-side nomination threshold), and
    ``imb_tab[L]`` is the host's ``active_min_count[L]`` (activation
    points are known up front, so the whole table is precomputable) for
    the imbalance check at node length ``L``.  With ``min_af == 0`` both
    tables are constant ``min_count`` and the behavior is unchanged.

    ``uniform`` (static) selects slice- vs gather-sourced read windows
    (see ``_j_run``); ``params[11]``/``params[12]`` carry each side's
    shared active-read offset when uniform.  ``params[13]``/``params[14]``
    are the sides' lock flags: a locked side is frozen — no votes, no
    column step, length fixed — while its tracked reads keep
    contributing their (frozen) distances to the node cost, divergence
    pruning, and the reached fold; its forced do-not-extend option is
    the host's only choice for that side, so it never triggers
    arbitration by itself.

    Preconditions (enforced by the engine): at most one side locked
    (with the unlocked side at least as long), and ``min_af == 0`` so
    the vote thresholds are static.

    Stop codes: 1 = host arbitration (ambiguous votes, != 1 passing
    symbol on a side, a side ran out of candidates, or a side finished),
    2 = some read reached its baseline end, 3 = node would lose the next
    pop (budget/priority — see ``_j_run``), 4 = step limit, 5 = band
    overflow (last step not committed), 6 = committed step made the node
    imbalanced (host pop discards it).

    This is the dual twin of ``_j_run`` and the answer to the reference's
    quadratic dual extension loop
    (``/root/reference/src/dual_consensus.rs:606-734``): clean dual
    stretches cost one host round-trip per *event*, not ~5 dispatches per
    appended base.

    ``params`` is ``[18] int32`` — (slot_a, slot_b, me_budget, other_cost,
    other_len, min_count, dual_max_ed_delta, imb_min, l2, weighted,
    max_steps, off0a, off0b, lock1, lock2, allow_records, rec_min,
    mc_dyn) —
    packed into a single host upload (``allow_records``: see ``_j_run``;
    here the host condition is every read active on at least one side
    under early termination).  ``rec_min`` is the host's
    ``full_min_count`` (``max(min_count, ceil(min_af * n))``): the
    record-acceptance imbalance threshold, which only shrinks the
    running budget when the host would also have accepted the record.

    ``cols`` (static): speculative block width — K single-column
    sub-steps per ``while_loop`` iteration with commit masking on the
    running stop code, bit-identical to K=1 (see ``_j_run``).  The
    extra return value ``iters`` counts loop iterations.

    ``blocks`` (static): megastep composition M — ``blocks > 1`` runs M
    blocks of K ALL-masked sub-columns through one traced ``fori_loop``
    body per iteration, bit-identical to ``blocks=1`` (see
    :func:`_run_impl`); ``run_extend_dual`` selects it under
    ``WAFFLE_MEGASTEP``.
    """
    ha = params[0]
    hb = params[1]
    me_budget = params[2]
    other_cost = params[3]
    other_len = params[4]
    min_count = params[5]
    delta = params[6]
    # params[7] (imb_min) is consumed host-side only: the wrapper builds
    # the fallback imb_tab from it; every kernel imbalance check reads
    # the table
    l2 = params[8].astype(bool)
    weighted = params[9].astype(bool)
    max_steps = params[10]
    off0a = params[11]
    off0b = params[12]
    lock_a = params[13].astype(bool)
    lock_b = params[14].astype(bool)
    allow_records = params[15].astype(bool)
    rec_min = params[16]
    mc_dyn = params[17].astype(bool)
    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    C = state["cons"].shape[1]
    offa = state["off"][ha]
    offb = state["off"][hb]
    IMBN = imb_tab.shape[0]

    def stats_at(D, e, rmin, er, off, act, clen, off0, pad=True):
        if uniform:
            return _stats_core_u(
                D, e, rmin, er, off, act, rlen, reads_pad, clen, off0,
                num_symbols, E, a_real=a_real, pad=pad,
            )
        return _stats_core(
            D, e, rmin, er, off, act, rlen, reads, clen, num_symbols, E,
            a_real=a_real, pad=pad,
        )

    def col_at(D, e, rmin, er, off, act, jnew, off0, sym):
        if uniform:
            return _col_step_u(
                D, e, rmin, er, off, act, rlen, reads_pad, jnew, off0, sym,
                wc, et, E,
            )
        return _col_step(
            D, e, rmin, er, off, act, rlen, reads, jnew, sym, wc, et, E
        )

    def substep(carry, masked):
        (Da, ea, rmina, era, acta, consa, clena,
         Db, eb, rminb, erb, actb, consb, clenb, steps, budget,
         rec_count, rec_steps, rec_f1, rec_f2, rec_a1, rec_a2,
         _code) = carry

        edsa, occa, splita, reacheda = stats_at(
            Da, ea, rmina, era, offa, acta, clena, off0a, pad=False
        )
        edsb, occb, splitb, reachedb = stats_at(
            Db, eb, rminb, erb, offb, actb, clenb, off0b, pad=False
        )

        # total node cost = per read, best over its tracked sides
        BIG = jnp.int32(1 << 28)
        ca = jnp.where(l2, edsa * edsa, edsa)
        cb = jnp.where(l2, edsb * edsb, edsb)
        best = jnp.minimum(
            jnp.where(acta, ca, BIG), jnp.where(actb, cb, BIG)
        )
        total = jnp.where(acta | actb, best, 0).sum()
        cost_overflow = l2 & (
            jnp.maximum(
                jnp.where(acta, edsa, 0).max(), jnp.where(actb, edsb, 0).max()
            )
            > 2048
        )

        # per-read vote weights: ed-scaled when weighted_by_ed (reference
        # get_ed_weights, dual_consensus.rs:1299-1336), otherwise FULL
        # weight for every read tracked on that side — the reference's
        # unweighted nomination uses vec![1.0; n], NOT the 1.0/0.5/0.0
        # comparison lattice (dual_consensus.rs:1257-1262)
        both = acta & actb
        c1f = jnp.maximum(edsa.astype(jnp.float32), 0.5)
        c2f = jnp.maximum(edsb.astype(jnp.float32), 0.5)
        denom = c1f + c2f
        wa_soft = jnp.where(both, c2f / denom, jnp.where(acta, 1.0, 0.0))
        wb_soft = jnp.where(both, c1f / denom, jnp.where(actb, 1.0, 0.0))
        wa = jnp.where(weighted, wa_soft, jnp.where(acta, 1.0, 0.0))
        wb = jnp.where(weighted, wb_soft, jnp.where(actb, 1.0, 0.0))

        def side(occ, split, w):
            dirty, sym = _nominate_side(
                occ, split, w, wc, weighted, mc_tab, mc_dyn
            )[:2]
            return dirty, sym

        dirty_a, sym_a = side(occa, splita, wa)
        dirty_b, sym_b = side(occb, splitb, wb)
        # a locked side never arbitrates: its do-not-extend option is
        # forced, so its votes and finished flag are moot
        dirty_a = dirty_a & ~lock_a
        dirty_b = dirty_b & ~lock_b

        # a side counting as finished adds a do-not-extend option to the
        # host's cross product — host arbitration either way
        reached_read = (acta & reacheda) | (actb & reachedb)
        # per-side "finished" mirrors reached_consensus_end: under early
        # termination an INACTIVE read counts as finished (require_all
        # default), unlike the whole-node record condition below
        fin_a = jnp.where(
            et, (reacheda | ~acta).all(), (acta & reacheda).any()
        )
        fin_b = jnp.where(
            et, (reachedb | ~actb).all(), (actb & reachedb).any()
        )
        # CONSERVATIVE completion fold (cf. _j_run): lanes inactive on
        # BOTH sides count as done so the run stops at or before every
        # host-recordable state — padding/non-member lanes must not block
        # and are indistinguishable from real never-activated reads here;
        # the host re-checks the real condition at the stop pop.
        # (Previously padding lanes blocked the fold outright, so et dual
        # runs never saw code 2 and could commit past recordable states.)
        reached_stop = jnp.where(
            et, (reached_read | (~acta & ~actb)).all(), reached_read.any()
        )
        cur_len = jnp.maximum(clena, clenb)
        wins_pop = (total < other_cost) | (
            (total == other_cost) & (cur_len > other_len)
        )

        # record eval of THIS (pre-push) state, mirroring _finalize: per
        # read, the better finalized side (ties side 1), acceptance
        # gated by the finalized-assignment imbalance re-check
        fin1_j, fo1 = _finalized(ea, rmina, acta, E)
        fin2_j, fo2 = _finalized(eb, rminb, actb, E)
        fc1 = jnp.where(l2, fin1_j * fin1_j, fin1_j)
        fc2 = jnp.where(l2, fin2_j * fin2_j, fin2_j)
        side0 = acta & (~actb | (fc1 <= fc2))
        any_act = acta | actb
        fin_total = jnp.where(any_act, jnp.where(side0, fc1, fc2), 0).sum()
        count0 = (side0 & any_act).sum()
        count1 = any_act.sum() - count0
        rec_imbalanced = (count0 < rec_min) | (count1 < rec_min)
        fin_cost_ovf = l2 & (
            jnp.maximum(
                jnp.where(acta, fin1_j, 0).max(),
                jnp.where(actb, fin2_j, 0).max(),
            )
            > 2048
        )
        rec_blocked = (
            ~allow_records | fo1 | fo2 | fin_cost_ovf | (rec_count >= REC_CAP)
        )

        code = jnp.where(
            (total > budget) | ~wins_pop,
            3,
            jnp.where(
                reached_stop & rec_blocked,
                2,
                jnp.where(
                    dirty_a
                    | dirty_b
                    | (fin_a & ~lock_a)
                    | (fin_b & ~lock_b)
                    | cost_overflow,
                    1,
                    jnp.where(steps >= max_steps, 4, 0),
                ),
            ),
        )

        consa2 = consa.at[jnp.clip(clena, 0, C - 1)].set(sym_a)
        consb2 = consb.at[jnp.clip(clenb, 0, C - 1)].set(sym_b)
        Da2, ea2, rmina2, era2 = col_at(
            Da, ea, rmina, era, offa, acta, clena + 1, off0a, sym_a
        )
        Db2, eb2, rminb2, erb2 = col_at(
            Db, eb, rminb, erb, offb, actb, clenb + 1, off0b, sym_b
        )
        # locked sides are frozen: discard their column step entirely
        frz = lambda lock, new, old: jnp.where(lock, old, new)  # noqa: E731
        Da2 = frz(lock_a, Da2, Da)
        ea2 = frz(lock_a, ea2, ea)
        rmina2 = frz(lock_a, rmina2, rmina)
        era2 = frz(lock_a, era2, era)
        consa2 = frz(lock_a, consa2, consa)
        Db2 = frz(lock_b, Db2, Db)
        eb2 = frz(lock_b, eb2, eb)
        rminb2 = frz(lock_b, rminb2, rminb)
        erb2 = frz(lock_b, erb2, erb)
        consb2 = frz(lock_b, consb2, consb)
        ovf = ((acta & (ea2 >= E)) | (actb & (eb2 >= E))).any()

        # divergence pruning on post-push distances (host order:
        # push both sides, then prune per read)
        both2 = acta & actb
        acta2 = acta & ~(both2 & (eb2 + delta < ea2))
        actb2 = actb & ~(both2 & (ea2 + delta < eb2))
        # the next pop's imbalance check runs at the committed length
        imb_v = imb_tab[jnp.clip(cur_len + 1, 0, IMBN - 1)]
        imb = (acta2.sum() < imb_v) | (actb2.sum() < imb_v)

        commit = (code == 0) & ~ovf
        code = jnp.where(
            code != 0,
            code,
            jnp.where(ovf, 5, jnp.where(imb, 6, 0)),
        )
        if masked:
            # speculative sub-column (see _j_run): a stop earlier in the
            # block freezes this column and the first stop code sticks
            commit = commit & (_code == 0)
            code = jnp.where(_code != 0, _code, code)
        # buffer the popped state's record on commit (the stopped state
        # is recorded by the host's own completion path), and shrink the
        # running budget exactly as an accepted record would
        do_rec = commit & reached_stop
        ri = jnp.clip(rec_count, 0, REC_CAP - 1)
        # row-scatter (select inside the updated row): keeps the five
        # [REC_CAP, R] planes out of the per-step write set
        rsel = lambda buf, new: buf.at[ri].set(  # noqa: E731
            jnp.where(do_rec, new, buf[ri])
        )
        rec_steps = rsel(rec_steps, steps)
        rec_f1 = rsel(rec_f1, fin1_j)
        rec_f2 = rsel(rec_f2, fin2_j)
        rec_a1 = rsel(rec_a1, acta)
        rec_a2 = rsel(rec_a2, actb)
        rec_count = rec_count + do_rec.astype(jnp.int32)
        budget = jnp.where(
            do_rec & ~rec_imbalanced & (fin_total < budget),
            fin_total,
            budget,
        )
        sel = lambda c, new, old: jnp.where(c, new, old)  # noqa: E731
        Da = sel(commit, Da2, Da)
        ea = sel(commit, ea2, ea)
        rmina = sel(commit, rmina2, rmina)
        era = sel(commit, era2, era)
        acta = sel(commit, acta2, acta)
        consa = sel(commit, consa2, consa)
        clena = sel(commit & ~lock_a, clena + 1, clena)
        Db = sel(commit, Db2, Db)
        eb = sel(commit, eb2, eb)
        rminb = sel(commit, rminb2, rminb)
        erb = sel(commit, erb2, erb)
        actb = sel(commit, actb2, actb)
        consb = sel(commit, consb2, consb)
        clenb = sel(commit & ~lock_b, clenb + 1, clenb)
        steps = steps + commit.astype(steps.dtype)
        return (Da, ea, rmina, era, acta, consa, clena,
                Db, eb, rminb, erb, actb, consb, clenb, steps, budget,
                rec_count, rec_steps, rec_f1, rec_f2, rec_a1, rec_a2,
                code)

    def body(carry):
        if blocks == 1:
            # speculative K-column block (see _j_run)
            sub = substep(carry[:-1], masked=False)
            for _ in range(cols - 1):
                sub = substep(sub, masked=True)
        else:
            # megastep composition (see _run_impl): M blocks of K
            # all-masked sub-columns, bit-identical to blocks=1
            def block(_, c):
                for _ in range(cols):
                    c = substep(c, masked=True)
                return c

            sub = lax.fori_loop(0, blocks, block, carry[:-1])
        return sub + (carry[-1] + 1,)

    R = rlen.shape[0]
    Da0 = state["D"][ha]
    Db0 = state["D"][hb]
    if i16:  # narrow once for the whole loop (see _j_run)
        Da0 = jnp.minimum(Da0, DINF16).astype(jnp.int16)
        Db0 = jnp.minimum(Db0, DINF16).astype(jnp.int16)
    init = (
        Da0, state["e"][ha], state["rmin"][ha], state["er"][ha],
        state["act"][ha], state["cons"][ha], state["clen"][ha],
        Db0, state["e"][hb], state["rmin"][hb], state["er"][hb],
        state["act"][hb], state["cons"][hb], state["clen"][hb],
        jnp.int32(0), me_budget,
        jnp.int32(0),
        jnp.zeros((REC_CAP,), jnp.int32),
        jnp.zeros((REC_CAP, R), jnp.int32),
        jnp.zeros((REC_CAP, R), jnp.int32),
        jnp.zeros((REC_CAP, R), bool),
        jnp.zeros((REC_CAP, R), bool),
        jnp.int32(0),
        jnp.int32(0),
    )
    (Da, ea, rmina, era, acta, consa, clena,
     Db, eb, rminb, erb, actb, consb, clenb, steps, _budget,
     rec_count, rec_steps, rec_f1, rec_f2, rec_a1, rec_a2,
     code, iters) = lax.while_loop(
        lambda c: c[22] == 0, body, init
    )
    if i16:  # widen back, restoring the INF sentinel
        Daw = Da.astype(jnp.int32)
        Da = jnp.where(Daw >= DINF16, INF, Daw)
        Dbw = Db.astype(jnp.int32)
        Db = jnp.where(Dbw >= DINF16, INF, Dbw)
    stats_a = stats_at(Da, ea, rmina, era, offa, acta, clena, off0a)
    stats_b = stats_at(Db, eb, rminb, erb, offb, actb, clenb, off0b)
    out = dict(state)
    out["D"] = state["D"].at[ha].set(Da).at[hb].set(Db)
    out["e"] = state["e"].at[ha].set(ea).at[hb].set(eb)
    out["rmin"] = state["rmin"].at[ha].set(rmina).at[hb].set(rminb)
    out["er"] = state["er"].at[ha].set(era).at[hb].set(erb)
    out["act"] = state["act"].at[ha].set(acta).at[hb].set(actb)
    out["cons"] = state["cons"].at[ha].set(consa).at[hb].set(consb)
    out["clen"] = state["clen"].at[ha].set(clena).at[hb].set(clenb)
    return (
        out, steps, code, stats_a, stats_b, acta, actb, consa, consb,
        rec_count, rec_steps, rec_f1, rec_f2, rec_a1, rec_a2, iters,
    )


#: creation budget of one arena call: total records, and the per-event
#: child cap (a split event with more children than this stops for host
#: expansion — the tail regime where dual cross products explode)
CRE_CAP = 64
CRE_PER_EVENT = 8


@partial(
    jax.jit,
    static_argnames=(
        "num_symbols", "max_steps", "K", "uniform", "a_real", "cols"
    ),
    donate_argnums=(0,),
)
def _j_arena(
    state, reads, reads_pad, rlen, params, slots, kinds0, seqv0, off0s0,
    tr_scalars, lc0, pc0, mc_tab, imb_tab, wc, et, num_symbols, max_steps,
    K, uniform, a_real=None, cols=1,
):
    """K-node pop ARENA: resolve the pop competition among the K best
    runnable queue entries entirely on device.

    Measured motivation: >99% of ``_j_run``/``_j_run_dual`` stops are
    "would lose the next pop" — a handful of live chains at near-equal
    cost leapfrog, costing one full host round-trip per few committed
    symbols each.  The arena simulates the host's EXACT pop loop for the
    group: priority comparison (cost, then length, then insertion
    order), per-kind tracker bookkeeping (threshold constriction,
    per-length capacity, queue totals — ``utils/pqueue.py`` semantics),
    me-budget/threshold/capacity/imbalance discard *detection*, per-node
    candidate nomination, and committed extensions.  It stops BEFORE any
    pop the host must arbitrate (ambiguous votes, reached end, any
    discard condition, a rest-of-queue entry winning, band overflow), so
    the host never replays a decision — it re-derives it naturally at
    the next real pop.  The host replays the committed pop history onto
    the real trackers (``DualConsensusDWFA._arena_attempt``).

    Layout: node n in {0..K-1} owns side rows 2n and 2n+1 of every
    per-side carry; single-kind and dead nodes back row(s) with DISTINCT
    scratch slots (content is garbage and overwritten — repeated slots
    would make the final scatter write conflicting rows).  Node 0 is the
    engine's in-hand pop (its first pop is forced and skips
    constriction/remove, which the engine already performed).  ``kinds``
    is ``[K] int32`` (0 single, 1 dual, -1 dead/pad) and selects each
    node's tracker in ``tr_scalars``/``lc``/``pc`` (stacked ``[2, ...]``:
    row 0 single tracker, row 1 dual).  ``seqv0`` ranks the nodes'
    original queue insertion order for FIFO tie-breaks; re-pushed nodes
    take fresh, larger ranks and lose full ties to never-popped entries.

    ``params`` is ``[17] int32``: (me_budget, min_count, ed_delta,
    imb_min, l2, weighted, rest_cost, rest_len, n_live, max_queue_size,
    capacity_per_size, step_limit, max_nodes_wo_constraint, create_mode,
    n_pool, split_relax, mc_dyn).  ``split_relax`` permits clear-margin
    fractional-vote splits (only sound when the mc table is constant,
    i.e. min_af == 0 — the vote-total index is undecidable otherwise).
    ``tr_scalars`` is ``[2, 4] int32``: per kind (threshold, total,
    farthest, last_constraint).  Both host constriction triggers are
    modeled on device (queue overflow and the ``max_nodes_wo_constraint``
    budget), so the host does NOT need to clamp ``step_limit``.

    ON-DEVICE CHILD CREATION (``create_mode`` > 0): a winner whose votes
    split cleanly — exact integer counts, no near-ties — no longer stops
    the arena.  The kernel enumerates the host's exact child list
    (``DualConsensusDWFA._build_specs`` order: singles by ascending
    symbol, then split pairs over all non-wildcard candidates in
    (count desc, sym) order when >= 2 candidates reach ``min_count``;
    for dual parents the full cross product of each side's passing
    symbols), clones + pushes each child into the next free node of the
    host-provided creation pool (node indices ``n_live .. n_live+n_pool``
    own real state slots), applies divergence pruning to dual children,
    replays the tracker arithmetic (parent pop = remove + process, one
    insert per child), and continues the pop loop with the children
    competing.  ``create_mode`` 1 = singles only (the single engine's
    expansion has no split pairs), 2 = singles + split pairs + dual
    cross products (the dual engine).  Events that don't fit (children >
    ``CRE_PER_EVENT``, pool exhausted, record buffer full, non-exact
    votes, a finished/locked side) stop with code 1 as before — the
    host re-derives the expansion, so absorption is purely an
    optimization with identical semantics.
    The history records ``2K + node`` for the consumed parent pop and
    ``3K + j`` for creation record ``j``; records carry (parent, kind,
    sym1, sym2, created_len), with child ``j`` living at node index
    ``n_live + j``.

    Stop codes: 1 = winner needs host arbitration (votes/finished side),
    2 = winner reached its baseline end (host records the result),
    3 = a rest-of-queue entry wins the pop (or every arena node died),
    4 = step limit, 5 = band overflow.  A winner that would be
    DISCARDED at its pop (me-budget, threshold, capacity, or dual
    imbalance) is discarded ON DEVICE — queue removal applied, the node
    marked dead, history records ``K + node`` — and the loop continues
    with the survivors (the host frees dead nodes and replays their
    removals).  Returns (state, hist, n_steps, code, stop_node,
    per-node steps, per-side stats, act, cons, clen, alive,
    cre_count, cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len).
    """
    me_budget = params[0]
    min_count = params[1]
    delta = params[2]
    # params[3] (imb_min) is consumed host-side only (fallback imb_tab)
    l2 = params[4].astype(bool)
    weighted = params[5].astype(bool)
    rest_cost = params[6]
    rest_len = params[7]
    n_live = params[8]
    max_queue = params[9]
    cap = params[10]
    step_limit = params[11]
    max_nwc = params[12]
    create_mode = params[13]
    n_pool = params[14]
    relax = params[15].astype(bool)
    mc_dyn = params[16].astype(bool)

    W = state["D"].shape[2]
    E = jnp.int32((W - 2) // 2)
    C = state["cons"].shape[1]
    Lw = lc0.shape[1]
    R = reads.shape[0]
    # the whole pop/vote/creation pipeline runs at the REAL alphabet
    # width (dense ids never reach the padded columns); only the final
    # host-visible stats are padded back to the shared num_symbols shape
    A = num_symbols if a_real is None else min(a_real, num_symbols)
    n_lim = n_live + n_pool          # nodes beyond this are pure scratch

    def stats_all(D, e, rmin, er, offs, act, clen, off0s, pad=True):
        """Per-side snapshots [2K, ...]; with ``uniform`` (static) the 2K
        read windows are unrolled ``dynamic_slice``s of ``reads_pad``
        (each side's active reads share offset ``off0s[side]``) instead
        of per-lane gathers — the arena's dominant per-iteration cost."""
        if uniform:
            vchars = jnp.stack(
                [
                    _read_window(reads_pad, W + clen[s] - off0s[s] - E, R, W)
                    for s in range(2 * K)
                ]
            )
            return jax.vmap(
                lambda D_, e_, rmin_, er_, off_, act_, vchar_, clen_: (
                    _stats_core_w(
                        D_, e_, rmin_, er_, off_, act_, rlen, vchar_,
                        clen_, num_symbols, E, a_real=a_real, pad=pad,
                    )
                )
            )(D, e, rmin, er, offs, act, vchars, clen)
        return jax.vmap(
            lambda D_, e_, rmin_, er_, off_, act_, clen_: _stats_core(
                D_, e_, rmin_, er_, off_, act_, rlen, reads, clen_,
                num_symbols, E, a_real=a_real, pad=pad,
            )
        )(D, e, rmin, er, offs, act, clen)

    def col_side(D, e, rmin, er, off, act, jnew, off0, sym):
        if uniform:
            return _col_step_u(
                D, e, rmin, er, off, act, rlen, reads_pad, jnew, off0, sym,
                wc, et, E,
            )
        return _col_step(
            D, e, rmin, er, off, act, rlen, reads, jnew, sym, wc, et, E
        )
    EPS = VOTE_EPS
    BIGTOT = jnp.int32(2**31 - 1)
    MCN = mc_tab.shape[0]
    IMBN = imb_tab.shape[0]

    def nominate(occ, split, w):
        return _nominate_side(occ, split, w, wc, weighted, mc_tab, mc_dyn)

    def node_eval(dual, off2, act2, eds2, occ2, split2, reached2, clen2):
        """Per-node decision inputs; side axes are [2, ...]."""
        a1 = act2[0]
        a2 = jnp.where(dual, act2[1], False)
        c1 = jnp.where(l2, eds2[0] * eds2[0], eds2[0])
        c2 = jnp.where(l2, eds2[1] * eds2[1], eds2[1])
        BIG = jnp.int32(1 << 28)
        best = jnp.minimum(jnp.where(a1, c1, BIG), jnp.where(a2, c2, BIG))
        total = jnp.where(
            dual,
            jnp.where(a1 | a2, best, 0).sum(),
            jnp.where(a1, c1, 0).sum(),
        )
        nlen = jnp.where(dual, jnp.maximum(clen2[0], clen2[1]), clen2[0])
        cost_ovf = l2 & (
            jnp.maximum(
                jnp.where(a1, eds2[0], 0).max(),
                jnp.where(a2, eds2[1], 0).max(),
            )
            > 2048
        )
        # conservative completion folds (see _j_run/_j_run_dual): lanes
        # inactive on every tracked side count as done so the arena stops
        # at or before each host-recordable state
        rr = (a1 & reached2[0]) | (a2 & reached2[1])
        reach_stop = jnp.where(
            dual,
            jnp.where(et, (rr | (~a1 & ~a2)).all(), rr.any()),
            jnp.where(
                et,
                (reached2[0] | ~a1).all(),
                reached2[0].any(),
            ),
        )
        fin1 = jnp.where(
            et, (reached2[0] | ~a1).all(), (a1 & reached2[0]).any()
        )
        fin2 = jnp.where(
            et, (reached2[1] | ~a2).all(), (a2 & reached2[1]).any()
        )
        both = a1 & a2
        c1f = jnp.maximum(eds2[0].astype(jnp.float32), 0.5)
        c2f = jnp.maximum(eds2[1].astype(jnp.float32), 0.5)
        denom = c1f + c2f
        use_w = weighted & dual
        w1 = jnp.where(
            use_w & both, c2f / denom, jnp.where(a1, 1.0, 0.0)
        )
        w2 = jnp.where(
            use_w & both, c1f / denom, jnp.where(a2, 1.0, 0.0)
        )
        (dirty1, sym1, cnt1, hv1, ex1, mc1, nt1) = nominate(
            occ2[0], split2[0], w1
        )
        (dirty2, sym2, cnt2, hv2, ex2, mc2, nt2) = nominate(
            occ2[1], split2[1], w2
        )
        dirty = jnp.where(
            dual, dirty1 | dirty2 | fin1 | fin2, dirty1
        ) | cost_ovf
        imb_v = imb_tab[jnp.clip(nlen, 0, IMBN - 1)]
        imb = dual & ((a1.sum() < imb_v) | (a2.sum() < imb_v))
        return (
            total, nlen, reach_stop, dirty, sym1, sym2, imb,
            fin1, fin2, cost_ovf,
            jnp.stack([cnt1, cnt2]), jnp.stack([hv1, hv2]),
            jnp.stack([ex1, ex2]), jnp.stack([mc1, mc2]),
            jnp.stack([nt1, nt2]),
        )

    def substep(carry, masked):
        (D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
         lc, pc, tr, steps, hist, nsteps, seqv, fresh, alive, seq_ctr,
         pool_next, cre_count, cre_parent, cre_kind, cre_sym1, cre_sym2,
         cre_len, _diag, _code, _stop_node) = carry
        # the tracker constriction below mutates tr unconditionally, so a
        # frozen speculative sub-step must restore it at the end
        tr_in = tr

        is_dual = kinds == 1
        eds, occ, split, reached = stats_all(
            D, e, rmin, er, offs, act, clen, off0s, pad=False
        )

        (totals, lens, reach, dirty, sym1s, sym2s, imb, fin1s, fin2s,
         covfs, cstk, hvstk, exstk, mcstk, ntstk) = jax.vmap(node_eval)(
            is_dual,
            offs.reshape(K, 2, R),
            act.reshape(K, 2, R),
            eds.reshape(K, 2, R),
            occ.reshape(K, 2, R, -1),
            split.reshape(K, 2, R),
            reached.reshape(K, 2, R),
            clen.reshape(K, 2),
        )
        totals = jnp.where(alive & (kinds >= 0), totals, BIGTOT)

        # ---- pop-winner tournament: host priority is (-cost, len) with
        # FIFO (smaller seq rank) on full ties.  Vectorized reductions:
        # an unrolled K-deep comparison chain bloated the compiled graph
        # (XLA:CPU flakily segfaulted compiling the arena at K=48)
        min_total = totals.min()
        cand1 = totals == min_total
        best_len = jnp.where(cand1, lens, -1).max()
        cand2 = cand1 & (lens == best_len)
        win = (
            jnp.where(cand2, seqv, jnp.int32(2**31 - 1))
            .argmin()
            .astype(jnp.int32)
        )
        first = nsteps == 0
        win = jnp.where(first, 0, win)
        wtot = totals[win]
        wlen = lens[win]
        # every arena node dead (all discarded): the host resumes from
        # the outer queue — same exit as a rest-of-queue win
        arena_empty = wtot == BIGTOT
        # vs the best rest-of-queue entry: rest wins cost ties at equal
        # length unless the winner's ORIGINAL queue entry (never
        # re-pushed) predates it
        rest_wins = ~first & (
            (wtot > rest_cost)
            | ((wtot == rest_cost) & (wlen < rest_len))
            | ((wtot == rest_cost) & (wlen == rest_len) & ~fresh[win])
        )

        # ---- tracker bookkeeping (exact PQueueTracker arithmetic).  The
        # engine constricts BOTH kinds' trackers at the top of every pop
        # iteration; the in-hand first pop (node 0) was already
        # constricted and removed by the engine before the arena engaged.
        def constrict_kind(k_, tr_):
            def body_(args):
                thr_, total_, _lcon = args
                total_ = total_ - lc[k_, jnp.clip(thr_, 0, Lw - 1)]
                return thr_ + 1, total_, jnp.int32(0)

            thr_, total_, lcon_ = lax.while_loop(
                lambda a: ~first
                & ((a[1] > max_queue) | (a[2] >= max_nwc))
                & (a[0] < tr_[k_, 2]),
                body_,
                (tr_[k_, 0], tr_[k_, 1], tr_[k_, 3]),
            )
            return tr_.at[k_, 0].set(thr_).at[k_, 1].set(total_).at[
                k_, 3
            ].set(lcon_)

        tr = constrict_kind(0, tr)
        tr = constrict_kind(1, tr)

        k = jnp.clip(kinds[win], 0, 1)
        thr = tr[k, 0]
        total_q = tr[k, 1]
        far = tr[k, 2]
        lcon = tr[k, 3]
        discarded = (
            (wtot > me_budget)
            | (wlen < thr)
            | (pc[k, jnp.clip(wlen, 0, Lw - 1)] >= cap)
            | imb[win]
        )

        # a discarded pop is handled ON DEVICE (the host pre-checked the
        # in-hand first pop, so `first` discards cannot occur): the node
        # dies, its queue entry is removed, and the loop continues with
        # the survivors — the host replays the removal from the history.
        # With the history full the arena stops 4 instead and the host
        # performs the discard at its own re-pop.
        # ~first is semantically a no-op (the engine pre-checks the
        # in-hand pop's discard conditions before engaging the arena) but
        # hardens against a caller violating that invariant: replaying a
        # queue removal for an already-removed entry would corrupt the
        # tracker counts.  The paired `first` arm in the code selection
        # below stops the loop instead (code 4, nothing committed), so
        # the host re-pops and performs the discard itself.
        discard_now = ~first & ~rest_wins & ~arena_empty & discarded & (
            nsteps < step_limit
        )

        # ---- on-device child creation decision (see docstring): a
        # clean vote split becomes a batch of child nodes competing in
        # the arena instead of a stop
        wk_single = kinds[win] == 0
        cA = cstk[win, 0]
        cB = cstk[win, 1]
        hvA = hvstk[win, 0]
        hvB = hvstk[win, 1]
        exA = exstk[win, 0]
        exB = exstk[win, 1]
        ntA = ntstk[win, 0]
        ntB = ntstk[win, 1]
        sym_idx = jnp.arange(A, dtype=jnp.int32)
        mcA_f = mcstk[win, 0].astype(jnp.float32)
        mcB_f = mcstk[win, 1].astype(jnp.float32)
        maxA = jnp.where(hvA, cA, -1.0).max()
        passA = hvA & (cA >= jnp.minimum(mcA_f, maxA))
        maxB = jnp.where(hvB, cB, -1.0).max()
        passB = hvB & (cB >= jnp.minimum(mcB_f, maxB))
        nA = passA.sum()
        nB = passB.sum()
        # split pairs (single parents): all non-wildcard candidates in
        # (count desc, sym asc) order, gated on >= 2 candidates reaching
        # the side's dynamic min count (host _build_specs semantics;
        # symtab is sorted, so dense-id order == byte order)
        wc_mask = (wc >= 0) & (sym_idx == jnp.maximum(wc, 0))
        cand_nw = hvA & ~wc_mask
        ncand = cand_nw.sum()
        npass_mc = (cand_nw & (cA >= mcA_f)).sum()
        n_pairs = jnp.where(
            (create_mode >= 2) & (npass_mc > 1),
            ncand * (ncand - 1) // 2,
            0,
        )
        n_children = jnp.where(wk_single, nA + n_pairs, nA * nB)
        # vote-decision safety: exact single-tip integer counts, OR
        # (``relax``: min_af == 0, so the mc-table index is moot)
        # fractional counts whose every comparison the f32 fold decides
        # with margin > EPS — the same contract the commit path uses —
        # including the pairwise ordering margins the split-pair
        # enumeration needs (equal-count ties are only safe when exact)
        mcmargA = jnp.where(hvA, jnp.abs(cA - mcA_f) > EPS, True).all()
        mcmargB = jnp.where(hvB, jnp.abs(cB - mcB_f) > EPS, True).all()
        dmat = jnp.abs(cA[:, None] - cA[None, :])
        pairm = (
            cand_nw[:, None]
            & cand_nw[None, :]
            & (sym_idx[:, None] != sym_idx[None, :])
        )
        pair_ok = jnp.where(pairm, dmat > EPS, True).all()
        relaxA = relax & ~ntA & mcmargA
        relaxB = relax & ~ntB & mcmargB
        # count-ordering margins only matter where split pairs can be
        # enumerated (mode >= 2); mode 1 emits singles by symbol order
        ord_ok = pair_ok | (create_mode < 2)
        exact_ok = jnp.where(
            wk_single,
            exA | (relaxA & ord_ok),
            (exA | relaxA) & (exB | relaxB),
        )
        kind_ok = wk_single | (
            (create_mode >= 2) & ~fin1s[win] & ~fin2s[win]
        )
        splitable = (
            (create_mode >= 1)
            & exact_ok
            & kind_ok
            & ~covfs[win]
            & (n_children >= 2)
            & (n_children <= CRE_PER_EVENT)
            & (pool_next + n_children <= n_lim)
            & (cre_count + n_children <= CRE_CAP)
            & (nsteps + 1 + n_children <= step_limit)
        )
        want_split = (
            dirty[win] & splitable & ~reach[win] & ~discarded
            & ~rest_wins & ~arena_empty
        )
        # stop diagnostics (read by the host at code-1 stops): why the
        # winner's split was not absorbed — packed flags + child count
        stop_diag = (
            n_children * 64
            + exact_ok.astype(jnp.int32)
            + kind_ok.astype(jnp.int32) * 2
            + (n_children <= CRE_PER_EVENT).astype(jnp.int32) * 4
            + (pool_next + n_children <= n_lim).astype(jnp.int32) * 8
            + (cre_count + n_children <= CRE_CAP).astype(jnp.int32) * 16
            + (nsteps + 1 + n_children <= step_limit).astype(jnp.int32) * 32
        )

        code = jnp.where(
            rest_wins | arena_empty,
            3,
            jnp.where(
                discarded,
                jnp.where(first | (nsteps >= step_limit), 4, 0),
                jnp.where(
                    reach[win],
                    2,
                    jnp.where(
                        dirty[win] & ~want_split,
                        1,
                        jnp.where(nsteps >= step_limit, 4, 0),
                    ),
                ),
            ),
        )
        if masked:
            # speculative sub-step (see _j_run): a stop earlier in the
            # block freezes the arena — no event of any kind (commit,
            # discard, split) may fire, and the first stop code sticks
            discard_now = discard_now & (_code == 0)
            want_split = want_split & (_code == 0)
            code = jnp.where(_code != 0, _code, code)

        # ---- child creation, under lax.cond so the staged column
        # pushes (2 per child slot) only execute on actual split events
        p1c = 2 * win
        p2c = p1c + 1
        plen = lens[win]

        def create_branch(op):
            (D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
             lc, tr, hist, seqv, fresh, alive,
             pool_next, cre_count, cre_parent, cre_kind, cre_sym1,
             cre_sym2, cre_len) = op
            cumA = jnp.cumsum(passA.astype(jnp.int32))
            cumB = jnp.cumsum(passB.astype(jnp.int32))

            def nth(cum, mask, t_):
                """Dense id of the (t_+1)-th passing symbol, ascending."""
                return jnp.argmax((cum == t_ + 1) & mask).astype(jnp.int32)

            # (count desc, sym asc) candidate order; valid only when
            # counts are exact or pairwise-separated (checked above)
            order = jnp.lexsort(
                (sym_idx, jnp.where(cand_nw, -cA, jnp.float32(3e38)))
            )
            row_sz = jnp.maximum(ncand - 1 - sym_idx, 0)
            cum_rows = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(row_sz)]
            )
            nB_safe = jnp.maximum(nB, 1)

            def spec_at(tt):
                """Child ``tt``'s (in_range, kind, sym1, sym2, src2) in
                the host's exact ``_build_specs`` order."""
                in_range = tt < n_children
                is_sing = wk_single & (tt < nA)
                s_sym = nth(cumA, passA, tt)
                pp = tt - nA
                prow = jnp.argmax(
                    (pp >= cum_rows[:-1]) & (pp < cum_rows[1:])
                ).astype(jnp.int32)
                pj = prow + 1 + pp - cum_rows[jnp.clip(prow, 0, A - 1)]
                pairA = order[jnp.clip(prow, 0, A - 1)]
                pairB = order[jnp.clip(pj, 0, A - 1)]
                crossA = nth(cumA, passA, tt // nB_safe)
                crossB = nth(cumB, passB, tt % nB_safe)
                symA = jnp.where(
                    wk_single, jnp.where(is_sing, s_sym, pairA), crossA
                )
                symB = jnp.where(wk_single, pairB, crossB)
                kind_t = jnp.where(is_sing, 0, 1).astype(jnp.int32)
                # split children clone BOTH sides from the parent's side 1
                src2 = jnp.where(wk_single, p1c, p2c)
                return in_range, kind_t, symA, symB, src2

            def cols_at(symA, symB, src2):
                """Both sides' pushed columns for one child (parent rows
                are never written by creation, so reading them from the
                carried arrays is stable)."""
                c1cols = col_side(
                    D[p1c], e[p1c], rmin[p1c], er[p1c], offs[p1c],
                    act[p1c], clen[p1c] + 1, off0s[p1c], symA,
                )
                c2cols = col_side(
                    D[src2], e[src2], rmin[src2], er[src2], offs[src2],
                    act[src2], clen[src2] + 1, off0s[src2], symB,
                )
                return c1cols, c2cols

            # pass 1: band-overflow scan, so an overflow anywhere aborts
            # the whole event atomically (nothing written)
            def ovf_body(t, ovf):
                in_range, kind_t, symA, symB, src2 = spec_at(t)
                (_, e1n, _, _), (_, e2n, _, _) = cols_at(symA, symB, src2)
                dual_t = kind_t == 1
                return ovf | (
                    in_range
                    & (
                        (act[p1c] & (e1n >= E)).any()
                        | (dual_t & (act[src2] & (e2n >= E)).any())
                    )
                )

            ovf_any = lax.fori_loop(
                0, CRE_PER_EVENT, ovf_body, jnp.bool_(False)
            )
            ok = ~ovf_any

            # pass 2: predicated writes (dynamic loop keeps the compiled
            # graph small — an unrolled version of this block crashed
            # the XLA:CPU compiler on large geometries)
            def write_body(t, st):
                (D, e, rmin, er, act_a, cons, clen, offs, off0s, kinds,
                 lc, tr, hist, seqv, fresh, alive,
                 cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len) = st
                in_range, kind_t, symA, symB, src2 = spec_at(t)
                do = ok & in_range
                dual_do = do & (kind_t == 1)
                (D1n, e1n, rmin1n, er1n), (D2n, e2n, rmin2n, er2n) = (
                    cols_at(symA, symB, src2)
                )
                # divergence pruning on the fresh dual pair (host prunes
                # children at pop-finishing time with the same rule)
                both_t = act_a[p1c] & act_a[src2] & (kind_t == 1)
                act1n = act_a[p1c] & ~(both_t & (e2n + delta < e1n))
                act2n = act_a[src2] & ~(both_t & (e1n + delta < e2n))
                c = pool_next + t
                c1 = 2 * c
                c2 = c1 + 1
                sel = lambda cnd, new, old: jnp.where(cnd, new, old)  # noqa: E731
                D = D.at[c1].set(sel(do, D1n, D[c1]))
                e = e.at[c1].set(sel(do, e1n, e[c1]))
                rmin = rmin.at[c1].set(sel(do, rmin1n, rmin[c1]))
                er = er.at[c1].set(sel(do, er1n, er[c1]))
                act_a = act_a.at[c1].set(sel(do, act1n, act_a[c1]))
                cons = cons.at[c1].set(
                    sel(
                        do,
                        cons[p1c].at[jnp.clip(clen[p1c], 0, C - 1)].set(
                            symA
                        ),
                        cons[c1],
                    )
                )
                clen = clen.at[c1].set(sel(do, clen[p1c] + 1, clen[c1]))
                offs = offs.at[c1].set(sel(do, offs[p1c], offs[c1]))
                off0s = off0s.at[c1].set(sel(do, off0s[p1c], off0s[c1]))
                D = D.at[c2].set(sel(dual_do, D2n, D[c2]))
                e = e.at[c2].set(sel(dual_do, e2n, e[c2]))
                rmin = rmin.at[c2].set(sel(dual_do, rmin2n, rmin[c2]))
                er = er.at[c2].set(sel(dual_do, er2n, er[c2]))
                act_a = act_a.at[c2].set(sel(dual_do, act2n, act_a[c2]))
                cons = cons.at[c2].set(
                    sel(
                        dual_do,
                        cons[src2].at[
                            jnp.clip(clen[src2], 0, C - 1)
                        ].set(symB),
                        cons[c2],
                    )
                )
                clen = clen.at[c2].set(
                    sel(dual_do, clen[src2] + 1, clen[c2])
                )
                offs = offs.at[c2].set(sel(dual_do, offs[src2], offs[c2]))
                off0s = off0s.at[c2].set(
                    sel(dual_do, off0s[src2], off0s[c2])
                )
                kinds = kinds.at[c].set(sel(do, kind_t, kinds[c]))
                alive = alive.at[c].set(alive[c] | do)
                seqv = seqv.at[c].set(sel(do, seq_ctr + t, seqv[c]))
                fresh = fresh.at[c].set(fresh[c] & ~do)
                # tracker insert: one per child, at the child's length,
                # against the child kind's CURRENT threshold
                nl = plen + 1
                li_c = jnp.clip(nl, 0, Lw - 1)
                kk = jnp.clip(kind_t, 0, 1)
                lc = lc.at[kk, li_c].add(do.astype(jnp.int32))
                tr = tr.at[kk, 1].add(
                    (do & (nl >= tr[kk, 0])).astype(jnp.int32)
                )
                ridx = cre_count + t
                rclip = jnp.clip(ridx, 0, CRE_CAP - 1)
                hp = jnp.clip(nsteps + 1 + t, 0, max_steps - 1)
                hist = hist.at[hp].set(
                    sel(do, (3 * K + ridx).astype(hist.dtype), hist[hp])
                )
                cre_parent = cre_parent.at[rclip].set(
                    sel(do, win, cre_parent[rclip])
                )
                cre_kind = cre_kind.at[rclip].set(
                    sel(do, kind_t, cre_kind[rclip])
                )
                cre_sym1 = cre_sym1.at[rclip].set(
                    sel(do, symA, cre_sym1[rclip])
                )
                cre_sym2 = cre_sym2.at[rclip].set(
                    sel(do, symB, cre_sym2[rclip])
                )
                cre_len = cre_len.at[rclip].set(
                    sel(do, nl, cre_len[rclip])
                )
                return (
                    D, e, rmin, er, act_a, cons, clen, offs, off0s,
                    kinds, lc, tr, hist, seqv, fresh, alive,
                    cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len,
                )

            (D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
             lc, tr, hist, seqv, fresh, alive,
             cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len) = (
                lax.fori_loop(
                    0,
                    CRE_PER_EVENT,
                    write_body,
                    (D, e, rmin, er, act, cons, clen, offs, off0s,
                     kinds, lc, tr, hist, seqv, fresh, alive,
                     cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len),
                )
            )
            n_made = jnp.where(ok, n_children, 0)
            pool_next = pool_next + n_made
            cre_count = cre_count + n_made
            return (
                (D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
                 lc, tr, hist, seqv, fresh, alive,
                 pool_next, cre_count, cre_parent, cre_kind, cre_sym1,
                 cre_sym2, cre_len),
                ovf_any,
            )

        def skip_branch(op):
            return op, jnp.bool_(False)

        ((D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
          lc, tr, hist, seqv, fresh, alive,
          pool_next, cre_count, cre_parent, cre_kind, cre_sym1,
          cre_sym2, cre_len), cre_ovf) = lax.cond(
            want_split,
            create_branch,
            skip_branch,
            (D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
             lc, tr, hist, seqv, fresh, alive,
             pool_next, cre_count, cre_parent, cre_kind, cre_sym1,
             cre_sym2, cre_len),
        )
        split_commit = want_split & ~cre_ovf
        code = jnp.where(want_split & cre_ovf, 5, code)

        # ---- commit: advance the winner's side(s) by its symbol(s)
        s1 = 2 * win
        s2 = s1 + 1
        dual_w = is_dual[win]
        sa = sym1s[win]
        sb = sym2s[win]

        D1n, e1n, rmin1n, er1n = col_side(
            D[s1], e[s1], rmin[s1], er[s1], offs[s1], act[s1],
            clen[s1] + 1, off0s[s1], sa,
        )
        D2n, e2n, rmin2n, er2n = col_side(
            D[s2], e[s2], rmin[s2], er[s2], offs[s2], act[s2],
            clen[s2] + 1, off0s[s2], sb,
        )
        ovf = (act[s1] & (e1n >= E)).any() | (
            dual_w & (act[s2] & (e2n >= E)).any()
        )
        both2 = act[s1] & act[s2] & dual_w
        act1n = act[s1] & ~(both2 & (e2n + delta < e1n))
        act2n = act[s2] & ~(both2 & (e1n + delta < e2n))

        commit = (code == 0) & ~discard_now & ~split_commit & ~ovf
        code = jnp.where(
            code != 0,
            code,
            jnp.where(
                discard_now | split_commit, 0, jnp.where(ovf, 5, 0)
            ),
        )

        D = D.at[s1].set(jnp.where(commit, D1n, D[s1]))
        e = e.at[s1].set(jnp.where(commit, e1n, e[s1]))
        rmin = rmin.at[s1].set(jnp.where(commit, rmin1n, rmin[s1]))
        er = er.at[s1].set(jnp.where(commit, er1n, er[s1]))
        act = act.at[s1].set(jnp.where(commit, act1n, act[s1]))
        cons = cons.at[s1].set(
            jnp.where(
                commit,
                cons[s1].at[jnp.clip(clen[s1], 0, C - 1)].set(sa),
                cons[s1],
            )
        )
        clen = clen.at[s1].set(jnp.where(commit, clen[s1] + 1, clen[s1]))
        dual_commit = commit & dual_w
        D = D.at[s2].set(jnp.where(dual_commit, D2n, D[s2]))
        e = e.at[s2].set(jnp.where(dual_commit, e2n, e[s2]))
        rmin = rmin.at[s2].set(jnp.where(dual_commit, rmin2n, rmin[s2]))
        er = er.at[s2].set(jnp.where(dual_commit, er2n, er[s2]))
        act = act.at[s2].set(jnp.where(dual_commit, act2n, act[s2]))
        cons = cons.at[s2].set(
            jnp.where(
                dual_commit,
                cons[s2].at[jnp.clip(clen[s2], 0, C - 1)].set(sb),
                cons[s2],
            )
        )
        clen = clen.at[s2].set(
            jnp.where(dual_commit, clen[s2] + 1, clen[s2])
        )

        # tracker commit: remove + process + insert (constriction above)
        new_len = wlen + 1
        li = jnp.clip(wlen, 0, Lw - 1)
        lc_k = lc[k]
        lc_k = jnp.where(first, lc_k, lc_k.at[li].add(-1))
        total_q2 = jnp.where(
            first, total_q, total_q - (wlen >= thr).astype(jnp.int32)
        )
        pc_k = pc[k].at[li].add(1)
        ni = jnp.clip(new_len, 0, Lw - 1)
        lc_k = lc_k.at[ni].add(1)
        total_q2 = total_q2 + (new_len >= thr).astype(jnp.int32)
        far2 = jnp.maximum(far, wlen)
        lcon2 = lcon + 1

        # discard bookkeeping: the pop's queue removal only (no process /
        # insert / farthest / lcon — the engine's ignored-pop path)
        lc_disc = lc.at[k, li].add(-1)
        tr_disc = tr.at[k, 1].set(total_q - (wlen >= thr).astype(jnp.int32))

        # split-pop bookkeeping: remove + process, NO parent insert (the
        # child inserts were applied inside the creation branch, so the
        # removal is ADDITIVE on top of them)
        lc_sp = jnp.where(first, lc, lc.at[k, li].add(-1))
        tr_sp = (
            tr.at[k, 1]
            .add(jnp.where(first, 0, -(wlen >= thr).astype(jnp.int32)))
            .at[k, 2]
            .set(jnp.maximum(far, wlen))
            .at[k, 3]
            .set(lcon + 1)
        )
        pc_sp = pc.at[k, li].add(1)

        lc = jnp.where(
            commit,
            lc.at[k].set(lc_k),
            jnp.where(
                discard_now, lc_disc, jnp.where(split_commit, lc_sp, lc)
            ),
        )
        pc = jnp.where(
            commit,
            pc.at[k].set(pc_k),
            jnp.where(split_commit, pc_sp, pc),
        )
        tr = jnp.where(
            commit,
            tr.at[k].set(jnp.stack([thr, total_q2, far2, lcon2])),
            jnp.where(
                discard_now, tr_disc, jnp.where(split_commit, tr_sp, tr)
            ),
        )

        recorded = commit | discard_now
        hist_val = jnp.where(
            split_commit,
            2 * K + win,
            jnp.where(discard_now, win + K, win),
        ).astype(hist.dtype)
        hist = jnp.where(
            recorded | split_commit,
            hist.at[jnp.clip(nsteps, 0, max_steps - 1)].set(hist_val),
            hist,
        )
        steps = jnp.where(commit, steps.at[win].add(1), steps)
        alive = jnp.where(
            discard_now | split_commit, alive.at[win].set(False), alive
        )
        nsteps = nsteps + jnp.where(
            split_commit, 1 + n_children, recorded.astype(jnp.int32)
        )
        seqv = jnp.where(commit, seqv.at[win].set(seq_ctr), seqv)
        fresh = jnp.where(commit, fresh.at[win].set(False), fresh)
        seq_ctr = seq_ctr + jnp.where(
            split_commit, n_children, commit.astype(jnp.int32)
        )
        stop_node = win
        if masked:
            # frozen sub-step: keep the stopping sub-step's tracker state
            # and stop diagnostics (every other write above is gated on
            # commit/discard_now/split_commit, all False once _code != 0)
            frozen = _code != 0
            tr = jnp.where(frozen, tr_in, tr)
            stop_diag = jnp.where(frozen, _diag, stop_diag)
            stop_node = jnp.where(frozen, _stop_node, stop_node)
        return (
            D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
            lc, pc, tr, steps, hist, nsteps, seqv, fresh, alive, seq_ctr,
            pool_next, cre_count, cre_parent, cre_kind, cre_sym1,
            cre_sym2, cre_len, stop_diag, code, stop_node,
        )

    def body(carry):
        # speculative multi-event block (see _j_run): sub-step 0 is the
        # exact single-event body (the loop condition guarantees code==0
        # there); later sub-steps freeze as soon as a stop code appears,
        # so the block is bit-identical to cols=1
        sub = substep(carry[:-1], masked=False)
        for _ in range(cols - 1):
            sub = substep(sub, masked=True)
        return sub + (carry[-1] + 1,)

    init = (
        state["D"][slots],
        state["e"][slots],
        state["rmin"][slots],
        state["er"][slots],
        state["act"][slots],
        state["cons"][slots],
        state["clen"][slots],
        state["off"][slots],
        off0s0,
        kinds0,
        lc0,
        pc0,
        tr_scalars,
        jnp.zeros((K,), jnp.int32),
        jnp.zeros((max_steps,), jnp.int16),
        jnp.int32(0),
        seqv0,
        jnp.arange(K) != 0,  # node 0's original entry is the in-hand pop
        jnp.arange(K) < n_live,  # alive: pool/pad nodes join on creation
        jnp.int32(K + 1),
        n_live.astype(jnp.int32),  # pool_next: next free pool node
        jnp.int32(0),              # cre_count
        jnp.zeros((CRE_CAP,), jnp.int32),  # cre_parent
        jnp.zeros((CRE_CAP,), jnp.int32),  # cre_kind
        jnp.zeros((CRE_CAP,), jnp.int32),  # cre_sym1
        jnp.zeros((CRE_CAP,), jnp.int32),  # cre_sym2
        jnp.zeros((CRE_CAP,), jnp.int32),  # cre_len
        jnp.int32(0),              # stop_diag
        jnp.int32(0),
        jnp.int32(0),
        jnp.int32(0),              # iters (while iterations)
    )
    (D, e, rmin, er, act, cons, clen, offs, off0s, kinds,
     _lc, _pc, _tr, steps, hist, nsteps, _seqv, _fresh, alive, _ctr,
     _pool, cre_count, cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len,
     stop_diag, code, stop_node, iters) = lax.while_loop(
        lambda c: c[28] == 0, body, init
    )

    eds, occ, split, reached = stats_all(
        D, e, rmin, er, offs, act, clen, off0s
    )

    out = dict(state)
    out["D"] = state["D"].at[slots].set(D)
    out["e"] = state["e"].at[slots].set(e)
    out["rmin"] = state["rmin"].at[slots].set(rmin)
    out["er"] = state["er"].at[slots].set(er)
    out["act"] = state["act"].at[slots].set(act)
    out["cons"] = state["cons"].at[slots].set(cons)
    out["clen"] = state["clen"].at[slots].set(clen)
    # off rows are carried (children inherit their parent's) and MUST be
    # scattered back: a created child's global off row is otherwise the
    # pool slot's stale garbage, corrupting its first post-arena push on
    # any offset workload (existing rows are rewritten unchanged)
    out["off"] = state["off"].at[slots].set(offs)
    return (
        out, hist, nsteps, code, stop_node, steps,
        (eds, occ, split, reached), act, cons, clen, alive,
        cre_count, cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len,
        stop_diag, iters,
    )


@partial(jax.jit, static_argnames=("P", "M"))
def _j_offset_scan(cons_win, heads, m, wc, P: int, M: int):
    """Batched activation-offset scoring (the second batchable kernel,
    SURVEY §3.5; reference loop ``/root/reference/src/consensus.rs:413-448``):
    for every window position ``p < P`` and head lane ``b``,
    ``ed[b, p] = min_j Lev(head[b][:m], cons_win[p : p + j])`` — the
    prefix-mode semantics of ``wfa_ed_config(require_both_end=False)``
    as one dense DP instead of ``offset_window`` serial host WFAs.

    ``cons_win`` is ``[P + 2M] int32`` dense symbol ids padded with a
    never-matching sentinel (alignments into padding can never beat the
    unpadded optimum: every pad char adds >= 1 cost).  ``heads`` is
    ``[B, M] int32`` (its own sentinel).  ``j`` ranges to ``2M``: any
    longer consensus prefix costs ``j - m > m >= Lev(head, empty)``.
    The wildcard matches on either side, as in ``wfa_ed_config``.
    """
    B = heads.shape[0]
    Jmax = 2 * M
    iidx = jnp.arange(M + 1, dtype=jnp.int32)
    pidx = jnp.arange(P, dtype=jnp.int32)
    Wn = cons_win.shape[0]
    col0 = jnp.broadcast_to(iidx[None, None, :], (B, P, M + 1)).astype(
        jnp.int32
    )
    best0 = jnp.minimum(jnp.full((B, P), Jmax + M + 5, jnp.int32), m)

    def body(j, carry):
        col, best = carry
        cj = cons_win[jnp.clip(pidx + j - 1, 0, Wn - 1)]  # [P]
        match = (
            (heads[:, None, :] == cj[None, :, None])
            | ((wc >= 0) & (heads[:, None, :] == wc))
            | ((wc >= 0) & (cj[None, :, None] == wc))
        )
        sub = col[:, :, :-1] + jnp.where(match, 0, 1)
        dele = col[:, :, 1:] + 1
        tmp = jnp.minimum(sub, dele)
        new0 = jnp.full((B, P, 1), j, jnp.int32)
        tmp_full = jnp.concatenate([new0, tmp], axis=2)
        # insertion chain new[i] = min_{k<=i} tmp_full[k] + (i - k)
        adj = tmp_full - iidx[None, None, :]
        new = lax.cummin(adj, axis=2) + iidx[None, None, :]
        ed_m = jnp.take_along_axis(
            new, jnp.full((B, P, 1), m, jnp.int32), axis=2
        )[..., 0]
        return new, jnp.minimum(best, ed_m)

    _col, best = lax.fori_loop(1, Jmax + 1, body, (col0, best0))
    return best


@partial(jax.jit, static_argnames=("W",))
def _j_replay(off, act, cons, clen, reads, rlen, wc, et, W: int):
    """Rebuild all branch DP state at band width ``W`` by replaying every
    branch's recorded consensus from scratch (used after band growth: a
    band is a window, so unlike the reference's wavefront it cannot be
    re-padded in place).  One device scan over the longest consensus."""
    E = jnp.int32((W - 2) // 2)
    B, R = off.shape

    # every read starts from the init column at its own DP anchor (its
    # activation offset), already present in D0; the loop only *steps*
    # reads whose anchor is behind the current column
    D0, e0, rmin0, er0 = jax.vmap(
        lambda o, a: _init_col(o, a, rlen, E, W)
    )(off, act)
    maxlen = clen.max()

    def body(j, carry):
        D, e, rmin, er = carry

        def per_branch(Db, eb, rminb, erb, offb, actb, consb, clenb):
            sym = consb[jnp.clip(j, 0, consb.shape[0] - 1)]
            Dn, en, rminn, ern = _col_step(
                Db, eb, rminb, erb, offb, actb, rlen, reads, j + 1, sym, wc,
                et, E,
            )
            stepm = actb & (offb <= j) & (j < clenb)
            sel = lambda new, old: jnp.where(stepm, new, old)  # noqa: E731
            return (
                jnp.where(stepm[:, None], Dn, Db),
                sel(en, eb),
                sel(rminn, rminb),
                sel(ern, erb),
            )

        return jax.vmap(per_branch)(
            D, e, rmin, er, off, act, cons, clen
        )

    D, e, rmin, er = lax.fori_loop(0, maxlen, body, (D0, e0, rmin0, er0))
    return D, e, rmin, er


class JaxScorer(WavefrontScorer):
    """Device-resident branch store over the banded column DP.

    Handles are host-side ids mapped to device slots; slot/geometry growth
    (branch count, consensus capacity, band width) recompiles the kernels
    for the new shapes — growth doubles, so recompiles are logarithmic.
    """

    INITIAL_E = 8
    INITIAL_SLOTS = 16
    #: geometry floors: quantizing small fixtures up to shared shapes means
    #: different datasets reuse the same compiled kernels (on this platform
    #: per-shape compile-cache traffic dominates small-fixture wall time;
    #: the extra vector lanes are noise)
    MIN_R = 16
    MIN_L = 256
    MIN_C = 512
    #: tip-vote tables are padded to at least this many dense symbols so
    #: 4-symbol and 5-symbol (wildcarded) alphabets share compiled shapes
    MIN_A = 8

    def __init__(self, reads: Sequence[bytes], config: CdwfaConfig) -> None:
        super().__init__(reads, config)
        n = len(self.reads)
        self._R = max(_next_pow2(max(n, 1)), self.MIN_R)
        # inside a served job the geometry floors rise to the ragged
        # arena's pool shapes, so every served job shares ONE compiled
        # kernel set (solo and ragged alike) and band-width equality —
        # the arena's byte-identity precondition — holds by default
        from waffle_con_tpu.ops import ragged as _ragged

        hint = _ragged.geometry_hint()
        if hint is not None:
            self._R = max(self._R, hint.rows)
        ms = config.mesh_shards or 1
        if self._R % ms:
            self._R = ms * ((self._R + ms - 1) // ms)
        self._shardings = None  # installed by parallel.shard_scorer
        max_len = max((len(r) for r in self.reads), default=1)
        #: real (unpadded) max read length; sizes the pallas staging
        self._max_rlen = max_len
        self._L = max(_next_pow2(max(max_len, 1)), self.MIN_L)
        if hint is not None and max_len <= hint.length:
            self._L = max(self._L, hint.length)
        self._A = max(_next_pow2(max(self.num_symbols, 1)), self.MIN_A)

        # int16 symbol storage: dense ids are < 257 and the -1 sentinel
        # fits, while the dominant ctor upload through the transfer
        # tunnel halves vs int32 (kernel arithmetic promotes as needed)
        reads_arr = np.full((self._R, self._L), -1, dtype=np.int16)
        rlen = np.zeros(self._R, dtype=np.int32)
        for i, r in enumerate(self.reads):
            reads_arr[i, : len(r)] = [self.sym_id[b] for b in r]
            rlen[i] = len(r)
        self._reads = jax.device_put(reads_arr)
        self._rlen = jax.device_put(rlen)

        # per-engine constants staged on device ONCE: passing a live device
        # array as a jit argument is free, while a fresh numpy scalar is a
        # separate host->device upload on every call
        self._wc = jax.device_put(
            np.int32(
                self.sym_id.get(config.wildcard, -2)
                if config.wildcard is not None
                else -2
            )
        )
        self._et = jax.device_put(np.bool_(config.allow_early_termination))

        if config.initial_band is not None:
            self._E = _next_pow2(int(config.initial_band), self.INITIAL_E)
        else:
            self._E = self.INITIAL_E
        if hint is not None:
            self._E = max(self._E, hint.band)
        self._B = self.INITIAL_SLOTS
        self._C = max(_next_pow2(max_len + 64), self.MIN_C)
        if hint is not None:
            self._C = max(self._C, hint.cons)
        #: fused-pallas run-loop mode ("tpu" | "interpret" | "off"),
        #: resolved once; the transposed reads staging is built lazily
        #: on the first pallas run and dropped on band growth
        from waffle_con_tpu.ops.pallas_run import pallas_mode

        self._pallas_mode = (
            pallas_mode() if config.backend != "native" else "off"
        )
        #: per-kernel health (1 = single, 2 = dual): a compile failure
        #: disables only the failing kernel, not the whole fused path
        # (sides, W, MS, i16) buckets individually disabled by a compile
        # failure; absent keys mean the bucket is still eligible, so one
        # huge-MS failure never disables the fused path for small
        # geometries (and a band grow naturally re-enables probing).
        self._pallas_kernel_ok = {}
        self._reads_T_cache = None
        self._stage_reads_pad()
        self._state = self._blank_state()
        #: host mirrors of the per-slot offset/active device state: the
        #: run kernels' dynamic-slice fast path needs to know — WITHOUT a
        #: device round trip — whether a branch's active reads share one
        #: offset (they do except after windowed late-read activation)
        self._off_host = np.zeros((self._B, self._R), dtype=np.int32)
        self._act_host = np.zeros((self._B, self._R), dtype=bool)
        self._free: List[int] = list(range(self._B))
        self._next_handle = 0
        self._slot_of = {}
        #: lazily created same-search speculation gang (see
        #: ops.ragged.FrontierGang / models.frontier)
        self._frontier_gang = None
        #: dispatch/step counters for bench + profiling observability
        self.counters = {
            "push_calls": 0,
            "push_branches": 0,
            "run_calls": 0,
            "run_steps": 0,
            "run_iters": 0,
            "run_spec_cols": 0,
            "run_dual_calls": 0,
            "run_dual_steps": 0,
            "run_dual_iters": 0,
            "run_dual_spec_cols": 0,
            "run_mega_calls": 0,
            "run_mega_steps": 0,
            "run_dual_mega_calls": 0,
            #: blocking device->host syncs paid by the run paths (one
            #: per control fetch / record fetch / stats fetch-or-resolve)
            #: — the quantity the megastep bundles down; see run_mega
            "host_round_trips": 0,
            "arena_iters": 0,
            "arena_spec_events": 0,
            "stats_calls": 0,
            "clone_calls": 0,
            "activate_calls": 0,
            "finalize_calls": 0,
            "grow_e_events": 0,
            "replayed_cols": 0,
        }

    # -- geometry ------------------------------------------------------

    @property
    def bucket_e(self) -> int:
        """Current band half-width (diagnostics; grows geometrically)."""
        return self._E

    def live_handles(self) -> Tuple[int, Optional[int]]:
        """(live handle count, slot capacity) — the arena-occupancy pair
        the obs gauges sample."""
        return len(self._slot_of), self._B

    @property
    def _W(self) -> int:
        return 2 * self._E + 2

    def _blank_state(self):
        return _j_blank(self._B, self._R, self._W, self._C)

    def _stage_reads_pad(self) -> None:
        """Stage the W-left-padded reads copy backing the run kernels'
        ``dynamic_slice`` window path (rebuilt on band growth: the pad
        width is the band width).  ``-1`` filler never matches a symbol
        or the wildcard, and every out-of-range lane is masked anyway."""
        self._reads_pad = _j_mkpad(self._reads, W=self._W)
        self._reads_T_cache = None  # geometry changed; restage lazily
        if self._shardings is not None and "_reads_pad" in self._shardings:
            self._reads_pad = jax.device_put(
                self._reads_pad, self._shardings["_reads_pad"]
            )

    def _place(self) -> None:  # waffle-lint: disable=WL003(placement bookkeeping only: rewrites _state slot ids, slot contents untouched)
        """Re-apply the mesh sharding (if any) after a geometry change —
        freshly built arrays default to single-device placement."""
        if self._shardings is not None:
            self._state = {
                name: jax.device_put(arr, self._shardings[name])
                for name, arr in self._state.items()
            }

    def _grow_e(self) -> None:
        """Double the band half-width and replay all branches at the new
        geometry (band values outside the old window are unknown, so the
        recorded consensus is re-scanned on device).  An arena-resident
        scorer is re-centered in pool rather than evicted: its staged
        reads are untouched by a band change, so it stays gang-eligible
        at the new per-row stride while the new width fits the pool's
        (see ``ops.ragged.recenter_scorer``)."""
        from waffle_con_tpu.ops import ragged as _ragged

        self._spec_drop()
        self._E *= 2
        _ragged.recenter_scorer(self)
        self.counters["grow_e_events"] += 1
        self.counters["replayed_cols"] += int(self._state["clen"].max())
        st = self._state
        D, e, rmin, er = _j_replay(
            st["off"], st["act"], st["cons"], st["clen"],
            self._reads, self._rlen, self._wc, self._et, self._W,
        )
        self._state = dict(st, D=D, e=e, rmin=rmin, er=er)
        self._place()
        self._stage_reads_pad()

    def _grow_slots(self) -> None:  # waffle-lint: disable=WL003(slot-axis growth copies every live slot verbatim; deposits stay valid)
        old_b = self._B
        self._B *= 2
        self._state = _j_grow_slots(self._state, new_b=self._B)
        self._place()
        self._free.extend(range(old_b, self._B))
        grow = lambda m, fill: np.concatenate(  # noqa: E731
            [m, np.full((self._B - old_b, self._R), fill, m.dtype)]
        )
        self._off_host = grow(self._off_host, 0)
        self._act_host = grow(self._act_host, False)

    def _grow_cons(self) -> None:
        self._spec_drop()
        self._C *= 2
        self._state = _j_grow_cons(self._state, new_c=self._C)
        self._place()

    def _alloc(self) -> Tuple[int, int]:
        if not self._free:
            self._grow_slots()
        slot = self._free.pop()
        handle = self._next_handle
        self._next_handle += 1
        self._slot_of[handle] = slot
        return handle, slot

    # -- interface -----------------------------------------------------

    def root(self, active: np.ndarray) -> int:  # waffle-lint: disable=WL003(writes a freshly allocated slot; a recycled handle was dropped in free)
        handle, slot = self._alloc()
        act = np.zeros(self._R, dtype=bool)
        act[: len(active)] = active
        self._state, stats = _j_root(
            self._state, self._reads, self._rlen, np.int32(slot), act,
            self._A,
        )
        #: un-fetched device stats; consumed by the engine's immediate
        #: ``stats()`` call without a second dispatch
        self._root_stats = (handle, stats)
        self._off_host[slot] = 0
        self._act_host[slot] = act
        return handle

    def clone(self, h: int) -> int:  # waffle-lint: disable=WL003(dst is a freshly allocated slot; src state is only read)
        self.counters["clone_calls"] += 1
        src = self._slot_of[h]
        handle, dst = self._alloc()
        self._state = _j_clone(self._state, np.int32(src), np.int32(dst))
        self._off_host[dst] = self._off_host[src]
        self._act_host[dst] = self._act_host[src]
        return handle

    def clone_many(self, hs: List[int]) -> List[int]:  # waffle-lint: disable=WL003(dsts are freshly allocated slots; src states are only read)
        """One fused scatter-copy for a batch of branch clones."""
        if not hs:
            return []
        self.counters["clone_calls"] += 1
        srcs = [self._slot_of[h] for h in hs]
        alloc = [self._alloc() for _ in hs]
        handles = [a[0] for a in alloc]
        dsts = [a[1] for a in alloc]
        npad = _next_pow2(len(hs))
        srcs += [srcs[0]] * (npad - len(hs))
        dsts += [dsts[0]] * (npad - len(hs))
        self._state = _j_clone_batch(
            self._state, np.asarray([srcs, dsts], dtype=np.int32)
        )
        n = len(hs)
        self._off_host[dsts[:n]] = self._off_host[srcs[:n]]
        self._act_host[dsts[:n]] = self._act_host[srcs[:n]]
        return handles

    def free(self, h: int) -> None:
        self._spec_drop(h)
        slot = self._slot_of.pop(h, None)
        if slot is not None:
            self._free.append(slot)

    def _spec_drop(self, h: Optional[int] = None) -> None:
        """Invalidate pending frontier-gang deposits: for one handle
        when its slot is about to mutate outside the speculated run
        (push / activate / arena / free), or for everything on a
        geometry change (the held post-state rows are old-geometry)."""
        gang = self._frontier_gang
        if gang is not None:
            if h is None:
                gang.drop_all()
            else:
                gang.drop(h)

    def _invalidate_root_stats(self) -> None:
        """The bundled root snapshot is only valid while the branch is
        untouched; any state evolution drops it (the engines consume it
        immediately after ``root``, so this never costs a re-dispatch in
        practice)."""
        self._root_stats = None

    def push(self, h: int, consensus: bytes) -> BranchStats:
        return self.push_many([(h, consensus)])[0]

    def push_many(
        self, specs: List[Tuple[int, bytes]]
    ) -> List[BranchStats]:
        """One fused device dispatch advancing every listed branch by its
        appended symbol (vmapped over branch slots)."""
        if not specs:
            return []
        self._invalidate_root_stats()
        self.counters["push_calls"] += 1
        self.counters["push_branches"] += len(specs)
        if self._frontier_gang is not None:
            for h, _c in specs:
                self._spec_drop(h)
        for _, consensus in specs:
            while len(consensus) >= self._C - 1:
                self._grow_cons()
        n = len(specs)
        npad = _next_pow2(n)
        slots = [self._slot_of[h] for h, _ in specs]
        if len(set(slots)) != n:
            # duplicate slots in one scatter batch would make the committed
            # row depend on scatter ordering; the engines never do this
            # (children are distinct clones), so treat it as a caller bug
            raise ValueError("push_many: duplicate branch handles in batch")
        syms = [self.sym_id[consensus[-1]] for _, consensus in specs]
        slots += [slots[0]] * (npad - n)
        syms += [syms[0]] * (npad - n)
        packed = np.asarray([slots, syms], dtype=np.int32)
        while True:
            _note_compile("j_push_batch", (
                self._B, self._R, self._W, self._C, self._A, npad,
            ))
            state, stats, overflow = _j_push_batch(
                self._state, self._reads, self._rlen, packed,
                self._wc, self._et, self._A,
            )
            self._state = state
            with _obs_span("device_get:push_many", "device-sync"):
                stats_np, ovf = jax.device_get((stats, overflow))
            if bool(ovf):
                self._grow_e()
                continue
            return self._stats_rows(stats_np, n)

    def clone_push_many(self, specs):
        """Fused expansion (see ``_j_clone_push_batch``): ``specs`` is a
        list of ``(src_handle, consensus_or_None, in_place)`` — clone
        ``src`` (or reuse its slot when ``in_place``) and, when a
        consensus is given, advance the copy by its last symbol.
        Returns ``[(handle, stats_or_None), ...]`` in spec order."""
        if not specs:
            return []
        self._invalidate_root_stats()
        self.counters["clone_push_calls"] = (
            self.counters.get("clone_push_calls", 0) + 1
        )
        for _src, consensus, _inp in specs:
            if consensus is not None:
                while len(consensus) >= self._C - 1:
                    self._grow_cons()
        n = len(specs)
        srcs = []
        dsts = []
        syms = []
        handles = []
        for src_h, consensus, in_place in specs:
            src = self._slot_of[src_h]
            if in_place:
                self._spec_drop(src_h)
                handles.append(src_h)
                dst = src
            else:
                handle, dst = self._alloc()
                handles.append(handle)
            srcs.append(src)
            dsts.append(dst)
            syms.append(
                -1 if consensus is None else self.sym_id[consensus[-1]]
            )
            self._off_host[dst] = self._off_host[src]
            self._act_host[dst] = self._act_host[src]
        if len(set(dsts)) != n:
            raise ValueError("clone_push_many: duplicate destination slots")
        npad = _next_pow2(n)
        srcs += [srcs[0]] * (npad - n)
        dsts += [dsts[0]] * (npad - n)
        syms += [syms[0]] * (npad - n)
        rows = np.asarray([srcs, dsts, syms], dtype=np.int32)
        while True:
            state, stats, overflow = _j_clone_push_batch(
                self._state, self._reads, self._rlen, rows,
                self._wc, self._et, self._A,
            )
            self._state = state
            with _obs_span("device_get:clone_push_many", "device-sync"):
                stats_np, ovf = jax.device_get((stats, overflow))
            if bool(ovf):
                self._grow_e()
                continue
            rows_out = self._stats_rows(stats_np, n)
            return [
                (h, rows_out[i] if specs[i][1] is not None else None)
                for i, h in enumerate(handles)
            ]

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        # the bundled snapshot from root() is only valid for the empty
        # consensus; a push on the handle invalidates it, but guard the
        # consensus length too so stats(h, non_empty) can never be served
        # the root snapshot
        cached = getattr(self, "_root_stats", None)
        if cached is not None and cached[0] == h and len(consensus) == 0:
            self._root_stats = None
            return self._stats_np(jax.device_get(cached[1]))
        self.counters["stats_calls"] += 1
        slot = self._slot_of[h]
        with _obs_span("device_get:stats", "device-sync"):
            return self._stats_np(
                jax.device_get(
                    _j_stats(
                        self._state, self._reads, self._rlen, np.int32(slot),
                        self._A,
                    )
                )
            )

    def activate(
        self, h: int, read_index: int, offset: int, consensus: bytes
    ) -> None:
        self._invalidate_root_stats()
        self.counters["activate_calls"] += 1
        self._spec_drop(h)
        slot = self._slot_of[h]
        self._off_host[slot, read_index] = offset
        self._act_host[slot, read_index] = True
        params = np.asarray([slot, read_index, offset], dtype=np.int32)
        while True:
            state, overflow = _j_activate(
                self._state, self._reads, self._rlen, params,
                self._wc, self._et,
            )
            self._state = state
            if bool(overflow):
                self._grow_e()
                continue
            return

    def deactivate(self, h: int, read_index: int) -> None:
        self._invalidate_root_stats()
        self._spec_drop(h)
        slot = self._slot_of[h]
        self._act_host[slot, read_index] = False
        self._state = _j_deactivate(
            self._state, np.int32(slot), np.int32(read_index)
        )

    def deactivate_many(self, pairs) -> None:
        if not pairs:
            return
        self._invalidate_root_stats()
        if self._frontier_gang is not None:
            for h, _r in pairs:
                self._spec_drop(h)
        npad = _next_pow2(len(pairs))
        hs = [self._slot_of[h] for h, _ in pairs]
        ridx = [r for _, r in pairs]
        self._act_host[hs, ridx] = False
        hs += [hs[0]] * (npad - len(pairs))
        ridx += [ridx[0]] * (npad - len(pairs))
        self._state = _j_deactivate_batch(
            self._state, np.asarray([hs, ridx], dtype=np.int32)
        )

    def _pallas_ms(self, max_steps: int) -> int:
        """SMEM symbol-buffer bucket for a dispatch of ``max_steps``
        (the pure half of :meth:`_pallas_prep`, shared so eligibility
        and setup agree on the kernel-variant key)."""
        return _next_pow2(min(max_steps, _PALLAS_MS_CAP - 2) + 2, 256)

    def _pallas_geom(self, sides: int, ms: int):
        """Kernel-variant bucket for the per-geometry disable map:
        one Mosaic compile failure only disqualifies the (sides, band
        width, symbol-buffer size, tile dtype) combination that
        actually failed."""
        return (sides, self._W, ms, self._pallas_i16())

    def _pallas_ok(self, sides: int = 1, ms: int = 0) -> bool:
        """Fused-kernel eligibility: mode on (and that kernel VARIANT —
        see :meth:`_pallas_geom` — not individually disabled by an
        earlier compile failure) + the whole staging fits the VMEM
        budget at current geometry (with the tile dtype the dispatch
        would actually use) + the occ output rows cover the alphabet
        (the kernel emits a fixed 8-row occ block) + the scorer is
        unsharded (pallas_call cannot partition GSPMD-sharded operands;
        the mesh path keeps the XLA while-loop kernels)."""
        if self._pallas_mode == "off" or self._A > 8:
            return False
        if not self._pallas_kernel_ok.get(self._pallas_geom(sides, ms), True):
            return False
        if self._shardings is not None:
            return False
        from waffle_con_tpu.ops.pallas_run import fits_budget

        return fits_budget(
            self._reads_T_rows(), self._R, self._W, self._C, sides,
            self._pallas_i16(),
        )

    def _pallas_i16(self) -> bool:
        from waffle_con_tpu.ops.pallas_run import i16_ok

        return (
            i16_ok(self._L, self._C, self._W)
            and envspec.get_raw("WAFFLE_PALLAS_I16", "1") != "0"
        )

    def _xla_i16(self, mega: bool = False) -> bool:
        """int16 band-state narrowing for the XLA while-loop run kernels
        (mirrors the pallas ``i16`` flag): on by default only where the
        narrower tile wins — TPU, where the ``[R, W]`` loop is
        memory-bound.  CPU XLA lowers the int16 column math slower than
        int32 at small band widths, so it stays off there unless forced
        for parity testing via ``WAFFLE_XLA_I16=1``.  The narrowed path
        is value-exact whenever the :func:`_xla_i16_ok` geometry bound
        holds.

        ``mega`` dispatches additionally opt in on ANY backend once the
        band is wide enough that the ``[R, W]`` traffic is memory-bound
        (measured on XLA:CPU at the north-star geometry, W=434: 878 ->
        1025 steps/s; the small-W fixtures where int16 lowering loses
        sit far below :data:`_MEGA_I16_MIN_W`)."""
        env = envspec.get_raw("WAFFLE_XLA_I16")
        if env == "0":
            return False
        if not _xla_i16_ok(self._L, self._C, self._W):
            return False
        if env == "1" or jax.default_backend() == "tpu":
            return True
        return mega and self._W >= _MEGA_I16_MIN_W

    def _pallas_prep(self, longest: int, max_steps: int):
        """Shared pallas dispatch setup: bucket the SMEM symbol-buffer
        size, cap the per-dispatch steps (a capped run stops with code
        4 and the engine re-engages), grow the consensus axis to fit,
        and resolve the DP-tile dtype.  Returns (MS, capped_steps,
        i16)."""
        MS = self._pallas_ms(max_steps)
        while longest + MS + 2 >= self._C:
            self._grow_cons()
        return MS, min(max_steps, MS - 2), self._pallas_i16()

    def _pallas_guarded(self, sides: int, ms: int, fn, *args):
        """Run a fused-kernel wrapper, bumping its engagement counter;
        a Mosaic lowering/compile failure must never take the engine
        down, so on exception the ONE failing kernel VARIANT (its
        ``_pallas_geom`` bucket) is disabled for this scorer and
        ``None`` signals the caller to fall back to the XLA while-loop
        path.  The result is synced with ``block_until_ready`` INSIDE
        the guard: a dispatch that fails asynchronously on device must
        surface here, where the fallback still exists, not at a later
        unrelated ``device_get``.  The one unrecoverable case — the
        failed dispatch already consumed the donated state buffers — is
        re-raised with intact context so the supervisor's retry/demote
        machinery handles it instead of a confusing deferred crash."""
        key = "run_pallas_calls" if sides == 1 else "run_dual_pallas_calls"
        geom = self._pallas_geom(sides, ms)
        try:
            from waffle_con_tpu.runtime import faults

            faults.check_pallas(sides)
            out = fn(*args)
            jax.block_until_ready(out)
        except Exception:
            logger.warning(
                "pallas kernel (sides=%d, geom=%s) failed; falling back "
                "to the XLA path", sides, geom, exc_info=True,
            )
            self._pallas_kernel_ok[geom] = False
            from waffle_con_tpu.runtime import events

            events.record("pallas_kernel_disabled", sides=sides, geom=geom)
            state_lost = any(
                getattr(leaf, "is_deleted", lambda: False)()
                for leaf in jax.tree_util.tree_leaves(self._state)
            )
            if state_lost:
                raise
            return None
        self.counters[key] = self.counters.get(key, 0) + 1
        return out

    def _reads_T_rows(self) -> int:
        from waffle_con_tpu.ops.pallas_run import staging_rows

        return staging_rows(self._max_rlen, self._W)

    def _reads_T(self):
        """Lazily staged transposed reads for the pallas kernel."""
        if self._reads_T_cache is None:
            self._reads_T_cache = _j_mkpad_T(
                self._reads, W=self._W, rows=self._reads_T_rows()
            )
        return self._reads_T_cache

    def _uniform_off(self, slot: int) -> Tuple[bool, int]:
        """(is_uniform, off0) for a slot's ACTIVE reads, from the host
        mirrors — decides the run kernels' dynamic-slice fast path."""
        offs = self._off_host[slot][self._act_host[slot]]
        if offs.size == 0:
            return True, 0
        off0 = int(offs[0])
        return bool((offs == off0).all()), off0

    def _geom_bucket(self) -> str:
        """Geometry label for phase profiling: band count x reads x
        band width — coarse enough to bucket, fine enough to separate
        the north-star geometry from small fixtures."""
        return f"B{self._B}R{self._R}W{self._W}"

    def ragged_run_probe(self, h: int):
        """Duck-typed hop for the serve layer's ragged dispatch: return
        ``(self, handle)`` when this scorer can in principle join a
        cross-job ragged gang for ``h`` (the arena still checks geometry
        eligibility against the live call args).  Proxies without this
        attribute — python backend, subset scorer — are simply never
        ragged-batched."""
        from waffle_con_tpu.ops import ragged as _ragged

        if not _ragged.enabled() or h not in self._slot_of:
            return None
        return (self, h)

    def ragged_release(self) -> None:
        """Release this scorer's paged-arena residency (no-op when not
        resident); the supervisor calls it before swapping backends so a
        demoted scorer's pages free immediately and its pending
        injections drop."""
        from waffle_con_tpu.ops import ragged as _ragged

        _ragged.release_scorer(self)

    def _spec_consume(  # waffle-lint: disable=WL003(the deposit-consumption seam itself: pops its own deposit by construction)
        self, inj, h: int, consensus: bytes, me_budget: int,
        other_cost: int, other_len: int, min_count: int, l2: bool,
        max_steps: int, first_sym: int,
    ) -> bool:
        """Validate a speculative frontier-gang deposit against the
        REAL ``run_extend`` arguments; on success scatter its held
        post-state into the slot and return True.

        Soundness: inside the run kernel only the vote decisions —
        pure functions of band state and the search constants
        (min_count / l2 / wildcard / early-termination) — choose WHAT
        commits; the per-call arguments (budget, competing-pop
        priority, step limit) only decide WHERE the run stops.
        Stopping EARLIER than the real call would have is always exact
        (the engine simply re-pops and continues), so consumption only
        has to prove the real call would have committed at least
        ``inj.steps`` columns:

        * the forced step-0 commit is argument-independent (only band
          overflow refuses it, and overflow is pure state), so a
          forced deposit needs in-run checks only for commits past it;
        * when the speculated (budget, other_cost, other_len) EQUAL
          the real call's, every stop decision the kernel made is the
          decision the real call would make — the whole run is exact
          verbatim (the dominant case: the in-hand member always
          carries real arguments, and near-tie peers usually predict
          the competing priority exactly);
        * otherwise every later commit passed ``total <= me_budget``
          and the wins predicate at its state; totals are nondecreasing
          over a run, so ``final_cost <= me_budget`` and the wins
          predicate evaluated at ``(final_cost, len0 + a)`` bound every
          intermediate check the real call would have made.  (The
          FINAL state need not win — stopping on a lost pop is the
          normal case — which is why the bound applies to the gating
          states, conservatively.)

        ``allow_records`` needs no gate: the ragged kernel stops at
        reached states (records force-disabled), which is a
        conservative early stop under a record-absorbing real call —
        the same argument the serving-path injections rely on."""
        if inj.len0 != len(consensus):
            return False
        if inj.first_sym != int(first_sym):
            return False
        if inj.min_count != int(min_count) or inj.l2 != bool(l2):
            return False
        if inj.steps > int(max_steps):
            return False
        a = 1 if inj.first_sym >= 0 else 0
        if inj.steps > a:
            me = min(int(me_budget), 2**31 - 1)
            oc = min(int(other_cost), 2**31 - 1)
            args_equal = (
                inj.other_cost == oc
                and inj.other_len == int(other_len)
                # unequal budgets are still exact when every state fit
                # the real one (budgets only shrink, so this is the
                # common drift) — the win decisions were identical
                and (inj.me_budget == me or inj.final_cost <= me)
            )
            if not args_equal:
                if inj.final_cost > me:
                    return False
                if not (
                    inj.final_cost < oc
                    or (inj.final_cost == oc and inj.len0 + a > int(other_len))
                ):
                    return False
        slot = self._slot_of[h]
        D, e, rmin, er, cons, clen = inj.post
        _note_compile("j_slot_put", tuple(
            self._state[k].shape for k in
            ("D", "e", "rmin", "er", "cons", "clen")
        ))
        self._state = _j_slot_put(
            self._state, np.int32(slot), D, e, rmin, er, cons,
            np.int32(clen),
        )
        return True

    def run_extend(
        self,
        h: int,
        consensus: bytes,
        me_budget: int,
        other_cost: int,
        other_len: int,
        min_count: int,
        l2: bool,
        max_steps: int,
        first_sym: int = -1,
        allow_records: bool = True,
        mega: bool = False,
    ) -> Tuple[int, int, bytes, BranchStats, list]:
        """Device-side unambiguous-run extension; returns
        ``(steps_committed, stop_code, appended_bytes, stats, records)``
        with ``stats`` the branch snapshot at the stopped position, its
        ``fin`` field carrying the finalized per-read distances there
        (``None`` when the band cannot express them), and ``records``
        the absorbed reached-state snapshots ``[(step, fin_eds), ...]``
        in commit order (see ``_j_run``) for the engine to replay.
        ``first_sym`` (a dense id, or -1) force-pushes the host's
        already-nominated unique child as step 0.  See ``_j_run`` for
        the stop-code contract; on overflow the band is grown so the
        caller can simply continue stepping.

        ``mega`` selects the MEGASTEP dispatch (normally reached via
        :attr:`run_mega`): the ``_j_run_mega`` kernel (M blocks of K
        columns per loop iteration, wide-band int16 admission),
        ``max_steps`` capped by the ``WAFFLE_MEGA_SYMS`` dispatch
        budget, and ONE bundled result transfer — control scalars,
        commit trail, and the stats snapshot cross the device boundary
        together instead of control-now/stats-deferred, so a megastep
        pop pays a single host round trip.  Results are bit-identical
        to the plain path."""
        from waffle_con_tpu.ops import ragged as _ragged

        inj = _ragged.take_injected(self, h)
        if inj is not None and getattr(inj, "speculative", False):
            # frontier-gang deposit: the slot was NOT advanced at gang
            # time — validate the speculated call against the real
            # arguments and scatter the held post-state only on a
            # match.  A mismatch discards the deposit; the slot still
            # holds the pristine pre-gang state, so the solo run below
            # is trivially exact.
            if self._spec_consume(
                inj, h, consensus, me_budget, other_cost, other_len,
                min_count, l2, max_steps, first_sym,
            ):
                key = "run_gang_injected"
                self.counters[key] = self.counters.get(key, 0) + 1
                from waffle_con_tpu.obs import metrics as _obs_metrics

                if _obs_metrics.metrics_enabled():
                    _obs_metrics.registry().counter(
                        "waffle_frontier_gang_commits_total"
                    ).inc()
            else:
                key = "run_gang_mispredict"
                self.counters[key] = self.counters.get(key, 0) + 1
                inj = None
        if inj is not None:
            # this exact call was precomputed by a ragged gang step (see
            # ops.ragged.BandArena.run_group / FrontierGang.run): the
            # state is (now) advanced in our slot — return the deposited
            # result through the normal contract so supervision/
            # validation/tracing all see an ordinary run_extend
            if inj.len0 != len(consensus):  # pragma: no cover - guard
                raise RuntimeError(
                    "ragged injection desynchronized: precomputed at "
                    f"consensus length {inj.len0}, called at "
                    f"{len(consensus)}"
                )
            self._invalidate_root_stats()
            rec = _phases.current()
            if rec is not None:
                # device work already happened inside the ragged gang's
                # own record; consuming the deposit is pure host time
                rec.annotate(kernel="ragged", k=1,
                             geom=self._geom_bucket())
            steps, code = inj.steps, inj.code
            self.counters["run_calls"] += 1
            self.counters["run_steps"] += steps
            self.counters["run_iters"] += inj.iters
            self.counters["run_spec_cols"] += inj.iters  # ragged is K=1
            key = f"run_stop_{code}"
            self.counters[key] = self.counters.get(key, 0) + 1
            self.counters["run_ragged_injected"] = (
                self.counters.get("run_ragged_injected", 0) + 1
            )
            appended = b""
            if steps:
                appended = (
                    self.symtab[inj.ids[:steps]].astype(np.uint8).tobytes()
                )
            if code == 5:
                # grow + in-pool re-center: the next probe gangs again
                # at the doubled per-row stride (only a width outgrowing
                # the pool evicts)
                self._grow_e()
            return steps, code, appended, self._stats_np(inj.stats), []
        self._invalidate_root_stats()
        rec = _phases.current()
        slot = self._slot_of[h]
        if mega:
            # the dispatch budget: stopping earlier is always exact (the
            # capped run stops with code 4 and the engine re-engages)
            max_steps = min(max_steps, _mega_syms())
        while len(consensus) + max_steps + 2 >= self._C:
            self._grow_cons()
        uniform, off0 = self._uniform_off(slot)
        # mega IS the XLA megastep: configs where the fused pallas
        # kernel applies keep it by running plain (WAFFLE_MEGASTEP=0)
        use_pallas = (not mega) and uniform and self._pallas_ok(
            sides=1, ms=self._pallas_ms(max_steps)
        )
        if use_pallas:
            MS, max_steps, i16 = self._pallas_prep(
                len(consensus), max_steps
            )
        params = np.asarray(
            [
                slot,
                min(me_budget, 2**31 - 1),
                min(other_cost, 2**31 - 1),
                other_len,
                min_count,
                int(l2),
                max_steps,
                off0,
                first_sym,
                int(allow_records),
            ],
            dtype=np.int32,
        )
        if use_pallas:
            from waffle_con_tpu.ops.pallas_run import _j_run_pallas

            _note_compile(
                "j_run_pallas", (self._B, self._R, self._W, MS, i16)
            )
            with _phases.device_scope(rec):
                # _pallas_guarded block_until_readys internally, so the
                # scope's elapsed time is real kernel time
                out = self._pallas_guarded(
                    1, MS, _j_run_pallas,
                    self._state, self._reads_T(), self._rlen, params,
                    self._wc, self._et, self._A, self.num_symbols, MS,
                    i16, self._pallas_mode == "interpret",
                )
            if out is None:
                use_pallas = False
            else:
                (state, steps, code, stats, cons_row, fin_eds, fin_ovf,
                 rec_count, rec_steps, rec_fins) = out
                iters, cols = steps, 1  # fused kernel: one col per iter
        if not use_pallas:
            cols = _run_cols()
            blocks = _mega_blocks() if mega else 1
            i16 = self._xla_i16(mega=mega)
            if mega:
                _note_compile("j_run_mega", (
                    self._B, self._R, self._W, self._C, self._L,
                    self._A, uniform, self.num_symbols, i16, cols,
                    blocks,
                ))
                run_fn = partial(_j_run_mega, blocks=blocks)
            else:
                _note_compile("j_run", (
                    self._B, self._R, self._W, self._C, self._L,
                    self._A, uniform, self.num_symbols, i16, cols,
                ))
                run_fn = _j_run
            with _phases.device_scope(rec):
                out_dev = run_fn(
                    self._state, self._reads, self._reads_pad,
                    self._rlen, params, self._wc, self._et, self._A,
                    uniform, a_real=self.num_symbols,
                    i16=i16, cols=cols,
                )
                if rec is not None:
                    # profiling fences the async dispatch so device
                    # time separates from the device_get below; an
                    # unprofiled run never blocks early
                    out_dev = jax.block_until_ready(out_dev)
            (state, steps, code, stats, cons_row, fin_eds, fin_ovf,
             rec_count, rec_steps, rec_fins, iters) = out_dev
        if rec is not None:
            rec.annotate(
                kernel="mega" if mega else
                ("pallas" if use_pallas else "solo"),
                k=int(cols) * (int(blocks) if mega else 1),
                geom=self._geom_bucket(),
            )
        self._state = state
        defer = deferred_sync_enabled() and not mega
        with _obs_span("device_get:run_extend", "device-sync"), \
                _phases.transfer_scope(rec):
            # async dispatch seam: only the CONTROL results the engine's
            # bookkeeping needs right now cross the device boundary here;
            # the bulk observation arrays ride a DeferredStats and are
            # fetched when the branch is next popped — the bookkeeping
            # for this run (and the dispatch of the next) overlaps the
            # outstanding transfer (see ops.scorer.DeferredStats).  A
            # MEGA dispatch instead bundles the stats snapshot into this
            # one transfer: its dispatches are long enough that overlap
            # is moot, and the bundle makes the common (record-free) pop
            # cost exactly ONE host round trip.
            stats_parts = (stats, fin_eds, fin_ovf)
            if mega:
                (steps, code, cons_np, rec_count, iters,
                 stats_parts) = jax.device_get(
                    (steps, code, cons_row, rec_count, iters,
                     stats_parts)
                )
            else:
                (steps, code, cons_np, rec_count, iters) = jax.device_get(
                    (steps, code, cons_row, rec_count, iters)
                )
            self.counters["host_round_trips"] += 1
            # the record buffers only ride home when something was
            # absorbed (most run calls have none, and every fetched byte
            # costs tunnel round-trip time)
            if int(rec_count):
                rec_steps_np, rec_fins_np = jax.device_get(
                    (rec_steps, rec_fins)
                )
                self.counters["host_round_trips"] += 1
            if not defer and not mega:
                stats_parts = jax.device_get(stats_parts)
                self.counters["host_round_trips"] += 1
        steps = int(steps)
        code = int(code)
        self.counters["run_calls"] += 1
        self.counters["run_steps"] += steps
        self.counters["run_iters"] += int(iters)
        self.counters["run_spec_cols"] += (
            int(iters) * cols * (int(blocks) if mega else 1)
        )
        if mega:
            self.counters["run_mega_calls"] += 1
            self.counters["run_mega_steps"] += steps
        key = f"run_stop_{code}"
        self.counters[key] = self.counters.get(key, 0) + 1
        appended = b""
        if steps:
            ids = cons_np[len(consensus) : len(consensus) + steps]
            appended = self.symtab[ids].astype(np.uint8).tobytes()
        if code == 5:
            self._grow_e()
        n = self.num_reads
        records = [
            (int(rec_steps_np[i]), rec_fins_np[i, :n].astype(np.int64))
            for i in range(int(rec_count))
        ]  # rec_count == 0 -> empty without touching the buffers

        def build_stats(parts):
            s4, fin_np, fovf = parts[0], parts[1], parts[2]
            return self._stats_np(
                tuple(s4) + (fin_np, np.logical_not(fovf))
            )

        if defer:
            def _resolve():
                # the deferred fetch is still a blocking sync when it
                # lands — count it where it happens so host_round_trips
                # reflects what the process actually paid
                self.counters["host_round_trips"] += 1
                return build_stats(jax.device_get(stats_parts))

            out_stats: BranchStats = DeferredStats(_resolve)
        else:
            out_stats = build_stats(stats_parts)
        return steps, code, appended, out_stats, records

    @property
    def run_mega(self):
        """MEGASTEP fast path, or ``None`` when ``WAFFLE_MEGASTEP=0``.

        Same call contract as :meth:`run_extend`; dispatches
        ``_j_run_mega`` (M blocks of K columns per device loop
        iteration), caps the dispatch at the ``WAFFLE_MEGA_SYMS``
        budget, and returns everything in one bundled transfer.  The
        property gate (rather than an always-present method) lets the
        ``fast_paths`` snapshot / SubsetScorer / supervisor capability
        machinery treat it exactly like the other optional kernels —
        engines prefer it when present and spill to plain stepping
        otherwise.  Bit-identical to the plain path by construction."""
        if not megastep_enabled():
            return None
        return self._run_mega_call

    def _run_mega_call(
        self,
        h: int,
        consensus: bytes,
        me_budget: int,
        other_cost: int,
        other_len: int,
        min_count: int,
        l2: bool,
        max_steps: int,
        first_sym: int = -1,
        allow_records: bool = True,
    ) -> Tuple[int, int, bytes, BranchStats, list]:
        return self.run_extend(
            h, consensus, me_budget, other_cost, other_len, min_count,
            l2, max_steps, first_sym=first_sym,
            allow_records=allow_records, mega=True,
        )

    def run_extend_dual(
        self,
        h1: int,
        h2: int,
        consensus1: bytes,
        consensus2: bytes,
        me_budget: int,
        other_cost: int,
        other_len: int,
        min_count: int,
        ed_delta: int,
        imb_min: int,
        l2: bool,
        weighted: bool,
        max_steps: int,
        lock1: bool = False,
        lock2: bool = False,
        allow_records: bool = True,
        rec_min: int | None = None,
        mc_tab: np.ndarray | None = None,
        imb_tab: np.ndarray | None = None,
        mc_dyn: bool = False,
    ):
        """Device-side dual-node extension (both branches step together,
        with on-device divergence pruning); returns ``(steps, stop_code,
        appended1, appended2, stats1, stats2, active1, active2,
        records)`` with ``records`` the absorbed reached-state snapshots
        ``[(step, fin1, fin2, act1, act2), ...]`` in commit order for
        the engine to replay (cf. ``_j_run``'s record absorption).  See
        ``_j_run_dual`` for the stop-code contract (including the
        one-side-locked mode).  Caller preconditions: at most one side
        locked; with ``min_af != 0`` the caller must supply ``mc_tab`` /
        ``imb_tab`` (see ``_j_run_dual``) — when omitted both default to
        constant ``min_count`` / ``imb_min`` tables (the ``min_af == 0``
        semantics)."""
        self._invalidate_root_stats()
        self._spec_drop(h1)
        self._spec_drop(h2)
        rec = _phases.current()
        s1 = self._slot_of[h1]
        s2 = self._slot_of[h2]
        need = max(len(consensus1), len(consensus2)) + max_steps + 2
        while need >= self._C:
            self._grow_cons()
        uni1, off0a = self._uniform_off(s1)
        uni2, off0b = self._uniform_off(s2)
        if mc_tab is None:
            mc_tab = np.full(self._R + 1, min_count, dtype=np.int32)
        # pad to the scorer's read capacity: every distinct engine/group
        # size would otherwise retrace the kernel (the index is clipped
        # and a vote total never exceeds the group's read count)
        mc_tab = self._pad_len_table(mc_tab, self._R + 1)
        if imb_tab is None:
            imb_tab = np.full(8, imb_min, dtype=np.int32)
        imb_tab = self._pad_len_table(
            imb_tab, max(len(consensus1), len(consensus2)) + max_steps + 2
        )
        params = np.asarray(
            [
                s1,
                s2,
                min(me_budget, 2**31 - 1),
                min(other_cost, 2**31 - 1),
                other_len,
                min_count,
                ed_delta,
                imb_min,
                int(l2),
                int(weighted),
                max_steps,
                off0a,
                off0b,
                int(lock1),
                int(lock2),
                int(allow_records),
                min_count if rec_min is None else rec_min,
                int(mc_dyn),
            ],
            dtype=np.int32,
        )
        use_pallas = (uni1 and uni2) and self._pallas_ok(
            sides=2, ms=self._pallas_ms(max_steps)
        )
        if use_pallas:
            from waffle_con_tpu.ops.pallas_run import _j_run_dual_pallas

            MS, capped, i16 = self._pallas_prep(
                max(len(consensus1), len(consensus2)), max_steps
            )
            params[10] = capped
            _note_compile(
                "j_run_dual_pallas", (self._B, self._R, self._W, MS, i16)
            )
            with _phases.device_scope(rec):
                out = self._pallas_guarded(
                    2, MS, _j_run_dual_pallas,
                    self._state, self._reads_T(), self._rlen, params,
                    np.ascontiguousarray(mc_tab, dtype=np.int32),
                    imb_tab, self._wc, self._et, self._A,
                    self.num_symbols, MS, i16,
                    self._pallas_mode == "interpret",
                )
            if out is None:
                use_pallas = False
            else:
                (state, steps, code, stats1, stats2, act1, act2, consa,
                 consb, rec_count, rec_steps, rec_f1, rec_f2, rec_a1,
                 rec_a2) = out
                iters, cols = steps, 1  # fused kernel: one col per iter
        if not use_pallas:
            cols = _run_cols()
            # the dual twin rides the same megastep composition: M
            # blocks per iteration and wide-band int16, env-gated here
            # because the engines' dual call site has no separate mega
            # entry (the kernel change is blocks>1, nothing else)
            mega = megastep_enabled()
            blocks = _mega_blocks() if mega else 1
            i16 = self._xla_i16(mega=mega)
            _note_compile("j_run_dual", (
                self._B, self._R, self._W, self._C, self._L, self._A,
                uni1 and uni2, self.num_symbols, i16, cols, blocks,
            ))
            with _phases.device_scope(rec):
                out_dev = _j_run_dual(
                    self._state, self._reads, self._reads_pad,
                    self._rlen, params,
                    np.ascontiguousarray(mc_tab, dtype=np.int32),
                    imb_tab, self._wc, self._et, self._A, uni1 and uni2,
                    a_real=self.num_symbols, i16=i16,
                    cols=cols, blocks=blocks,
                )
                if rec is not None:
                    # profiling fences the async dispatch (see
                    # run_extend)
                    out_dev = jax.block_until_ready(out_dev)
            (state, steps, code, stats1, stats2, act1, act2, consa,
             consb, rec_count, rec_steps, rec_f1, rec_f2, rec_a1,
             rec_a2, iters) = out_dev
        else:
            mega, blocks = False, 1
        if rec is not None:
            rec.annotate(
                kernel="pallas" if use_pallas else
                ("mega" if mega else "dual"),
                k=int(cols) * int(blocks), geom=self._geom_bucket(),
            )
        self._state = state
        defer = deferred_sync_enabled()
        with _obs_span("device_get:run_extend_dual", "device-sync"), \
                _phases.transfer_scope(rec):
            # async dispatch seam (see run_extend): control results now,
            # per-side observation arrays deferred.  The act masks are
            # control — the host act mirror must update before the next
            # dispatch touches these branches.
            (steps, code, act1_np, act2_np,
             consa_np, consb_np, rec_count, iters) = jax.device_get(
                (steps, code, act1, act2, consa, consb,
                 rec_count, iters)
            )
            self.counters["host_round_trips"] += 1
            if int(rec_count):
                (rec_steps_np, rec_f1_np, rec_f2_np, rec_a1_np,
                 rec_a2_np) = jax.device_get(
                    (rec_steps, rec_f1, rec_f2, rec_a1, rec_a2)
                )
                self.counters["host_round_trips"] += 1
            if not defer:
                stats1, stats2 = jax.device_get((stats1, stats2))
                self.counters["host_round_trips"] += 1
        steps = int(steps)
        code = int(code)
        self.counters["run_dual_calls"] += 1
        self.counters["run_dual_steps"] += steps
        self.counters["run_dual_iters"] += int(iters)
        self.counters["run_dual_spec_cols"] += int(iters) * cols * blocks
        if mega:
            self.counters["run_dual_mega_calls"] += 1
        key = f"run_dual_stop_{code}"
        self.counters[key] = self.counters.get(key, 0) + 1

        def appended(cons_np, consensus, locked):
            if not steps or locked:
                return b""
            ids = cons_np[len(consensus) : len(consensus) + steps]
            return self.symtab[ids].astype(np.uint8).tobytes()

        app1 = appended(consa_np, consensus1, lock1)
        app2 = appended(consb_np, consensus2, lock2)
        n = self.num_reads
        records = [
            (
                int(rec_steps_np[i]),
                rec_f1_np[i, :n].astype(np.int64),
                rec_f2_np[i, :n].astype(np.int64),
                rec_a1_np[i, :n],
                rec_a2_np[i, :n],
            )
            for i in range(int(rec_count))
        ]  # rec_count == 0 -> empty without touching the buffers
        # divergence pruning deactivates lanes on device; keep the host
        # act mirror exact or _uniform_off goes stale and silently drops
        # the dynamic-slice fast path for this branch and its clones
        self._act_host[s1] = act1_np
        self._act_host[s2] = act2_np
        if code == 5:
            self._grow_e()
        if defer:
            def _resolve_side(side_stats):
                def _resolve():
                    # count the landing sync (see run_extend)
                    self.counters["host_round_trips"] += 1
                    return self._stats_np(jax.device_get(side_stats))

                return _resolve

            out1: BranchStats = DeferredStats(_resolve_side(stats1))
            out2: BranchStats = DeferredStats(_resolve_side(stats2))
        else:
            out1 = self._stats_np(stats1)
            out2 = self._stats_np(stats2)
        return (
            steps,
            code,
            app1,
            app2,
            out1,
            out2,
            act1_np[:n],
            act2_np[:n],
            records,
        )

    #: fixed history capacity of the arena kernel (static shape: one
    #: compiled kernel per geometry, dynamic step_limit rides in params)
    #: ceiling for the arena history; the effective per-scorer cap
    #: (``ARENA_CAP`` property) scales with read length so small
    #: fixtures keep small compiled windows while 10kb workloads get
    #: long uninterrupted arena stretches
    ARENA_CAP_MAX = 2048

    @property
    def ARENA_CAP(self) -> int:
        # sized to the read length so one engagement can carry a search
        # through a full consensus-length stretch of events; history is
        # int16 so even the 2048 ceiling costs 4 KB (step-limit stops
        # were the top residual dispatch source at benchmark scale)
        return min(self.ARENA_CAP_MAX, max(512, _next_pow2(self._L)))
    #: node capacity of the arena kernel (static; dead-node padding).
    #: Sized for the live-chain count of tie-heavy dual searches; per-
    #: iteration compute scales with K but stays tiny for a TPU VPU
    ARENA_K = 64
    #: engines cap the competitors they take at this, reserving node
    #: slots for the creation pool — tie-heavy engagements otherwise
    #: fill the table (n_live ~ K) and every split stops pool-starved
    ARENA_TAKE_MAX = ARENA_K - 1 - 16
    #: engines consult this to decide whether a split-shaped expansion
    #: can engage the arena (0 would mean no on-device child creation)
    ARENA_CRE_PER_EVENT = CRE_PER_EVENT

    #: creation pool nodes offered per arena call (each owns two real
    #: state slots for the duration of the call; unconsumed pairs are
    #: returned to the free list afterwards).  Sized close to ARENA_K:
    #: pool exhaustion was the dominant residual stop once splits were
    #: absorbed (the n_live cap keeps the sum within the node table)
    ARENA_POOL = 36

    def run_arena(
        self,
        node_specs,        # [(h1, h2|None, len1, len2), ...] 1..ARENA_K
        me_budget: int,
        min_count: int,
        ed_delta: int,
        imb_min: int,
        l2: bool,
        weighted: bool,
        rest_cost: int,
        rest_len: int,
        max_queue_size: int,
        capacity_per_size: int,
        step_limit: int,
        max_nodes_wo_constraint: int,
        lc: np.ndarray,    # [2, Lw] per-kind queue length counts
        pc: np.ndarray,    # [2, Lw] per-kind processed counts
        tr_scalars: np.ndarray,  # [2, 4] (thr, total, farthest, last_constr)
        create_mode: int = 0,
        mc_tab: np.ndarray | None = None,
        imb_tab: np.ndarray | None = None,
        split_relax: bool = True,
        mc_dyn: bool = False,
    ):
        """K-node pop arena (see ``_j_arena``); node 0 must be the
        engine's in-hand pop, later nodes in their queue pop order.
        Returns ``(events, nsteps, code, stop_node, per_node_steps,
        per_side_appended, per_side_stats, per_side_act, alive,
        creations)`` with sides flattened as ``[n0s1, n0s2, n1s1, ...]``
        (side-2 entries of single nodes and all entries of unused
        padding nodes are None).  ``events`` is the committed history as
        ``("commit", node)`` / ``("discard", node)`` / ``("split",
        node)`` / ``("create", rec)`` tuples in pop order;
        ``alive[node]`` is False when the node died mid-arena (caller
        frees it and must not re-queue it).

        ``create_mode`` (see ``_j_arena``) enables on-device child
        creation: 1 = singles only, 2 = singles + split pairs + dual
        cross products.  ``creations[j]`` describes child node
        ``len(node_specs) + j`` as a dict with ``parent`` (node index —
        possibly itself a child), ``kind`` (0 single / 1 dual), ``sym1``
        / ``sym2`` (byte symbols; ``sym2`` None for singles),
        ``created_len`` (the child's length at creation, i.e. parent
        length at the split + 1), and fresh registered handles ``h1`` /
        ``h2`` (``h2`` None for singles)."""
        self._invalidate_root_stats()
        rec = _phases.current()
        K = self.ARENA_K
        n_live = len(node_specs)
        if not 1 <= n_live <= K:
            raise ValueError("arena takes 1..ARENA_K nodes")
        if self._frontier_gang is not None:
            for nh1, nh2, _nl1, _nl2 in node_specs:
                self._spec_drop(nh1)
                if nh2 is not None:
                    self._spec_drop(nh2)
        kinds = []
        slots = []
        live_sides = []
        self._scratch_reset()
        for h1, h2, _l1, _l2 in node_specs:
            kinds.append(1 if h2 is not None else 0)
            live_sides.append(len(slots))
            slots.append(self._slot_of[h1])
            if h2 is not None:
                live_sides.append(len(slots))
                slots.append(self._slot_of[h2])
            else:
                slots.append(self._scratch_slot())
        # creation pool: real allocated slot pairs the kernel may turn
        # into child nodes; the remainder of the node table is scratch
        n_pool = min(self.ARENA_POOL, K - n_live) if create_mode else 0
        pool_pairs = [
            (self._alloc(), self._alloc()) for _ in range(n_pool)
        ]
        for (h1p, s1p), (h2p, s2p) in pool_pairs:
            kinds.append(-1)
            slots.append(s1p)
            slots.append(s2p)
        for _ in range(K - n_live - n_pool):
            kinds.append(-1)
            slots.append(self._scratch_slot())
            slots.append(self._scratch_slot())
        if len(set(slots)) != 2 * K:
            raise ValueError("arena requires distinct state slots")
        # dynamic-slice window path: every LIVE side's active reads must
        # share one offset (scratch sides are garbage either way;
        # children inherit their parent's offset row on device)
        off0s = np.zeros(2 * K, dtype=np.int32)
        uniform = True
        for side in live_sides:
            uni, off0 = self._uniform_off(slots[side])
            uniform = uniform and uni
            off0s[side] = off0
        step_limit = min(step_limit, self.ARENA_CAP)
        max_len = max(max(s[2], s[3]) for s in node_specs)
        while max_len + step_limit + 2 >= self._C:
            self._grow_cons()
        if mc_tab is None:
            mc_tab = np.full(self._R + 1, min_count, dtype=np.int32)
        mc_tab = self._pad_len_table(mc_tab, self._R + 1)
        if imb_tab is None:
            imb_tab = np.full(8, imb_min, dtype=np.int32)
        imb_tab = self._pad_len_table(
            imb_tab, max_len + step_limit + 2
        )
        params = np.asarray(
            [
                min(me_budget, 2**31 - 1),
                min_count,
                ed_delta,
                imb_min,
                int(l2),
                int(weighted),
                min(rest_cost, 2**31 - 1),
                rest_len,
                n_live,
                max_queue_size,
                capacity_per_size,
                step_limit,
                max_nodes_wo_constraint,
                int(create_mode),
                n_pool,
                int(split_relax),
                int(mc_dyn),
            ],
            dtype=np.int32,
        )
        seqv0 = np.arange(K, dtype=np.int32)
        # the arena body is an order of magnitude bigger than the run
        # kernels (pop tournament + tracker loops + creation cond), so
        # the speculative unroll is capped low: XLA:CPU has crashed
        # compiling large unrolled arena graphs before (see the
        # tournament comment in _j_arena)
        cols = min(_run_cols(), 4)
        _note_compile("j_arena", (
            self._B, self._R, self._W, self._C, self._A, K, uniform,
            self.num_symbols, cols,
        ))
        with _phases.device_scope(rec):
            out_dev = _j_arena(
                self._state,
                self._reads,
                self._reads_pad,
                self._rlen,
                params,
                np.asarray(slots, dtype=np.int32),
                np.asarray(kinds, dtype=np.int32),
                seqv0,
                off0s,
                np.asarray(tr_scalars, dtype=np.int32),
                np.ascontiguousarray(lc, dtype=np.int32),
                np.ascontiguousarray(pc, dtype=np.int32),
                np.ascontiguousarray(mc_tab, dtype=np.int32),
                imb_tab,
                self._wc,
                self._et,
                self._A,
                self.ARENA_CAP,
                K,
                uniform,
                a_real=self.num_symbols,
                cols=cols,
            )
            if rec is not None:
                # profiling fences the async dispatch (see run_extend)
                out_dev = jax.block_until_ready(out_dev)
        (state, hist, nsteps, code, stop_node, steps, stats, act, cons,
         clen, alive, cre_count, cre_parent, cre_kind, cre_sym1,
         cre_sym2, cre_len, stop_diag, iters) = out_dev
        if rec is not None:
            rec.annotate(
                kernel="arena", k=int(cols), geom=self._geom_bucket()
            )
        self._state = state
        with _obs_span("device_get:run_arena", "device-sync"), \
                _phases.transfer_scope(rec):
            (hist_np, nsteps, code, stop_node, steps_np, stats_np, act_np,
             cons_np, alive_np, cre_count, stop_diag,
             iters) = jax.device_get(
                (hist, nsteps, code, stop_node, steps, stats, act, cons,
                 alive, cre_count, stop_diag, iters)
            )
        self.counters["arena_iters"] += int(iters)
        self.counters["arena_spec_events"] += int(iters) * cols
        nsteps = int(nsteps)
        code = int(code)
        stop_node = int(stop_node)
        cre_count = int(cre_count)
        if code == 1:
            # why the stopping winner's split wasn't absorbed: child
            # count + gate flags (see stop_diag in _j_arena)
            diag = int(stop_diag)
            key1 = f"arena_s1_nc{diag // 64}_f{diag % 64:02d}"
            self.counters[key1] = self.counters.get(key1, 0) + 1
        if cre_count:
            with _phases.transfer_scope(rec):
                (cre_parent_np, cre_kind_np, cre_sym1_np, cre_sym2_np,
                 cre_len_np) = jax.device_get(
                    (cre_parent, cre_kind, cre_sym1, cre_sym2, cre_len)
                )

        # decode the typed event stream
        events = []
        for v in hist_np[:nsteps]:
            v = int(v)
            if v < K:
                events.append(("commit", v))
            elif v < 2 * K:
                events.append(("discard", v - K))
            elif v < 3 * K:
                events.append(("split", v - 2 * K))
            else:
                events.append(("create", v - 3 * K))

        # creation records -> child descriptors with registered handles;
        # unconsumed pool pairs (and the unused side-2 slot of single
        # children) go straight back to the free list
        creations = []
        for j in range(cre_count):
            (h1p, _s1p), (h2p, _s2p) = pool_pairs[j]
            kind_j = int(cre_kind_np[j])
            creations.append(
                {
                    "parent": int(cre_parent_np[j]),
                    "kind": kind_j,
                    "sym1": int(self.symtab[int(cre_sym1_np[j])]),
                    "sym2": (
                        int(self.symtab[int(cre_sym2_np[j])])
                        if kind_j == 1
                        else None
                    ),
                    "created_len": int(cre_len_np[j]),
                    "h1": h1p,
                    "h2": h2p if kind_j == 1 else None,
                }
            )
            if kind_j == 0:
                self.free(h2p)
        for j in range(cre_count, n_pool):
            (h1p, _), (h2p, _) = pool_pairs[j]
            self.free(h1p)
            self.free(h2p)

        self.counters["arena_calls"] = self.counters.get("arena_calls", 0) + 1
        self.counters["arena_steps"] = (
            self.counters.get("arena_steps", 0) + nsteps
        )
        key = f"arena_stop_{code}"
        self.counters[key] = self.counters.get(key, 0) + 1
        n_disc = int(np.count_nonzero(~alive_np[: n_live + cre_count]))
        if n_disc:
            self.counters["arena_discards"] = (
                self.counters.get("arena_discards", 0) + n_disc
            )
        if cre_count:
            self.counters["arena_creations"] = (
                self.counters.get("arena_creations", 0) + cre_count
            )
            self.counters["arena_split_events"] = (
                self.counters.get("arena_split_events", 0)
                + sum(1 for kind, _ in events if kind == "split")
            )
        # arena divergence pruning deactivates lanes on device; mirror it
        for side in live_sides:
            self._act_host[slots[side]] = act_np[side]

        # per-node effective (kind, l0_side1, l0_side2) covering children
        eff = []
        for i in range(n_live):
            eff.append((kinds[i], node_specs[i][2], node_specs[i][3]))
        for j, cre in enumerate(creations):
            eff.append((cre["kind"], cre["created_len"], cre["created_len"]))
            # host offset mirrors for the consumed pool slots (the act
            # mirror comes from the device act rows below)
            pk = eff[cre["parent"]][0]
            p1s = slots[2 * cre["parent"]]
            src2 = slots[2 * cre["parent"] + (1 if pk == 1 else 0)]
            c1s = slots[2 * (n_live + j)]
            self._off_host[c1s] = self._off_host[p1s]
            self._act_host[c1s] = act_np[2 * (n_live + j)]
            if cre["kind"] == 1:
                c2s = slots[2 * (n_live + j) + 1]
                self._off_host[c2s] = self._off_host[src2]
                self._act_host[c2s] = act_np[2 * (n_live + j) + 1]

        appended = []
        sides_stats = []
        sides_act = []
        n = self.num_reads
        n_nodes = n_live + cre_count
        for f in range(2 * K):
            node = f // 2
            if node >= n_nodes or (f % 2 == 1 and eff[node][0] == 0):
                appended.append(None)
                sides_stats.append(None)
                sides_act.append(None)
                continue
            k_steps = int(steps_np[node])
            l0 = eff[node][1 + (f % 2)]
            ids = cons_np[f, l0 : l0 + k_steps]
            appended.append(self.symtab[ids].astype(np.uint8).tobytes())
            sides_stats.append(
                self._stats_np(
                    (
                        stats_np[0][f],
                        stats_np[1][f],
                        stats_np[2][f],
                        stats_np[3][f],
                    )
                )
            )
            sides_act.append(act_np[f, :n])
        if code == 5:
            self._grow_e()
        return (
            events,
            nsteps,
            code,
            stop_node,
            [int(s) for s in steps_np],
            appended,
            sides_stats,
            sides_act,
            [bool(a) for a in alive_np],
            creations,
        )

    def best_activation_offset(
        self,
        consensus: bytes,
        seq_index: int,
        offset_window: int,
        offset_compare_length: int,
        wildcard,
    ) -> int:
        """Device-batched activation-offset search (one ``_j_offset_scan``
        dispatch scoring the whole window) with the host loop's exact
        first-best/midpoint-incumbent tie semantics; tiny problems fall
        back to the host WFA loop."""
        seq = self.reads[seq_index]
        cmp_len = min(offset_compare_length, len(seq))
        con_len = len(consensus)
        start = max(0, con_len - (offset_window + cmp_len))
        end = max(0, con_len - cmp_len)
        n_pos = end - start
        if n_pos <= 1 or cmp_len * n_pos < 512:
            from waffle_con_tpu.ops.scorer import find_activation_offset

            return find_activation_offset(
                consensus, seq, offset_window, offset_compare_length,
                wildcard,
            )
        M = _next_pow2(cmp_len)
        P = _next_pow2(n_pos)
        win = np.full(P + 2 * M, -2, dtype=np.int32)
        tail = consensus[start : min(con_len, start + P + 2 * M)]
        win[: len(tail)] = [self.sym_id[b] for b in tail]
        head = np.full((1, M), -3, dtype=np.int32)
        head[0, :cmp_len] = [self.sym_id[b] for b in seq[:cmp_len]]
        self.counters["offset_scan_calls"] = (
            self.counters.get("offset_scan_calls", 0) + 1
        )
        eds = np.asarray(
            _j_offset_scan(win, head, np.int32(cmp_len), self._wc, P, M)
        )[0]
        best_offset = max(0, con_len - (cmp_len + offset_window // 2))
        min_ed = int(eds[best_offset - start])
        for p in range(n_pos):
            if int(eds[p]) < min_ed:
                min_ed = int(eds[p])
                best_offset = start + p
        return best_offset

    @staticmethod
    def _pad_len_table(tab: np.ndarray, need: int) -> np.ndarray:
        """Pad a per-length int table to a power-of-two length >= need
        with its final value (tables are constant past the last
        activation point), bounding the number of compiled geometries."""
        n = _next_pow2(max(int(need), len(tab), 8))
        out = np.full(n, tab[-1], dtype=np.int32)
        out[: len(tab)] = tab
        return out

    def _scratch_reset(self) -> None:
        self._scratch_next = 0

    def _scratch_slot(self) -> int:
        """Dedicated slots backing the unused side-2 rows of single-kind
        arena nodes and both rows of padding nodes (content is scratch;
        the pool keeps each use in one call distinct so the output
        scatter never writes one slot twice)."""
        if not hasattr(self, "_scratch"):
            self._scratch = [
                self._alloc()[1] for _ in range(2 * self.ARENA_K)
            ]
        slot = self._scratch[self._scratch_next]
        self._scratch_next += 1
        return slot

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        self.counters["finalize_calls"] += 1
        slot = self._slot_of[h]
        while True:
            eds, overflow = _j_finalize(self._state, np.int32(slot))
            with _obs_span("device_get:finalized_eds", "device-sync"):
                eds_np, ovf = jax.device_get((eds, overflow))
            if bool(ovf):
                self._grow_e()
                continue
            return eds_np[: self.num_reads].astype(np.int64)

    # -----------------------------------------------------------------

    def _stats_np(self, stats_np) -> BranchStats:
        """Host-array stats -> :class:`BranchStats`, slicing read padding
        and alphabet padding away.  Input must already be numpy (ONE
        ``jax.device_get`` per scorer call — per-element indexing of live
        device arrays would dispatch a tiny gather op each time).  A
        6-tuple carries bundled finalized distances (+validity)."""
        eds, occ, split, reached = stats_np[:4]
        n = self.num_reads
        a = self.num_symbols
        fin = None
        if len(stats_np) == 6 and bool(stats_np[5]):
            fin = stats_np[4][:n].astype(np.int64)
        return BranchStats(
            eds[:n].astype(np.int64),
            occ[:n, :a].astype(np.int64),
            split[:n].astype(np.int64),
            reached[:n],
            fin,
        )

    def _stats_rows(self, stats_np, count: int) -> List[BranchStats]:
        return [
            self._stats_np(tuple(part[i] for part in stats_np))
            for i in range(count)
        ]
