"""Batched JAX/TPU wavefront scorer.

The TPU-native implementation of the
:class:`~waffle_con_tpu.ops.scorer.WavefrontScorer` seam.  Where the
reference iterates a ``Vec<DWFALite>`` serially per consensus symbol
(``/root/reference/src/consensus.rs:455-463``), this scorer keeps *every*
branch's per-read wavefront in device arrays and advances all of them in
fused XLA kernels:

* ``d``   — ``[B, R, W] int32``: bases consumed in the consensus per
  (branch-slot, read, diagonal), ``W = 2*E_max + 1`` diagonals in
  *centered* coordinates (``k = column - E``, baseline position is simply
  ``d - k``); invalid diagonals hold a large negative sentinel.
* ``e/off/act`` — ``[B, R]``: per-read edit distance, consensus offset,
  tracking flag.
* ``cons/clen`` — ``[B, C]``: the per-branch consensus (dense symbol ids).

One ``update`` call performs the greedy diagonal extension (lock-step
``lax.while_loop`` — every (read, diagonal) lane advances while its
characters match) interleaved with per-read edit-distance escalation (a
3-point stencil in diagonal space: ``new[k] = max(old[k+1], old[k]+1,
old[k-1]+1)``), exactly the semantics of
``DWFALite::update`` (``/root/reference/src/dynamic_wfa.rs:75-191``).

Dynamic wavefront growth is handled by bucketing: when any read would need
``e > E_max`` the kernel reports overflow without committing state, and
the host re-buckets (doubles ``E_max``, recenters the buffers) and
retries.  Shapes are padded to powers of two to bound XLA recompiles.

Sharding: reads are the embarrassingly-parallel axis.  All kernels are
pure functions of arrays whose read axis can be sharded over a
``jax.sharding.Mesh`` — :mod:`waffle_con_tpu.parallel` provides the
``shard_map`` wrappers with ``psum`` vote reductions.
"""

from __future__ import annotations

import math
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from waffle_con_tpu.config import CdwfaConfig
from waffle_con_tpu.ops.scorer import BranchStats, WavefrontScorer

NEG = jnp.int32(-(1 << 28))


def _next_pow2(n: int, minimum: int = 1) -> int:
    return max(minimum, 1 << max(0, (n - 1).bit_length()))


# ======================================================================
# single-branch kernels (row = one branch), vmapped/batched by callers.
# All take dense-id arrays; `wc` is the wildcard dense id or -2; `et` is
# allow_early_termination as a traced bool scalar.


def _valid_mask(e, kvec):
    return jnp.abs(kvec)[None, :] <= e[:, None]


def _extend(d, e, off, act, cons, clen, reads, rlen, wc, kvec):
    """Greedy furthest-reaching extension of all (read, diagonal) lanes
    (parity: ``DWFALite::extend``, ``/root/reference/src/dynamic_wfa.rs:109-153``)."""
    L = reads.shape[1]
    C = cons.shape[0]

    def step(dcur):
        valid = act[:, None] & _valid_mask(e, kvec)
        bo = dcur - kvec[None, :]
        oo = dcur + off[:, None]
        inb = (
            (bo >= 0)
            & (bo < rlen[:, None])
            & (oo >= 0)
            & (oo < clen)
        )
        bchar = jnp.take_along_axis(reads, jnp.clip(bo, 0, L - 1), axis=1)
        ochar = cons[jnp.clip(oo, 0, C - 1)]
        match = (bchar == ochar) | (bchar == wc)
        adv = valid & inb & match
        return dcur + adv.astype(dcur.dtype), adv.any()

    d, again = step(d)
    d, _ = lax.while_loop(
        lambda carry: carry[1], lambda carry: step(carry[0]), (d, again)
    )
    return d


def _maxima(d, e, off, kvec):
    valid = _valid_mask(e, kvec)
    dv = jnp.where(valid, d, NEG)
    max_other = off + dv.max(axis=1)
    max_base = jnp.where(valid, d - kvec[None, :], NEG).max(axis=1)
    return max_other, max_base


def _escalate_once(d, e, need, kvec):
    """Grow needy reads' wavefronts by one edit: 3-point stencil in
    diagonal space (parity: ``DWFALite::increase_edit_distance``,
    ``/root/reference/src/dynamic_wfa.rs:162-191``)."""
    up = jnp.concatenate([d[:, 1:], jnp.full_like(d[:, :1], NEG)], axis=1)
    down = jnp.concatenate([jnp.full_like(d[:, :1], NEG), d[:, :-1]], axis=1)
    cand = jnp.maximum(jnp.maximum(up, d + 1), down + 1)
    e_new = e + need.astype(e.dtype)
    newvalid = _valid_mask(e_new, kvec)
    d_new = jnp.where(newvalid, cand, NEG)
    d = jnp.where(need[:, None], d_new, d)
    return d, e_new


def _update_row(d, e, off, act, cons, clen, reads, rlen, wc, et, kvec, emax):
    """Full ``update``: extend, then escalate+re-extend until every active
    read consumed the whole consensus (or hit its baseline end under early
    termination).  Returns ``(d, e, overflow)``; on overflow the caller
    must discard the state and re-bucket."""

    def need_mask(dcur, ecur):
        max_other, max_base = _maxima(dcur, ecur, off, kvec)
        reached = max_base == rlen
        return act & (max_other < clen) & ~(et & reached)

    d = _extend(d, e, off, act, cons, clen, reads, rlen, wc, kvec)

    def cond(carry):
        dcur, ecur = carry
        need = need_mask(dcur, ecur)
        can = need & (ecur < emax)
        return can.any() & ~(need & (ecur >= emax)).any()

    def body(carry):
        dcur, ecur = carry
        need = need_mask(dcur, ecur)
        dcur, ecur = _escalate_once(dcur, ecur, need, kvec)
        dcur = _extend(dcur, ecur, off, act, cons, clen, reads, rlen, wc, kvec)
        return dcur, ecur

    d, e = lax.while_loop(cond, body, (d, e))
    overflow = (need_mask(d, e) & (e >= emax)).any()
    return d, e, overflow


def _finalize_row(d, e, off, act, cons, clen, reads, rlen, wc, kvec, emax):
    """Escalate until every active read's wavefront touches its baseline
    end (parity: ``DWFALite::finalize``,
    ``/root/reference/src/dynamic_wfa.rs:201-210``)."""

    def need_mask(dcur, ecur):
        _, max_base = _maxima(dcur, ecur, off, kvec)
        return act & (max_base < rlen)

    def cond(carry):
        dcur, ecur = carry
        need = need_mask(dcur, ecur)
        return (need & (ecur < emax)).any() & ~(need & (ecur >= emax)).any()

    def body(carry):
        dcur, ecur = carry
        need = need_mask(dcur, ecur)
        dcur, ecur = _escalate_once(dcur, ecur, need, kvec)
        dcur = _extend(dcur, ecur, off, act, cons, clen, reads, rlen, wc, kvec)
        return dcur, ecur

    d, e = lax.while_loop(cond, body, (d, e))
    overflow = (need_mask(d, e) & (e >= emax)).any()
    return e, overflow


def _stats_row(d, e, off, act, cons, clen, reads, rlen, num_symbols, kvec):
    """Snapshot: per-read edit distance, baseline-end flags, and the tip
    vote histogram over dense symbols (parity:
    ``DWFALite::get_extension_candidates``,
    ``/root/reference/src/dynamic_wfa.rs:241-255``)."""
    L = reads.shape[1]
    valid = act[:, None] & _valid_mask(e, kvec)
    _, max_base = _maxima(d, e, off, kvec)
    reached = act & (max_base == rlen)
    eds = jnp.where(act, e, 0)

    bo = d - kvec[None, :]
    tip = valid & (d + off[:, None] == clen) & (bo >= 0) & (bo < rlen[:, None])
    sym = jnp.take_along_axis(reads, jnp.clip(bo, 0, L - 1), axis=1)
    onehot = (sym[:, :, None] == jnp.arange(num_symbols)[None, None, :]) & tip[
        :, :, None
    ]
    occ = onehot.sum(axis=1, dtype=jnp.int32)
    split = occ.sum(axis=1)
    return eds, occ, split, reached


# ======================================================================
# whole-state jitted entry points.  state = dict of arrays; shapes drive
# jax's compile cache.


def _fresh_read_row(W):
    row = jnp.full((W,), NEG, dtype=jnp.int32)
    return row.at[W // 2].set(0)


@jax.jit
def _j_clone(state, src, dst):
    out = dict(state)
    for name in ("d", "e", "off", "act", "cons", "clen"):
        out[name] = state[name].at[dst].set(state[name][src])
    return out


@partial(jax.jit, static_argnames=("num_symbols",))
def _j_push(state, reads, rlen, h, sym, wc, et, num_symbols):
    W = state["d"].shape[2]
    emax = jnp.int32(W // 2)
    kvec = jnp.arange(W, dtype=jnp.int32) - W // 2
    C = state["cons"].shape[1]

    clen0 = state["clen"][h]
    cons = state["cons"].at[h, jnp.clip(clen0, 0, C - 1)].set(sym)
    clen = state["clen"].at[h].add(1)

    d, e, overflow = _update_row(
        state["d"][h],
        state["e"][h],
        state["off"][h],
        state["act"][h],
        cons[h],
        clen[h],
        reads,
        rlen,
        wc,
        et,
        kvec,
        emax,
    )
    out = dict(state)
    out["cons"] = cons
    out["clen"] = clen
    out["d"] = state["d"].at[h].set(d)
    out["e"] = state["e"].at[h].set(e)
    eds, occ, split, reached = _stats_row(
        d, e, out["off"][h], out["act"][h], cons[h], clen[h], reads, rlen,
        num_symbols, kvec,
    )
    return out, (eds, occ, split, reached), overflow


@partial(jax.jit, static_argnames=("num_symbols",))
def _j_stats(state, reads, rlen, h, num_symbols):
    W = state["d"].shape[2]
    kvec = jnp.arange(W, dtype=jnp.int32) - W // 2
    return _stats_row(
        state["d"][h],
        state["e"][h],
        state["off"][h],
        state["act"][h],
        state["cons"][h],
        state["clen"][h],
        reads,
        rlen,
        num_symbols,
        kvec,
    )


@jax.jit
def _j_activate(state, reads, rlen, h, read_index, offset, wc, et):
    W = state["d"].shape[2]
    emax = jnp.int32(W // 2)
    kvec = jnp.arange(W, dtype=jnp.int32) - W // 2

    d0 = state["d"][h].at[read_index].set(_fresh_read_row(W))
    e0 = state["e"][h].at[read_index].set(0)
    off0 = state["off"][h].at[read_index].set(offset)
    act0 = state["act"][h].at[read_index].set(True)

    d, e, overflow = _update_row(
        d0, e0, off0, act0, state["cons"][h], state["clen"][h],
        reads, rlen, wc, et, kvec, emax,
    )
    out = dict(state)
    out["d"] = state["d"].at[h].set(d)
    out["e"] = state["e"].at[h].set(e)
    out["off"] = state["off"].at[h].set(off0)
    out["act"] = state["act"].at[h].set(act0)
    return out, overflow


@jax.jit
def _j_deactivate(state, h, read_index):
    out = dict(state)
    out["act"] = state["act"].at[h, read_index].set(False)
    return out


@jax.jit
def _j_finalize(state, reads, rlen, h, wc):
    W = state["d"].shape[2]
    emax = jnp.int32(W // 2)
    kvec = jnp.arange(W, dtype=jnp.int32) - W // 2
    e, overflow = _finalize_row(
        state["d"][h],
        state["e"][h],
        state["off"][h],
        state["act"][h],
        state["cons"][h],
        state["clen"][h],
        reads,
        rlen,
        wc,
        kvec,
        emax,
    )
    eds = jnp.where(state["act"][h], e, 0)
    return eds, overflow


@partial(jax.jit, static_argnames=("num_symbols",))
def _j_run(
    state, reads, rlen, h, budget, min_count, l2, wc, et, max_steps,
    num_symbols,
):
    """Device-resident multi-symbol extension: keep appending the unique
    passing candidate while the votes are exactly reproducible host-side
    (one tip symbol per read → integer counts), stopping at any event the
    host search must arbitrate.

    Stop codes: 1 = votes need host arbitration (non-one-hot, wildcard
    votes, or #passing != 1), 2 = some read reached its baseline end,
    3 = node cost exceeded the budget, 4 = step limit, 5 = wavefront
    bucket overflow (last push not committed).

    This is the TPU answer to the reference's symbol-at-a-time host loop:
    for clean stretches the consensus grows entirely on device, with one
    host round-trip per *event* instead of per base.
    """
    W = state["d"].shape[2]
    emax = jnp.int32(W // 2)
    kvec = jnp.arange(W, dtype=jnp.int32) - W // 2
    C = state["cons"].shape[1]
    off = state["off"][h]
    act = state["act"][h]

    def body(carry):
        d, e, cons, clen, steps, _code = carry
        eds, occ, split, reached = _stats_row(
            d, e, off, act, cons, clen, reads, rlen, num_symbols, kvec
        )
        # int32-safe cost total: with L2 and huge per-read distances the
        # squared sum could wrap, so treat that regime as a host event
        costs = jnp.where(l2, eds * eds, eds)
        total = jnp.where(act, costs, 0).sum()
        cost_overflow = l2 & (jnp.where(act, eds, 0).max() > 2048)

        # fractional votes, mirroring the host's candidate nomination: each
        # read splits one unit across its tip symbols.  The host sums in
        # f64 read order; device f32 reductions agree on every >=-decision
        # whenever the comparison margin exceeds EPS, so we continue only
        # on clear margins (exact when all reads are single-tip).
        EPS = jnp.float32(1e-3)
        voters = occ > 0  # [R, A]
        has_votes = voters.any(axis=0)
        n_cands = has_votes.sum()
        frac = jnp.where(
            split[:, None] > 0,
            occ.astype(jnp.float32) / jnp.maximum(split, 1)[:, None].astype(jnp.float32),
            0.0,
        )
        counts = frac.sum(axis=0)  # [A]
        # wildcard removal (host drops it whenever another candidate exists)
        wc_col = jnp.maximum(wc, 0)
        drop_wc = (wc >= 0) & (n_cands > 1)
        has_votes = jnp.where(
            drop_wc, has_votes.at[wc_col].set(False), has_votes
        )
        counts = jnp.where(drop_wc, counts.at[wc_col].set(0.0), counts)

        maxc = jnp.where(has_votes, counts, -1.0).max()
        min_count_f = min_count.astype(jnp.float32)
        thr = jnp.minimum(min_count_f, maxc)
        passing = has_votes & (counts >= thr)
        npass = passing.sum()

        all_onehot = (voters.sum(axis=1) <= 1).all()
        near_tie = (
            (jnp.abs(maxc - min_count_f) < EPS)
            | (has_votes & (jnp.abs(counts - thr) < EPS)).any()
        )
        ambiguous = ~all_onehot & near_tie
        dirty = ambiguous | (npass != 1) | (n_cands == 0) | cost_overflow

        code = jnp.where(
            reached.any(),
            2,
            jnp.where(
                total > budget,
                3,
                jnp.where(
                    dirty,
                    1,
                    jnp.where(steps >= max_steps, 4, 0),
                ),
            ),
        )

        sym = jnp.argmax(jnp.where(passing, counts, -1.0)).astype(jnp.int32)
        cons2 = cons.at[jnp.clip(clen, 0, C - 1)].set(sym)
        clen2 = clen + 1
        d2, e2, ovf = _update_row(
            d, e, off, act, cons2, clen2, reads, rlen, wc, et, kvec, emax
        )
        commit = (code == 0) & ~ovf
        code = jnp.where(code != 0, code, jnp.where(ovf, 5, 0))
        d = jnp.where(commit, d2, d)
        e = jnp.where(commit, e2, e)
        cons = jnp.where(commit, cons2, cons)
        clen = jnp.where(commit, clen2, clen)
        steps = steps + commit.astype(steps.dtype)
        return d, e, cons, clen, steps, code

    init = (
        state["d"][h],
        state["e"][h],
        state["cons"][h],
        state["clen"][h],
        jnp.int32(0),
        jnp.int32(0),
    )
    d, e, cons, clen, steps, code = lax.while_loop(
        lambda c: c[5] == 0, body, init
    )
    out = dict(state)
    out["d"] = state["d"].at[h].set(d)
    out["e"] = state["e"].at[h].set(e)
    out["cons"] = state["cons"].at[h].set(cons)
    out["clen"] = state["clen"].at[h].set(clen)
    return out, steps, code


@jax.jit
def _j_root(state, h, act):
    W = state["d"].shape[2]
    out = dict(state)
    out["d"] = state["d"].at[h].set(
        jnp.broadcast_to(_fresh_read_row(W), state["d"].shape[1:])
    )
    out["e"] = state["e"].at[h].set(0)
    out["off"] = state["off"].at[h].set(0)
    out["act"] = state["act"].at[h].set(act)
    out["clen"] = state["clen"].at[h].set(0)
    return out


class ScorerOverflow(Exception):
    """Internal: a kernel needed a larger wavefront bucket."""


class JaxScorer(WavefrontScorer):
    """Device-resident branch store.

    Handles are host-side ids mapped to device slots; slot/geometry growth
    (branch count, consensus capacity, wavefront bucket) recompiles the
    kernels for the new shapes — growth doubles, so recompiles are
    logarithmic.
    """

    INITIAL_E = 8
    INITIAL_SLOTS = 16

    def __init__(self, reads: Sequence[bytes], config: CdwfaConfig) -> None:
        super().__init__(reads, config)
        n = len(self.reads)
        self._R = _next_pow2(n)
        max_len = max((len(r) for r in self.reads), default=1)
        self._L = _next_pow2(max(max_len, 1))

        reads_arr = np.full((self._R, self._L), -1, dtype=np.int32)
        rlen = np.zeros(self._R, dtype=np.int32)
        for i, r in enumerate(self.reads):
            reads_arr[i, : len(r)] = [self.sym_id[b] for b in r]
            rlen[i] = len(r)
        self._reads = jnp.asarray(reads_arr)
        self._rlen = jnp.asarray(rlen)

        self._wc = jnp.int32(
            self.sym_id.get(config.wildcard, -2)
            if config.wildcard is not None
            else -2
        )
        self._et = jnp.bool_(config.allow_early_termination)

        self._E = self.INITIAL_E
        self._B = self.INITIAL_SLOTS
        self._C = _next_pow2(max_len + 64)
        self._state = self._blank_state()
        self._free: List[int] = list(range(self._B))
        self._next_handle = 0
        self._slot_of = {}

    # -- geometry ------------------------------------------------------

    def _blank_state(self):
        W = 2 * self._E + 1
        return {
            "d": jnp.full((self._B, self._R, W), NEG, dtype=jnp.int32),
            "e": jnp.zeros((self._B, self._R), dtype=jnp.int32),
            "off": jnp.zeros((self._B, self._R), dtype=jnp.int32),
            "act": jnp.zeros((self._B, self._R), dtype=bool),
            "cons": jnp.zeros((self._B, self._C), dtype=jnp.int32),
            "clen": jnp.zeros((self._B,), dtype=jnp.int32),
        }

    def _grow_e(self) -> None:
        old_w = 2 * self._E + 1
        self._E *= 2
        new_w = 2 * self._E + 1
        pad = (new_w - old_w) // 2
        d = jnp.full(
            (self._B, self._R, new_w), NEG, dtype=jnp.int32
        ).at[:, :, pad : pad + old_w].set(self._state["d"])
        self._state = dict(self._state, d=d)

    def _grow_slots(self) -> None:
        old_b = self._B
        self._B *= 2
        state = self._state
        out = {}
        for name, arr in state.items():
            shape = (self._B,) + arr.shape[1:]
            fill = NEG if name == "d" else 0
            grown = jnp.full(shape, fill, dtype=arr.dtype) if name == "d" else jnp.zeros(shape, dtype=arr.dtype)
            out[name] = grown.at[:old_b].set(arr)
        self._state = out
        self._free.extend(range(old_b, self._B))

    def _grow_cons(self) -> None:
        old_c = self._C
        self._C *= 2
        cons = jnp.zeros((self._B, self._C), dtype=jnp.int32)
        self._state = dict(
            self._state, cons=cons.at[:, :old_c].set(self._state["cons"])
        )

    def _alloc(self) -> Tuple[int, int]:
        if not self._free:
            self._grow_slots()
        slot = self._free.pop()
        handle = self._next_handle
        self._next_handle += 1
        self._slot_of[handle] = slot
        return handle, slot

    # -- interface -----------------------------------------------------

    def root(self, active: np.ndarray) -> int:
        handle, slot = self._alloc()
        act = np.zeros(self._R, dtype=bool)
        act[: len(active)] = active
        self._state = _j_root(self._state, slot, jnp.asarray(act))
        return handle

    def clone(self, h: int) -> int:
        src = self._slot_of[h]
        handle, dst = self._alloc()
        self._state = _j_clone(self._state, src, dst)
        return handle

    def free(self, h: int) -> None:
        slot = self._slot_of.pop(h, None)
        if slot is not None:
            self._free.append(slot)

    def push(self, h: int, consensus: bytes) -> BranchStats:
        slot = self._slot_of[h]
        if len(consensus) >= self._C - 1:
            self._grow_cons()
        sym = self.sym_id[consensus[-1]]
        while True:
            state, stats, overflow = _j_push(
                self._state,
                self._reads,
                self._rlen,
                slot,
                jnp.int32(sym),
                self._wc,
                self._et,
                self.num_symbols,
            )
            if bool(overflow):
                self._grow_e()
                continue
            self._state = state
            return self._to_host(stats)

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        slot = self._slot_of[h]
        return self._to_host(
            _j_stats(
                self._state, self._reads, self._rlen, slot, self.num_symbols
            )
        )

    def activate(self, h: int, read_index: int, offset: int, consensus: bytes) -> None:
        slot = self._slot_of[h]
        while True:
            state, overflow = _j_activate(
                self._state,
                self._reads,
                self._rlen,
                slot,
                jnp.int32(read_index),
                jnp.int32(offset),
                self._wc,
                self._et,
            )
            if bool(overflow):
                self._grow_e()
                continue
            self._state = state
            return

    def deactivate(self, h: int, read_index: int) -> None:
        slot = self._slot_of[h]
        self._state = _j_deactivate(self._state, slot, jnp.int32(read_index))

    def run_extend(
        self,
        h: int,
        consensus: bytes,
        budget: int,
        min_count: int,
        l2: bool,
        max_steps: int,
    ) -> Tuple[int, int, bytes]:
        """Device-side unambiguous-run extension; returns
        ``(steps_committed, stop_code, appended_bytes)``.  See ``_j_run``
        for the stop-code contract; on overflow the bucket is grown so the
        caller can simply continue stepping."""
        slot = self._slot_of[h]
        while len(consensus) + max_steps + 2 >= self._C:
            self._grow_cons()
        state, steps, code = _j_run(
            self._state,
            self._reads,
            self._rlen,
            slot,
            jnp.int32(min(budget, 2**31 - 1)),
            jnp.int32(min_count),
            jnp.bool_(l2),
            self._wc,
            self._et,
            jnp.int32(max_steps),
            self.num_symbols,
        )
        steps = int(steps)
        code = int(code)
        self._state = state
        appended = b""
        if steps:
            ids = np.asarray(
                state["cons"][slot, len(consensus) : len(consensus) + steps]
            )
            appended = bytes(int(self.symtab[i]) for i in ids)
        if code == 5:
            self._grow_e()
        return steps, code, appended

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        slot = self._slot_of[h]
        while True:
            eds, overflow = _j_finalize(
                self._state, self._reads, self._rlen, slot, self._wc
            )
            if bool(overflow):
                self._grow_e()
                continue
            return np.asarray(eds[: self.num_reads], dtype=np.int64)

    # -----------------------------------------------------------------

    def _to_host(self, stats) -> BranchStats:
        eds, occ, split, reached = stats
        n = self.num_reads
        return BranchStats(
            np.asarray(eds[:n], dtype=np.int64),
            np.asarray(occ[:n], dtype=np.int64),
            np.asarray(split[:n], dtype=np.int64),
            np.asarray(reached[:n]),
        )
