"""Alignment kernels: incremental dynamic-WFA, one-shot WFA, and the
batched JAX/TPU scorer."""

from waffle_con_tpu.ops.alignment import wfa_ed, wfa_ed_config
from waffle_con_tpu.ops.dwfa import DWFALite

__all__ = ["DWFALite", "wfa_ed", "wfa_ed_config"]
