"""Fused Pallas TPU kernel for the single-engine run loop.

``_j_run`` (ops/jax_scorer.py) executes the device-resident multi-symbol
extension as a ``lax.while_loop`` of ~40 XLA kernels per consensus
symbol; at north-star scale the measured cost is ~55-80 us/step, almost
all of it per-kernel launch latency and HBM round-trips (the compiled
HLO re-copies the full padded reads array HBM->VMEM every iteration).
This module re-derives the same loop as ONE Mosaic kernel: the whole
extension runs inside a single ``pl.pallas_call`` with every operand
pinned in VMEM, so a step is ~40 VPU passes over a [W, R] tile with no
launch overhead — measured ~10x less wall per step.

Layout is TRANSPOSED relative to the XLA path: the DP tile is
``D[W, R]`` (band position on sublanes, reads on lanes) because Mosaic
only allows dynamic slicing on the sublane dimension.  The per-step
read window is an aligned dynamic sublane load + ``pltpu.roll`` by the
16-residue, and per-read scalars are natural ``[1, R]`` lane vectors.
The in-column insertion chain (``lax.cummin`` upstream) is an exact
log-shift prefix-min over sublanes.

Semantics mirror ``_j_run`` / ``_j_run_dual`` decision-for-decision
(stop codes, vote EPS contract, record absorption, forced first
symbol, band-overflow refusal, locks, divergence pruning, min-count
tables); see those docstrings for the contracts.  The host searches
these kernels accelerate are the reference's symbol-at-a-time loops:
``/root/reference/src/consensus.rs:258-472`` (advance/expand),
``/root/reference/src/dual_consensus.rs:606-734`` (dual extension
cross product) and ``:1257-1336`` (vote weights), with the per-symbol
wavefront hot loop at ``/root/reference/src/dynamic_wfa.rs:75-191``
re-derived as the banded column DP (equivalence argument in
ops/jax_scorer.py).  Parity is enforced by tests/test_pallas_run.py
(interpret mode on CPU) and the fuzz/e2e suites with
``WAFFLE_PALLAS=interpret``.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from waffle_con_tpu.ops.jax_scorer import INF, REC_CAP, VOTE_EPS
from waffle_con_tpu.utils import envspec

#: sublane alignment of the int16 reads staging array ((16, 128) tiling)
_ALIGN = 16

#: VMEM budget gate for the whole-array-resident kernel; above this the
#: caller falls back to the XLA while-loop path.  ~16 MB of VMEM per
#: core minus headroom for Mosaic's own carry double-buffering; the
#: estimate in fits_budget is itself conservative (int32 tile sizes)
_VMEM_BUDGET = 12 * 1024 * 1024


def pallas_mode() -> str:
    """``"tpu"`` | ``"interpret"`` | ``"off"`` — resolved once per
    process from WAFFLE_PALLAS (default: on iff a TPU is attached)."""
    env = envspec.get_raw("WAFFLE_PALLAS", "auto")
    if env == "0":
        mode = "off"
    elif env == "interpret":
        mode = "interpret"
    else:
        try:
            platform = jax.devices()[0].platform
        except Exception:  # pragma: no cover - no backend at all
            platform = None
        if platform == "tpu":
            mode = "tpu"
        else:
            mode = "interpret" if env == "1" else "off"
    # the round-5 driver bench silently fell back to CPU with
    # run_pallas_calls == 0; stamping the resolved mode into the runtime
    # event log makes "pallas never even attempted" visible in evidence
    from waffle_con_tpu.runtime import events

    events.record("pallas_mode", mode=mode)
    return mode


def fits_budget(stage_rows: int, R: int, W: int, C: int,
                sides: int = 1, i16: bool = False) -> bool:
    """Conservative VMEM estimate for the resident kernel.
    ``stage_rows`` is the transposed-staging row count (from
    :func:`staging_rows` — NOT the pow2-padded storage length);
    ``sides=2`` models the dual kernel (two DP tiles in+out, two stats
    blocks, and four REC_CAP x R record planes instead of one);
    ``i16`` halves the DP-tile term (the int16 tile is what admits
    10 kb-scale dual geometries)."""
    reads = stage_rows * R * 2
    cell = 2 if i16 else 4
    tiles = sides * 6 * W * R * cell  # D + dele/base/chain temporaries
    rec = (4 if sides == 2 else 1) * REC_CAP * R * 4
    return reads + tiles + rec + C * 4 < _VMEM_BUDGET


def window_block(W: int) -> int:
    """Sublane extent of one aligned window load (the ONE definition the
    reads-staging row provisioning must match; see ``staging_rows``)."""
    return ((W + 2 * _ALIGN - 1) // _ALIGN) * _ALIGN


def staging_rows(max_rlen: int, W: int) -> int:
    """Row count of the transposed reads staging, sized by the REAL
    max read length (not the pow2-padded storage axis — for 10 kb reads
    that padding alone would blow the VMEM budget): rows cover
    ``W`` filler + every real read position + one aligned window block,
    so any clipped/overrun window load lands in ``-1`` filler or at
    positions past every read's end (masked by ``i < rlen`` /
    ``i_new > rlen`` either way).  Bucketed to 1 KiB rows to bound the
    number of compiled kernel geometries."""
    need = W + max_rlen + window_block(W) + _ALIGN
    return ((need + 1023) // 1024) * 1024


#: int16 band "infinity": every legitimate finite cell value is gated
#: below it by ``i16_ok``; anything >= DINF16 maps back to the int32 INF
DINF16 = 30000


def i16_ok(L: int, C: int, W: int) -> bool:
    """Whether the int16 DP-tile variant is exact at this geometry:
    a finite cell value is bounded by an edit distance at row <= L,
    column <= C, i.e. by ``max(L, C)``; the gate adds ``W + 4`` — the
    in-kernel arithmetic headroom (the +1/+sub steps and the
    ``x + tcol`` chain term, each < W) plus margin — and requires the
    total to stay below ``DINF16``."""
    return max(L, C) + W + 4 < DINF16


def _roll_fn(interpret):
    if interpret:
        return lambda x, s: jnp.roll(x, s, axis=0)
    return lambda x, s: pltpu.roll(x, s, axis=0)


def _band_ops(*, reads_ref, rlen, wc, et, W, R, E, Wb, Lp, a_real, dt,
              roll):
    """Shared [W, R]-layout band primitives for the fused kernels.

    ``act`` and ``off0`` are per-call parameters (the dual kernel's
    active masks evolve via divergence pruning and each side has its
    own offset); everything else is closed over.  Returns
    ``(window, unmap, stats_at, col_at)`` — the transposed twins of
    ``_read_window`` / ``_stats_core_w`` / ``_col_step_w``."""
    INF32 = int(INF)
    DINF = DINF16 if dt == jnp.int16 else INF32
    i16 = dt == jnp.int16
    tcol = lax.broadcasted_iota(jnp.int32, (W, 1), 0)
    tcol_d = tcol.astype(dt)
    wc16 = wc.astype(jnp.int16)

    def window(clen, off0):
        """[W, R] int16 read window at consensus position ``clen``
        (serves both the tip-vote chars at ``clen`` and the column
        consumed by the push to ``clen+1`` — identical start)."""
        wstart = W + clen - off0 - E
        astart = jnp.clip((wstart // _ALIGN) * _ALIGN, 0, Lp - Wb)
        r = jnp.clip(wstart - astart, 0, Wb)
        blk = reads_ref[pl.ds(pl.multiple_of(astart, _ALIGN), Wb), :]
        blk = roll(blk, Wb - r)
        return blk[0:W, :]

    def unmap(v):
        """int32 view of a reduced band value (DINF -> INF)."""
        v = v.astype(jnp.int32)
        if not i16:
            return v
        return jnp.where(v >= DINF, INF32, v)

    def stats_at(D, e, rmin, er, act, clen, off0, wnd):
        i = clen - off0 - E + tcol                      # [W, 1]
        e_d = jnp.minimum(e, DINF).astype(dt)
        tip = (D <= e_d) & act & (i >= 0) & (i < rlen)  # [W, R]
        occ = [
            jnp.sum(((wnd == a) & tip).astype(jnp.int32), axis=0,
                    keepdims=True)
            for a in range(a_real)
        ]
        split = occ[0]
        for a in range(1, a_real):
            split = split + occ[a]
        reached = act & (er < INF32) & (e == er)
        eds = jnp.where(act, e, 0)
        return eds, occ, split, reached

    def col_at(D, e, rmin, er, act, jnew, off0, sym, wnd):
        i_new = jnew - off0 - E + tcol                  # [W, 1]
        sub = ((wnd != sym.astype(jnp.int16)) & (wnd != wc16)).astype(dt)
        diag = D + sub
        dele = jnp.concatenate(
            [D[1:], jnp.full((1, R), DINF, dt)], axis=0
        ) + jnp.asarray(1, dt)
        base = jnp.minimum(diag, dele)
        invalid = (i_new < 0) | (i_new > rlen)
        base = jnp.where(invalid, jnp.asarray(DINF, dt), base)
        # exact prefix-min over sublanes (insertion chain); values
        # >= DINF are "infinite" either side of the cap below
        x = base - tcol_d
        k = 1
        while k < W:
            x = jnp.minimum(
                x,
                jnp.concatenate(
                    [jnp.full((k, R), DINF, dt), x[: W - k]], axis=0
                ),
            )
            k *= 2
        Dn = jnp.minimum(
            jnp.minimum(base, x + tcol_d), jnp.asarray(DINF, dt)
        )
        colmin = unmap(jnp.min(Dn, axis=0, keepdims=True))
        rend = unmap(jnp.min(
            jnp.where(i_new == rlen, Dn, jnp.asarray(DINF, dt)),
            axis=0, keepdims=True,
        ))
        rmin_n = jnp.minimum(rmin, rend)
        e_unc = jnp.maximum(e, colmin)
        e_cap = jnp.where(
            er < INF32,
            e,
            jnp.maximum(e, jnp.minimum(colmin, jnp.maximum(e, rmin_n))),
        )
        e_n = jnp.where(et, e_cap, e_unc)
        er_n = jnp.where(
            er < INF32,
            er,
            jnp.where(rmin_n <= e_n, jnp.maximum(e, rmin_n), INF32),
        )
        D2 = jnp.where(act, Dn, D)
        return (
            D2,
            jnp.where(act, e_n, e),
            jnp.where(act, rmin_n, rmin),
            jnp.where(act, er_n, er),
        )

    return window, unmap, stats_at, col_at


def _mkkernel(*, W, R, a_real, E, Wb, Lp, MS, i16, interpret):
    """Build the single-engine kernel body for static geometry.
    ``a_real`` is the true dense-symbol count (the [8, R] occ output is
    zero-padded above it); ``i16`` selects the int16 DP tile."""
    # python scalars (NOT jnp arrays: those would be captured consts,
    # which pallas kernels reject)
    INF32 = int(INF)
    EPS = float(VOTE_EPS)
    dt = jnp.int16 if i16 else jnp.int32
    DINF = DINF16 if i16 else INF32
    roll = _roll_fn(interpret)

    def kernel(
        p_ref, reads_ref, D_ref, e_ref, rmin_ref, er_ref, act_ref,
        rlen_ref,
        Do_ref, eo_ref, rmino_ref, ero_ref,
        eds_ref, occ_ref, split_ref, reached_ref, fin_ref,
        syms_ref, sc_ref, recs_ref, recf_ref,
    ):
        me_budget = p_ref[0]
        other_cost = p_ref[1]
        other_len = p_ref[2]
        min_count = p_ref[3]
        l2 = p_ref[4] != 0
        max_steps = p_ref[5]
        off0 = p_ref[6]
        first_sym = p_ref[7]
        allow_records = p_ref[8] != 0
        clen0 = p_ref[9]
        wc = p_ref[10]
        et = p_ref[11] != 0

        act0 = act_ref[...] != 0       # [1, R] (fixed for this kernel)
        rlen = rlen_ref[...]           # [1, R]
        min_count_f = min_count.astype(jnp.float32)

        _window, unmap, _stats_at, _col_at = _band_ops(
            reads_ref=reads_ref, rlen=rlen, wc=wc, et=et, W=W, R=R, E=E,
            Wb=Wb, Lp=Lp, a_real=a_real, dt=dt, roll=roll,
        )
        act = act0
        window = lambda clen: _window(clen, off0)  # noqa: E731
        stats_at = lambda D, e, rmin, er, clen, wnd: _stats_at(  # noqa: E731
            D, e, rmin, er, act, clen, off0, wnd
        )
        col_at = lambda D, e, rmin, er, jnew, sym, wnd: _col_at(  # noqa: E731
            D, e, rmin, er, act, jnew, off0, sym, wnd
        )

        # ---- forced first push (host-nominated child): vote/priority
        # checks bypassed, only band overflow can refuse it
        D0 = D_ref[...]
        e0 = e_ref[...]
        rmin0 = rmin_ref[...]
        er0 = er_ref[...]
        wnd0 = window(clen0)
        fsym = jnp.maximum(first_sym, 0)
        Df, ef, rminf, erf = col_at(D0, e0, rmin0, er0, clen0 + 1, fsym,
                                    wnd0)
        fovf = jnp.any(act & (ef >= E))
        do_force = (first_sym >= 0) & ~fovf
        sel = lambda n, o: jnp.where(do_force, n, o)  # noqa: E731
        D1, e1, rmin1, er1 = (
            sel(Df, D0), sel(ef, e0), sel(rminf, rmin0), sel(erf, er0)
        )
        clen1 = jnp.where(do_force, clen0 + 1, clen0)
        steps0 = do_force.astype(jnp.int32)
        code0 = jnp.where((first_sym >= 0) & fovf, 5, 0).astype(jnp.int32)

        @pl.when(do_force)
        def _():
            syms_ref[0] = fsym

        def body(carry):
            (D, e, rmin, er, clen, steps, budget, rec_count, _code) = carry
            wnd = window(clen)
            eds, occ, split, reached = stats_at(D, e, rmin, er, clen, wnd)
            fin_v = jnp.where(
                act, jnp.minimum(jnp.maximum(e, rmin), INF32), 0
            )

            costs = jnp.where(l2, eds * eds, eds)
            fin_costs = jnp.where(l2, fin_v * fin_v, fin_v)
            total = jnp.sum(costs)
            fin_total = jnp.sum(fin_costs)
            cost_overflow = l2 & (jnp.max(eds) > 2048)
            fin_max = jnp.max(fin_v)
            fin_ovf_j = fin_max >= E
            fin_cost_ovf = l2 & (fin_max > 2048)
            all_exact = ~jnp.any((split > 0) & ((split & (split - 1)) != 0))
            reached_here = jnp.where(
                et, ~jnp.any(act & ~reached), jnp.any(reached)
            )

            # fractional votes: static per-symbol scalar folds (see
            # _j_run for the f32-vs-f64 EPS contract)
            split_f = jnp.maximum(split, 1).astype(jnp.float32)
            counts = []
            has_votes = []
            for a in range(a_real):
                frac_a = jnp.where(
                    split > 0, occ[a].astype(jnp.float32) / split_f, 0.0
                )
                counts.append(jnp.sum(frac_a))
                has_votes.append(jnp.any(occ[a] > 0))
            n_cands = functools.reduce(
                lambda x, y: x + y,
                [hv.astype(jnp.int32) for hv in has_votes],
            )
            # wildcard removal (host drops it whenever another candidate
            # exists); n_cands keeps the PRE-drop count, as in _j_run
            drop_wc = (wc >= 0) & (n_cands > 1)
            for a in range(a_real):
                is_wc = drop_wc & (wc == a)
                has_votes[a] = has_votes[a] & ~is_wc
                counts[a] = jnp.where(is_wc, 0.0, counts[a])

            maxc = jnp.float32(-1.0)
            for a in range(a_real):
                maxc = jnp.maximum(
                    maxc, jnp.where(has_votes[a], counts[a], -1.0)
                )
            thr = jnp.minimum(min_count_f, maxc)
            npass = jnp.int32(0)
            near_any = jnp.asarray(False)
            best = jnp.float32(-1.0)
            sym = jnp.int32(0)
            for a in range(a_real):
                passing_a = has_votes[a] & (counts[a] >= thr)
                npass = npass + passing_a.astype(jnp.int32)
                near_any = near_any | (
                    has_votes[a] & (jnp.abs(counts[a] - thr) < EPS)
                )
                ca = jnp.where(passing_a, counts[a], -1.0)
                take = ca > best
                sym = jnp.where(take, a, sym)
                best = jnp.where(take, ca, best)
            near_tie = (jnp.abs(maxc - min_count_f) < EPS) | near_any
            ambiguous = ~all_exact & near_tie
            dirty = (
                ambiguous | (npass != 1) | (n_cands == 0) | cost_overflow
            )

            rec_blocked = (
                ~allow_records
                | fin_ovf_j
                | fin_cost_ovf
                | (rec_count >= REC_CAP)
            )
            wins_pop = (total < other_cost) | (
                (total == other_cost) & (clen > other_len)
            )
            code = jnp.where(
                (total > budget) | ~wins_pop,
                3,
                jnp.where(
                    reached_here & rec_blocked,
                    2,
                    jnp.where(
                        dirty,
                        1,
                        jnp.where(steps >= max_steps, 4, 0),
                    ),
                ),
            ).astype(jnp.int32)

            clen2 = clen + 1
            D2, e2, rmin2, er2 = col_at(D, e, rmin, er, clen2, sym, wnd)
            ovf = jnp.any(act & (e2 >= E))
            commit = (code == 0) & ~ovf
            code = jnp.where(code != 0, code, jnp.where(ovf, 5, 0))
            code = code.astype(jnp.int32)

            @pl.when(commit)
            def _():
                syms_ref[steps] = sym

            do_rec = commit & reached_here

            @pl.when(do_rec)
            def _():
                ri = jnp.clip(rec_count, 0, REC_CAP - 1)
                recs_ref[ri] = steps
                base8 = pl.multiple_of((ri // 8) * 8, 8)
                blk = recf_ref[pl.ds(base8, 8), :]
                row = lax.broadcasted_iota(jnp.int32, (8, 1), 0)
                recf_ref[pl.ds(base8, 8), :] = jnp.where(
                    row == (ri % 8), fin_v, blk
                )

            rec_count = rec_count + do_rec.astype(jnp.int32)
            budget = jnp.where(
                do_rec & (fin_total < budget), fin_total, budget
            )
            cm = commit
            return (
                jnp.where(cm, D2, D),
                jnp.where(cm, e2, e),
                jnp.where(cm, rmin2, rmin),
                jnp.where(cm, er2, er),
                jnp.where(cm, clen2, clen),
                steps + cm.astype(jnp.int32),
                budget,
                rec_count,
                code,
            )

        (Dn, en, rminn, ern, clen_f, steps, _budget, rec_count,
         code) = lax.while_loop(
            lambda c: c[8] == 0,
            body,
            (D1, e1, rmin1, er1, clen1, steps0, me_budget, jnp.int32(0),
             code0),
        )

        # ---- final snapshot (stats + finalized) and output writeback
        wndf = window(clen_f)
        eds, occ, split, reached = stats_at(Dn, en, rminn, ern, clen_f,
                                            wndf)
        fin_u = jnp.maximum(en, rminn)
        fin_masked = jnp.where(act, jnp.minimum(fin_u, INF32), 0)
        fin_ovf = jnp.any(act & (fin_u >= E))

        Do_ref[...] = Dn
        eo_ref[...] = en
        rmino_ref[...] = rminn
        ero_ref[...] = ern
        eds_ref[...] = eds
        occ_ref[...] = jnp.concatenate(
            occ + [jnp.zeros((8 - a_real, R), jnp.int32)], axis=0
        )
        split_ref[...] = split
        reached_ref[...] = reached.astype(jnp.int32)
        fin_ref[...] = fin_masked
        sc_ref[0] = steps
        sc_ref[1] = code
        sc_ref[2] = rec_count
        sc_ref[3] = fin_ovf.astype(jnp.int32)
        sc_ref[4] = clen_f

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("num_symbols", "a_real", "MS", "i16", "interpret"),
    donate_argnums=(0,),
)
def _j_run_pallas(
    state: Dict[str, Any], reads_T, rlen, params, wc, et,
    num_symbols: int, a_real: int, MS: int, i16: bool, interpret: bool,
) -> Tuple:
    """Drop-in twin of ``_j_run`` backed by the fused kernel (uniform
    active-offset branches only; the caller guarantees uniformity, the
    VMEM budget, ``C >= clen0 + MS``, and — when ``i16`` — the
    ``i16_ok`` value-range gate).  Same return tuple as ``_j_run``;
    ``params`` is the same ``[10] int32`` upload."""
    h = params[0]
    W = state["D"].shape[2]
    R = state["D"].shape[1]
    C = state["cons"].shape[1]
    E = int((W - 2) // 2)
    Lp = reads_T.shape[0]
    Wb = window_block(W)
    dt = jnp.int16 if i16 else jnp.int32

    D0t = state["D"][h].T                       # [W, R]
    if i16:
        # DINF16 stands in for INF inside the tile; every legitimate
        # finite value is far below it (i16_ok gate), so the mapping
        # round-trips exactly
        D0t = jnp.minimum(D0t, DINF16).astype(dt)
    row = lambda a: a.reshape(1, R)             # noqa: E731
    e0 = row(state["e"][h])
    rmin0 = row(state["rmin"][h])
    er0 = row(state["er"][h])
    act = row(state["act"][h].astype(jnp.int32))
    rlen2 = row(rlen)
    clen0 = state["clen"][h]
    # kernel params: [me_budget, other_cost, other_len, min_count, l2,
    # max_steps, off0, first_sym, allow_records, clen0, wc, et]
    p = jnp.concatenate([
        params[1:10],
        clen0[None],
        jnp.asarray(wc, jnp.int32)[None],
        jnp.asarray(et, jnp.int32)[None],
    ], axis=0)

    kernel = _mkkernel(
        W=W, R=R, a_real=a_real, E=E, Wb=Wb, Lp=Lp, MS=MS,
        i16=i16, interpret=interpret,
    )
    out_shape = (
        jax.ShapeDtypeStruct((W, R), dt),           # D
        jax.ShapeDtypeStruct((1, R), jnp.int32),    # e
        jax.ShapeDtypeStruct((1, R), jnp.int32),    # rmin
        jax.ShapeDtypeStruct((1, R), jnp.int32),    # er
        jax.ShapeDtypeStruct((1, R), jnp.int32),    # eds
        jax.ShapeDtypeStruct((8, R), jnp.int32),    # occ (A rows used)
        jax.ShapeDtypeStruct((1, R), jnp.int32),    # split
        jax.ShapeDtypeStruct((1, R), jnp.int32),    # reached
        jax.ShapeDtypeStruct((1, R), jnp.int32),    # fin_eds
        jax.ShapeDtypeStruct((MS,), jnp.int32),     # syms
        jax.ShapeDtypeStruct((8,), jnp.int32),      # scalars
        jax.ShapeDtypeStruct((REC_CAP,), jnp.int32),    # rec steps
        jax.ShapeDtypeStruct((REC_CAP, R), jnp.int32),  # rec fins
    )
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)  # noqa: E731
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    (Dn, en, rminn, ern, eds, occ8, split, reached, fin_eds, syms,
     scalars, rec_steps, rec_fins) = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[
            smem(), vmem(), vmem(), vmem(), vmem(), vmem(), vmem(),
            vmem(),
        ],
        out_specs=(
            vmem(), vmem(), vmem(), vmem(), vmem(), vmem(), vmem(),
            vmem(), vmem(), smem(), smem(), smem(), vmem(),
        ),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(p, reads_T, D0t, e0, rmin0, er0, act, rlen2)

    steps = scalars[0]
    code = scalars[1]
    rec_count = scalars[2]
    fin_ovf = scalars[3].astype(bool)
    clen_f = scalars[4]

    # caller guarantees clen0 + MS <= C, so the start never clamps.
    # The kernel writes syms[k] only at committed steps, so entries
    # beyond the committed count are UNINITIALIZED TPU memory — mask
    # them back to the row's previous bytes before the splice, making
    # the full cons row bit-identical to the XLA path (which only ever
    # writes committed positions).
    cons_prev = state["cons"][h]
    prev_win = lax.dynamic_slice(cons_prev, (clen0,), (MS,))
    syms = jnp.where(
        jnp.arange(MS, dtype=jnp.int32) < (clen_f - clen0), syms, prev_win
    )
    cons_row = lax.dynamic_update_slice(cons_prev, syms, (clen0,))
    Dn32 = Dn.astype(jnp.int32)
    if i16:
        Dn32 = jnp.where(Dn32 >= DINF16, jnp.int32(INF), Dn32)
    out = dict(state)
    out["D"] = state["D"].at[h].set(Dn32.T)
    out["e"] = state["e"].at[h].set(en[0])
    out["rmin"] = state["rmin"].at[h].set(rminn[0])
    out["er"] = state["er"].at[h].set(ern[0])
    out["cons"] = state["cons"].at[h].set(cons_row)
    out["clen"] = state["clen"].at[h].set(clen_f)
    stats = (
        eds[0], occ8[:num_symbols].T, split[0], reached[0].astype(bool)
    )
    return (
        out, steps, code, stats, cons_row, fin_eds[0], fin_ovf,
        rec_count, rec_steps, rec_fins,
    )


def _mkkernel_dual(*, W, R, a_real, E, Wb, Lp, MS, MCN, IMBN, i16,
                   interpret):
    """Dual twin of :func:`_mkkernel`: both sides advance one symbol per
    iteration with per-side nomination (``_nominate_side`` semantics,
    including the dynamic min-count table), locks, divergence pruning,
    the imbalance table, and two-side record absorption — mirroring
    ``_j_run_dual`` decision-for-decision."""
    INF32 = int(INF)
    EPS = float(VOTE_EPS)
    BIG = 1 << 28
    dt = jnp.int16 if i16 else jnp.int32
    roll = _roll_fn(interpret)

    def kernel(
        p_ref, mc_ref, imb_ref, reads_ref,
        Da_ref, ea_ref, rmina_ref, era_ref, acta_ref,
        Db_ref, eb_ref, rminb_ref, erb_ref, actb_ref,
        rlen_ref,
        Dao_ref, eao_ref, rminao_ref, erao_ref, actao_ref,
        Dbo_ref, ebo_ref, rminbo_ref, erbo_ref, actbo_ref,
        edsa_ref, occa_ref, splita_ref, reacheda_ref,
        edsb_ref, occb_ref, splitb_ref, reachedb_ref,
        symsa_ref, symsb_ref, sc_ref, recs_ref,
        recf1_ref, recf2_ref, reca1_ref, reca2_ref,
    ):
        me_budget = p_ref[0]
        other_cost = p_ref[1]
        other_len = p_ref[2]
        min_count = p_ref[3]
        delta = p_ref[4]
        # p_ref[5] (imb_min) is host-side only, as in _j_run_dual
        l2 = p_ref[6] != 0
        weighted = p_ref[7] != 0
        max_steps = p_ref[8]
        off0a = p_ref[9]
        off0b = p_ref[10]
        lock_a = p_ref[11] != 0
        lock_b = p_ref[12] != 0
        allow_records = p_ref[13] != 0
        rec_min = p_ref[14]
        mc_dyn = p_ref[15] != 0
        clen0a = p_ref[16]
        clen0b = p_ref[17]
        wc = p_ref[18]
        et = p_ref[19] != 0

        rlen = rlen_ref[...]
        window, unmap, stats_at, col_at = _band_ops(
            reads_ref=reads_ref, rlen=rlen, wc=wc, et=et, W=W, R=R, E=E,
            Wb=Wb, Lp=Lp, a_real=a_real, dt=dt, roll=roll,
        )

        def nominate(occ, split, w):
            """_dual_votes + _nominate_side as static scalar folds."""
            voting = (w > 0) & (split > 0)
            split_f = jnp.maximum(split, 1).astype(jnp.float32)
            counts = []
            has_votes = []
            for a in range(a_real):
                voters_a = (occ[a] > 0) & voting
                frac_a = jnp.where(
                    split > 0, occ[a].astype(jnp.float32) / split_f, 0.0
                ) * w
                counts.append(jnp.sum(jnp.where(voters_a, frac_a, 0.0)))
                has_votes.append(jnp.any(voters_a))
            n_cands = functools.reduce(
                lambda x, y: x + y,
                [hv.astype(jnp.int32) for hv in has_votes],
            )
            drop_wc = (wc >= 0) & (n_cands > 1)
            for a in range(a_real):
                is_wc = drop_wc & (wc == a)
                has_votes[a] = has_votes[a] & ~is_wc
                counts[a] = jnp.where(is_wc, 0.0, counts[a])
            # dual semantics recount candidates AFTER the wildcard drop
            n_cands = functools.reduce(
                lambda x, y: x + y,
                [hv.astype(jnp.int32) for hv in has_votes],
            )
            dyadic = (split & (split - 1)) == 0
            exactable = ~jnp.any(voting & ~dyadic) & ~weighted

            n_vote_f = functools.reduce(lambda x, y: x + y, counts)
            n_vote_r = jnp.round(n_vote_f)
            int_ok = jnp.abs(n_vote_f - n_vote_r) < EPS
            tab_bad = mc_dyn & ~int_ok
            exactable = exactable & ~tab_bad
            mc = mc_ref[jnp.clip(n_vote_r.astype(jnp.int32), 0, MCN - 1)]
            mc_f = mc.astype(jnp.float32)
            maxc = jnp.float32(-1.0)
            for a in range(a_real):
                maxc = jnp.maximum(
                    maxc, jnp.where(has_votes[a], counts[a], -1.0)
                )
            thr = jnp.minimum(mc_f, maxc)
            npass = jnp.int32(0)
            near_any = jnp.asarray(False)
            best = jnp.float32(-1.0)
            sym = jnp.int32(0)
            for a in range(a_real):
                passing_a = has_votes[a] & (counts[a] >= thr)
                npass = npass + passing_a.astype(jnp.int32)
                near_any = near_any | (
                    has_votes[a] & (jnp.abs(counts[a] - thr) < EPS)
                )
                ca = jnp.where(passing_a, counts[a], -1.0)
                take = ca > best
                sym = jnp.where(take, a, sym)
                best = jnp.where(take, ca, best)
            near_tie = (jnp.abs(maxc - mc_f) < EPS) | near_any
            ambiguous = ~exactable & near_tie
            dirty = (
                ambiguous | (npass != 1) | (n_cands == 0) | tab_bad
            )
            return dirty, sym

        def body(carry):
            (Da, ea, rmina, era, acta, clena,
             Db, eb, rminb, erb, actb, clenb,
             steps, budget, rec_count, _code) = carry
            wnda = window(clena, off0a)
            wndb = window(clenb, off0b)
            edsa, occa, splita, reacheda = stats_at(
                Da, ea, rmina, era, acta, clena, off0a, wnda
            )
            edsb, occb, splitb, reachedb = stats_at(
                Db, eb, rminb, erb, actb, clenb, off0b, wndb
            )

            # total node cost = per read, best over its tracked sides
            ca_c = jnp.where(l2, edsa * edsa, edsa)
            cb_c = jnp.where(l2, edsb * edsb, edsb)
            best_c = jnp.minimum(
                jnp.where(acta, ca_c, BIG), jnp.where(actb, cb_c, BIG)
            )
            total = jnp.sum(jnp.where(acta | actb, best_c, 0))
            cost_overflow = l2 & (
                jnp.maximum(
                    jnp.max(jnp.where(acta, edsa, 0)),
                    jnp.max(jnp.where(actb, edsb, 0)),
                )
                > 2048
            )

            # per-read vote weights (reference get_ed_weights semantics;
            # unweighted nomination uses full weight per tracked read)
            both = acta & actb
            c1f = jnp.maximum(edsa.astype(jnp.float32), 0.5)
            c2f = jnp.maximum(edsb.astype(jnp.float32), 0.5)
            denom = c1f + c2f
            wa_soft = jnp.where(
                both, c2f / denom, jnp.where(acta, 1.0, 0.0)
            )
            wb_soft = jnp.where(
                both, c1f / denom, jnp.where(actb, 1.0, 0.0)
            )
            wa = jnp.where(weighted, wa_soft, jnp.where(acta, 1.0, 0.0))
            wb = jnp.where(weighted, wb_soft, jnp.where(actb, 1.0, 0.0))

            dirty_a, sym_a = nominate(occa, splita, wa)
            dirty_b, sym_b = nominate(occb, splitb, wb)
            # a locked side never arbitrates
            dirty_a = dirty_a & ~lock_a
            dirty_b = dirty_b & ~lock_b

            reached_read = (acta & reacheda) | (actb & reachedb)
            fin_a = jnp.where(
                et, ~jnp.any(~(reacheda | ~acta)),
                jnp.any(acta & reacheda),
            )
            fin_b = jnp.where(
                et, ~jnp.any(~(reachedb | ~actb)),
                jnp.any(actb & reachedb),
            )
            # CONSERVATIVE completion fold (see _j_run_dual)
            reached_stop = jnp.where(
                et, ~jnp.any(~(reached_read | (~acta & ~actb))),
                jnp.any(reached_read),
            )
            cur_len = jnp.maximum(clena, clenb)
            wins_pop = (total < other_cost) | (
                (total == other_cost) & (cur_len > other_len)
            )

            # record eval of THIS (pre-push) state (_finalize mirror)
            fu1 = jnp.maximum(ea, rmina)
            fu2 = jnp.maximum(eb, rminb)
            fo1 = jnp.any(acta & (fu1 >= E))
            fo2 = jnp.any(actb & (fu2 >= E))
            fin1_j = jnp.where(acta, jnp.minimum(fu1, INF32), 0)
            fin2_j = jnp.where(actb, jnp.minimum(fu2, INF32), 0)
            fc1 = jnp.where(l2, fin1_j * fin1_j, fin1_j)
            fc2 = jnp.where(l2, fin2_j * fin2_j, fin2_j)
            side0 = acta & (~actb | (fc1 <= fc2))
            any_act = acta | actb
            fin_total = jnp.sum(
                jnp.where(any_act, jnp.where(side0, fc1, fc2), 0)
            )
            count0 = jnp.sum((side0 & any_act).astype(jnp.int32))
            count1 = jnp.sum(any_act.astype(jnp.int32)) - count0
            rec_imbalanced = (count0 < rec_min) | (count1 < rec_min)
            fin_cost_ovf = l2 & (
                jnp.maximum(
                    jnp.max(jnp.where(acta, fin1_j, 0)),
                    jnp.max(jnp.where(actb, fin2_j, 0)),
                )
                > 2048
            )
            rec_blocked = (
                ~allow_records | fo1 | fo2 | fin_cost_ovf
                | (rec_count >= REC_CAP)
            )

            code = jnp.where(
                (total > budget) | ~wins_pop,
                3,
                jnp.where(
                    reached_stop & rec_blocked,
                    2,
                    jnp.where(
                        dirty_a
                        | dirty_b
                        | (fin_a & ~lock_a)
                        | (fin_b & ~lock_b)
                        | cost_overflow,
                        1,
                        jnp.where(steps >= max_steps, 4, 0),
                    ),
                ),
            ).astype(jnp.int32)

            Da2, ea2, rmina2, era2 = col_at(
                Da, ea, rmina, era, acta, clena + 1, off0a, sym_a, wnda
            )
            Db2, eb2, rminb2, erb2 = col_at(
                Db, eb, rminb, erb, actb, clenb + 1, off0b, sym_b, wndb
            )
            # locked sides are frozen: discard their column step
            frz = lambda lock, new, old: jnp.where(lock, old, new)  # noqa: E731
            Da2 = frz(lock_a, Da2, Da)
            ea2 = frz(lock_a, ea2, ea)
            rmina2 = frz(lock_a, rmina2, rmina)
            era2 = frz(lock_a, era2, era)
            Db2 = frz(lock_b, Db2, Db)
            eb2 = frz(lock_b, eb2, eb)
            rminb2 = frz(lock_b, rminb2, rminb)
            erb2 = frz(lock_b, erb2, erb)
            ovf = jnp.any((acta & (ea2 >= E)) | (actb & (eb2 >= E)))

            # divergence pruning on post-push distances
            both2 = acta & actb
            acta2 = acta & ~(both2 & (eb2 + delta < ea2))
            actb2 = actb & ~(both2 & (ea2 + delta < eb2))
            imb_v = imb_ref[jnp.clip(cur_len + 1, 0, IMBN - 1)]
            imb = (
                jnp.sum(acta2.astype(jnp.int32)) < imb_v
            ) | (jnp.sum(actb2.astype(jnp.int32)) < imb_v)

            commit = (code == 0) & ~ovf
            code = jnp.where(
                code != 0,
                code,
                jnp.where(ovf, 5, jnp.where(imb, 6, 0)),
            ).astype(jnp.int32)

            @pl.when(commit & ~lock_a)
            def _():
                symsa_ref[steps] = sym_a

            @pl.when(commit & ~lock_b)
            def _():
                symsb_ref[steps] = sym_b

            do_rec = commit & reached_stop

            @pl.when(do_rec)
            def _():
                ri = jnp.clip(rec_count, 0, REC_CAP - 1)
                recs_ref[ri] = steps
                base8 = pl.multiple_of((ri // 8) * 8, 8)
                row = lax.broadcasted_iota(jnp.int32, (8, 1), 0)
                mask = row == (ri % 8)
                for ref, val in (
                    (recf1_ref, fin1_j),
                    (recf2_ref, fin2_j),
                    (reca1_ref, acta.astype(jnp.int32)),
                    (reca2_ref, actb.astype(jnp.int32)),
                ):
                    blk = ref[pl.ds(base8, 8), :]
                    ref[pl.ds(base8, 8), :] = jnp.where(mask, val, blk)

            rec_count = rec_count + do_rec.astype(jnp.int32)
            budget = jnp.where(
                do_rec & ~rec_imbalanced & (fin_total < budget),
                fin_total,
                budget,
            )
            cm = commit
            sel = lambda new, old: jnp.where(cm, new, old)  # noqa: E731
            return (
                sel(Da2, Da), sel(ea2, ea), sel(rmina2, rmina),
                sel(era2, era), sel(acta2, acta),
                jnp.where(cm & ~lock_a, clena + 1, clena),
                sel(Db2, Db), sel(eb2, eb), sel(rminb2, rminb),
                sel(erb2, erb), sel(actb2, actb),
                jnp.where(cm & ~lock_b, clenb + 1, clenb),
                steps + cm.astype(jnp.int32),
                budget,
                rec_count,
                code,
            )

        init = (
            Da_ref[...], ea_ref[...], rmina_ref[...], era_ref[...],
            acta_ref[...] != 0, clen0a,
            Db_ref[...], eb_ref[...], rminb_ref[...], erb_ref[...],
            actb_ref[...] != 0, clen0b,
            jnp.int32(0), me_budget, jnp.int32(0), jnp.int32(0),
        )
        (Da, ea, rmina, era, acta, clena,
         Db, eb, rminb, erb, actb, clenb,
         steps, _budget, rec_count, code) = lax.while_loop(
            lambda c: c[15] == 0, body, init
        )

        wnda = window(clena, off0a)
        wndb = window(clenb, off0b)
        edsa, occa, splita, reacheda = stats_at(
            Da, ea, rmina, era, acta, clena, off0a, wnda
        )
        edsb, occb, splitb, reachedb = stats_at(
            Db, eb, rminb, erb, actb, clenb, off0b, wndb
        )

        pad = [jnp.zeros((8 - a_real, R), jnp.int32)]
        Dao_ref[...] = Da
        eao_ref[...] = ea
        rminao_ref[...] = rmina
        erao_ref[...] = era
        actao_ref[...] = acta.astype(jnp.int32)
        Dbo_ref[...] = Db
        ebo_ref[...] = eb
        rminbo_ref[...] = rminb
        erbo_ref[...] = erb
        actbo_ref[...] = actb.astype(jnp.int32)
        edsa_ref[...] = edsa
        occa_ref[...] = jnp.concatenate(occa + pad, axis=0)
        splita_ref[...] = splita
        reacheda_ref[...] = reacheda.astype(jnp.int32)
        edsb_ref[...] = edsb
        occb_ref[...] = jnp.concatenate(occb + pad, axis=0)
        splitb_ref[...] = splitb
        reachedb_ref[...] = reachedb.astype(jnp.int32)
        sc_ref[0] = steps
        sc_ref[1] = code
        sc_ref[2] = rec_count
        sc_ref[3] = clena
        sc_ref[4] = clenb

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("num_symbols", "a_real", "MS", "i16", "interpret"),
    donate_argnums=(0,),
)
def _j_run_dual_pallas(
    state: Dict[str, Any], reads_T, rlen, params, mc_tab, imb_tab, wc,
    et, num_symbols: int, a_real: int, MS: int, i16: bool,
    interpret: bool,
) -> Tuple:
    """Drop-in twin of ``_j_run_dual`` backed by the fused dual kernel
    (uniform offsets both sides; caller guarantees the VMEM budget and
    ``C >= max(clen0) + MS``).  Same return tuple as ``_j_run_dual``;
    ``params`` is the same ``[18] int32`` upload."""
    ha = params[0]
    hb = params[1]
    W = state["D"].shape[2]
    R = state["D"].shape[1]
    E = int((W - 2) // 2)
    Lp = reads_T.shape[0]
    Wb = window_block(W)
    dt = jnp.int16 if i16 else jnp.int32

    def side(h):
        D = state["D"][h].T
        if i16:
            D = jnp.minimum(D, DINF16).astype(dt)
        return (
            D,
            state["e"][h].reshape(1, R),
            state["rmin"][h].reshape(1, R),
            state["er"][h].reshape(1, R),
            state["act"][h].astype(jnp.int32).reshape(1, R),
        )

    Da0, ea0, rmina0, era0, acta0 = side(ha)
    Db0, eb0, rminb0, erb0, actb0 = side(hb)
    clen0a = state["clen"][ha]
    clen0b = state["clen"][hb]
    # kernel params: _j_run_dual's params[2:18] + clen0a/b + wc + et
    p = jnp.concatenate([
        params[2:18],
        clen0a[None],
        clen0b[None],
        jnp.asarray(wc, jnp.int32)[None],
        jnp.asarray(et, jnp.int32)[None],
    ], axis=0)

    kernel = _mkkernel_dual(
        W=W, R=R, a_real=a_real, E=E, Wb=Wb, Lp=Lp, MS=MS,
        MCN=int(mc_tab.shape[0]), IMBN=int(imb_tab.shape[0]), i16=i16,
        interpret=interpret,
    )
    vec = lambda: jax.ShapeDtypeStruct((1, R), jnp.int32)  # noqa: E731
    out_shape = (
        jax.ShapeDtypeStruct((W, R), dt), vec(), vec(), vec(), vec(),
        jax.ShapeDtypeStruct((W, R), dt), vec(), vec(), vec(), vec(),
        vec(), jax.ShapeDtypeStruct((8, R), jnp.int32), vec(), vec(),
        vec(), jax.ShapeDtypeStruct((8, R), jnp.int32), vec(), vec(),
        jax.ShapeDtypeStruct((MS,), jnp.int32),
        jax.ShapeDtypeStruct((MS,), jnp.int32),
        jax.ShapeDtypeStruct((8,), jnp.int32),
        jax.ShapeDtypeStruct((REC_CAP,), jnp.int32),
        jax.ShapeDtypeStruct((REC_CAP, R), jnp.int32),
        jax.ShapeDtypeStruct((REC_CAP, R), jnp.int32),
        jax.ShapeDtypeStruct((REC_CAP, R), jnp.int32),
        jax.ShapeDtypeStruct((REC_CAP, R), jnp.int32),
    )
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)  # noqa: E731
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)  # noqa: E731
    (Da, ea, rmina, era, acta, Db, eb, rminb, erb, actb,
     edsa, occa8, splita, reacheda, edsb, occb8, splitb, reachedb,
     symsa, symsb, scalars, rec_steps, rec_f1, rec_f2, rec_a1,
     rec_a2) = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[
            smem(), smem(), smem(), vmem(),
            vmem(), vmem(), vmem(), vmem(), vmem(),
            vmem(), vmem(), vmem(), vmem(), vmem(),
            vmem(),
        ],
        out_specs=(
            vmem(), vmem(), vmem(), vmem(), vmem(),
            vmem(), vmem(), vmem(), vmem(), vmem(),
            vmem(), vmem(), vmem(), vmem(),
            vmem(), vmem(), vmem(), vmem(),
            smem(), smem(), smem(), smem(),
            vmem(), vmem(), vmem(), vmem(),
        ),
        input_output_aliases={4: 0, 9: 5},
        interpret=interpret,
    )(p, mc_tab, imb_tab, reads_T,
      Da0, ea0, rmina0, era0, acta0,
      Db0, eb0, rminb0, erb0, actb0,
      rlen.reshape(1, R))

    steps = scalars[0]
    code = scalars[1]
    rec_count = scalars[2]
    clena_f = scalars[3]
    clenb_f = scalars[4]

    def unmapD(D):
        D32 = D.astype(jnp.int32)
        if i16:
            D32 = jnp.where(D32 >= DINF16, jnp.int32(INF), D32)
        return D32.T

    # symsa/symsb are written only at committed steps; entries past the
    # committed count are uninitialized SMEM — mask them back to the
    # previous cons bytes so the rows stay bit-identical to the XLA path.
    ms_iota = jnp.arange(MS, dtype=jnp.int32)
    consa_prev = state["cons"][ha]
    consb_prev = state["cons"][hb]
    symsa = jnp.where(
        ms_iota < (clena_f - clen0a),
        symsa,
        lax.dynamic_slice(consa_prev, (clen0a,), (MS,)),
    )
    symsb = jnp.where(
        ms_iota < (clenb_f - clen0b),
        symsb,
        lax.dynamic_slice(consb_prev, (clen0b,), (MS,)),
    )
    consa_row = lax.dynamic_update_slice(consa_prev, symsa, (clen0a,))
    consb_row = lax.dynamic_update_slice(consb_prev, symsb, (clen0b,))
    acta_b = acta[0].astype(bool)
    actb_b = actb[0].astype(bool)
    out = dict(state)
    out["D"] = state["D"].at[ha].set(unmapD(Da)).at[hb].set(unmapD(Db))
    out["e"] = state["e"].at[ha].set(ea[0]).at[hb].set(eb[0])
    out["rmin"] = state["rmin"].at[ha].set(rmina[0]).at[hb].set(rminb[0])
    out["er"] = state["er"].at[ha].set(era[0]).at[hb].set(erb[0])
    out["act"] = state["act"].at[ha].set(acta_b).at[hb].set(actb_b)
    out["cons"] = (
        state["cons"].at[ha].set(consa_row).at[hb].set(consb_row)
    )
    out["clen"] = state["clen"].at[ha].set(clena_f).at[hb].set(clenb_f)
    stats_a = (
        edsa[0], occa8[:num_symbols].T, splita[0],
        reacheda[0].astype(bool),
    )
    stats_b = (
        edsb[0], occb8[:num_symbols].T, splitb[0],
        reachedb[0].astype(bool),
    )
    return (
        out, steps, code, stats_a, stats_b, acta_b, actb_b,
        consa_row, consb_row, rec_count, rec_steps, rec_f1, rec_f2,
        rec_a1.astype(bool), rec_a2.astype(bool),
    )
