"""ctypes bindings for the native (C++) kernels and engine.

Builds ``src/waffle_native.cpp`` with g++ on first use (cached shared
object next to the sources).  Provides:

* :class:`NativeScorer` — the C++ implementation of the
  :class:`~waffle_con_tpu.ops.scorer.WavefrontScorer` seam
  (``backend="native"``);
* :func:`native_consensus` — the complete C++ single-consensus engine,
  used as the CPU baseline by ``bench.py``;
* :func:`native_wfa_ed` — one-shot edit distance.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import struct
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.ops.scorer import BranchStats, WavefrontScorer
from waffle_con_tpu.analysis import lockcheck

_HERE = pathlib.Path(__file__).resolve().parent
_SRC = _HERE / "src" / "waffle_native.cpp"
_LIB = _HERE / "_libwaffle.so"
_LOCK = lockcheck.make_lock("native.BUILD")
_lib: Optional[ctypes.CDLL] = None

_I64 = ctypes.c_longlong
_I64P = ctypes.POINTER(_I64)
_U8P = ctypes.POINTER(ctypes.c_uint8)


class NativeBuildError(RuntimeError):
    pass


def _build() -> None:
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        str(_SRC),
        "-o",
        str(_LIB),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{proc.stderr[-4000:]}"
        )


def load_library() -> ctypes.CDLL:
    global _lib
    with _LOCK:
        if _lib is not None:
            return _lib
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            _build()
        lib = ctypes.CDLL(str(_LIB))

        lib.wn_scorer_new.restype = ctypes.c_void_p
        lib.wn_scorer_new.argtypes = [
            _U8P, _I64P, _I64, _U8P, _I64, ctypes.c_int, ctypes.c_int,
        ]
        lib.wn_scorer_free.argtypes = [ctypes.c_void_p]
        lib.wn_root.restype = _I64
        lib.wn_root.argtypes = [ctypes.c_void_p, _U8P]
        lib.wn_clone.restype = _I64
        lib.wn_clone.argtypes = [ctypes.c_void_p, _I64]
        lib.wn_free_branch.argtypes = [ctypes.c_void_p, _I64]
        lib.wn_push.argtypes = [
            ctypes.c_void_p, _I64, _U8P, _I64, _I64P, _I64P, _I64P, _U8P,
        ]
        lib.wn_stats.argtypes = lib.wn_push.argtypes
        lib.wn_activate.argtypes = [
            ctypes.c_void_p, _I64, _I64, _I64, _U8P, _I64,
        ]
        lib.wn_deactivate.argtypes = [ctypes.c_void_p, _I64, _I64]
        lib.wn_finalized_eds.argtypes = [
            ctypes.c_void_p, _I64, _U8P, _I64, _I64P,
        ]
        lib.wn_wfa_ed.restype = _I64
        lib.wn_wfa_ed.argtypes = [
            _U8P, _I64, _U8P, _I64, ctypes.c_int, ctypes.c_int,
        ]
        lib.wn_consensus.restype = ctypes.c_int
        lib.wn_consensus.argtypes = [
            _U8P, _I64P, _I64, _I64P, _I64P, ctypes.c_double,
            ctypes.POINTER(_U8P), _I64P,
        ]
        lib.wn_dual_consensus.restype = ctypes.c_int
        lib.wn_dual_consensus.argtypes = [
            _U8P, _I64P, _I64, _I64P, _I64P, ctypes.c_double,
            ctypes.POINTER(_U8P), _I64P,
        ]
        lib.wn_priority_consensus.restype = ctypes.c_int
        lib.wn_priority_consensus.argtypes = [
            _U8P, _I64P, _I64, _I64, _I64P, _I64P, _I64P, ctypes.c_double,
            ctypes.POINTER(_U8P), _I64P,
        ]
        lib.wn_blob_free.argtypes = [_U8P]
        _lib = lib
        return lib


def _bytes_ptr(data: bytes):
    return ctypes.cast(ctypes.create_string_buffer(data, len(data)), _U8P)


def _pack_reads(reads: Sequence[bytes]):
    blob = b"".join(reads)
    lens = np.array([len(r) for r in reads], dtype=np.int64)
    return (
        _bytes_ptr(blob),
        lens.ctypes.data_as(_I64P),
        lens,  # keep alive
    )


class NativeScorer(WavefrontScorer):
    """C++ branch store behind the scorer seam."""

    def __init__(self, reads: Sequence[bytes], config: CdwfaConfig) -> None:
        super().__init__(reads, config)
        self._lib = load_library()
        data_ptr, lens_ptr, self._keep = _pack_reads(self.reads)
        symtab = np.asarray(self.symtab, dtype=np.uint8)
        self._ptr = self._lib.wn_scorer_new(
            data_ptr,
            lens_ptr,
            len(self.reads),
            symtab.ctypes.data_as(_U8P),
            len(symtab),
            -1 if config.wildcard is None else config.wildcard,
            1 if config.allow_early_termination else 0,
        )

    def __del__(self):
        try:
            if getattr(self, "_ptr", None):
                self._lib.wn_scorer_free(self._ptr)
                self._ptr = None
        except Exception:
            pass

    def _out_buffers(self):
        n, a = self.num_reads, self.num_symbols
        eds = np.zeros(n, dtype=np.int64)
        occ = np.zeros((n, a), dtype=np.int64)
        split = np.zeros(n, dtype=np.int64)
        reached = np.zeros(n, dtype=np.uint8)
        return eds, occ, split, reached

    def root(self, active: np.ndarray) -> int:
        act = np.ascontiguousarray(active, dtype=np.uint8)
        return self._lib.wn_root(self._ptr, act.ctypes.data_as(_U8P))

    def clone(self, h: int) -> int:
        return self._lib.wn_clone(self._ptr, h)

    def free(self, h: int) -> None:
        self._lib.wn_free_branch(self._ptr, h)

    def push(self, h: int, consensus: bytes) -> BranchStats:
        eds, occ, split, reached = self._out_buffers()
        self._lib.wn_push(
            self._ptr, h, _bytes_ptr(consensus), len(consensus),
            eds.ctypes.data_as(_I64P), occ.ctypes.data_as(_I64P),
            split.ctypes.data_as(_I64P), reached.ctypes.data_as(_U8P),
        )
        return BranchStats(eds, occ, split, reached.astype(bool))

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        eds, occ, split, reached = self._out_buffers()
        self._lib.wn_stats(
            self._ptr, h, _bytes_ptr(consensus), len(consensus),
            eds.ctypes.data_as(_I64P), occ.ctypes.data_as(_I64P),
            split.ctypes.data_as(_I64P), reached.ctypes.data_as(_U8P),
        )
        return BranchStats(eds, occ, split, reached.astype(bool))

    def activate(self, h: int, read_index: int, offset: int, consensus: bytes) -> None:
        self._lib.wn_activate(
            self._ptr, h, read_index, offset, _bytes_ptr(consensus), len(consensus)
        )

    def deactivate(self, h: int, read_index: int) -> None:
        self._lib.wn_deactivate(self._ptr, h, read_index)

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        eds = np.zeros(self.num_reads, dtype=np.int64)
        self._lib.wn_finalized_eds(
            self._ptr, h, _bytes_ptr(consensus), len(consensus),
            eds.ctypes.data_as(_I64P),
        )
        return eds


def native_wfa_ed(
    v1: bytes, v2: bytes, require_both_end: bool = True,
    wildcard: Optional[int] = None,
) -> int:
    lib = load_library()
    return lib.wn_wfa_ed(
        _bytes_ptr(v1), len(v1), _bytes_ptr(v2), len(v2),
        1 if require_both_end else 0,
        -1 if wildcard is None else wildcard,
    )


_ENGINE_ERRORS = {
    1: "Must have at least one initial offset of None to see the consensus.",
    2: "Encountered coverage gap",  # detail-less fallback; the engine
    # normally attaches [top_len, max_activate] for the full message
    3: "Finalize called on DWFA that was never initialized.",
    4: "internal invariant violated: activating an already-active read",
}


class _BlobReader:
    def __init__(self, raw: bytes) -> None:
        self.raw = raw
        self.pos = 0

    def i64(self) -> int:
        (v,) = struct.unpack_from("<q", self.raw, self.pos)
        self.pos += 8
        return v

    def data(self) -> bytes:
        n = self.i64()
        out = self.raw[self.pos : self.pos + n]
        self.pos += n
        return out

    def vec(self) -> List[int]:
        return [self.i64() for _ in range(self.i64())]


def _int_cfg_base(cfg: CdwfaConfig) -> List[int]:
    return [
        1 if cfg.consensus_cost is ConsensusCost.L2_DISTANCE else 0,
        cfg.max_queue_size,
        cfg.max_capacity_per_size,
        cfg.max_return_size,
        cfg.max_nodes_wo_constraint,
        cfg.min_count,
        -1 if cfg.wildcard is None else cfg.wildcard,
        1 if cfg.allow_early_termination else 0,
        1 if cfg.auto_shift_offsets else 0,
        cfg.offset_window,
        cfg.offset_compare_length,
    ]


def _int_cfg_dual(cfg: CdwfaConfig) -> np.ndarray:
    return np.array(
        _int_cfg_base(cfg)
        + [1 if cfg.weighted_by_ed else 0, cfg.dual_max_ed_delta],
        dtype=np.int64,
    )


def _check_offsets(offsets, n: int, what: str = "offsets"):
    from waffle_con_tpu.models.consensus import EngineError

    if len(offsets) != n:
        raise EngineError(
            f"{what} must have one entry per sequence "
            f"({len(offsets)} != {n})"
        )


def _call_blob(fn, *args):
    """Invoke a blob-returning engine entry; raises EngineError on rc != 0.

    Error rc 2 (coverage gap) carries a 2x i64 detail blob so the raised
    message matches the reference exactly, lengths included
    (``/root/reference/src/consensus.rs:305``)."""
    from waffle_con_tpu.models.consensus import EngineError

    lib = load_library()
    blob = _U8P()
    size = _I64(0)
    rc = fn(lib, *args, ctypes.byref(blob), ctypes.byref(size))
    if rc != 0:
        detail = b""
        if blob and size.value > 0:
            detail = ctypes.string_at(blob, size.value)
            lib.wn_blob_free(blob)
        if rc == 2 and len(detail) == 16:
            top_len, max_activate = struct.unpack("<qq", detail)
            raise EngineError(
                f"Encountered coverage gap: consensus is length {top_len} "
                f"with no candidates, but sequences activate at {max_activate}"
            )
        raise EngineError(_ENGINE_ERRORS.get(rc, f"native engine error {rc}"))
    try:
        return ctypes.string_at(blob, size.value)
    finally:
        lib.wn_blob_free(blob)


def _read_dual_results(reader: "_BlobReader", cost: ConsensusCost):
    """Decode the dual-result blob into DualConsensus objects."""
    from waffle_con_tpu.models.consensus import Consensus
    from waffle_con_tpu.models.dual_consensus import DualConsensus

    results = []
    n_results = reader.i64()
    for _ in range(n_results):
        cons1 = reader.data()
        has2 = reader.i64()
        cons2 = reader.data() if has2 else None
        n = reader.i64()
        is_cons1 = [bool(reader.i64()) for _ in range(n)]
        scores1 = [None if v < 0 else v for v in reader.vec()]
        scores2 = [None if v < 0 else v for v in reader.vec()]
        c1_scores = reader.vec()
        c2_scores = reader.vec()
        c1 = Consensus(cons1, cost, c1_scores)
        c2 = Consensus(cons2, cost, c2_scores) if has2 else None
        results.append(
            DualConsensus(c1, c2, is_cons1, scores1, scores2)
        )
    return results


def native_dual_consensus(
    reads: Sequence[bytes],
    offsets: Optional[Sequence[Optional[int]]] = None,
    config: Optional[CdwfaConfig] = None,
):
    """Run the full C++ dual-consensus engine; returns the same
    ``List[DualConsensus]`` the Python/JAX engines produce."""
    cfg = config if config is not None else CdwfaConfig()
    if offsets is None:
        offsets = [None] * len(reads)
    _check_offsets(offsets, len(reads))
    data_ptr, lens_ptr, _keep = _pack_reads([bytes(r) for r in reads])
    offs = np.array([-1 if o is None else o for o in offsets], dtype=np.int64)
    int_cfg = _int_cfg_dual(cfg)

    raw = _call_blob(
        lambda lib, *a: lib.wn_dual_consensus(*a),
        data_ptr, lens_ptr, len(reads), offs.ctypes.data_as(_I64P),
        int_cfg.ctypes.data_as(_I64P), cfg.min_af,
    )
    return _read_dual_results(_BlobReader(raw), cfg.consensus_cost)


def native_priority_consensus(
    chains: Sequence[Sequence[bytes]],
    offsets: Optional[Sequence[Sequence[Optional[int]]]] = None,
    seed_groups: Optional[Sequence[Optional[int]]] = None,
    config: Optional[CdwfaConfig] = None,
):
    """Run the full C++ priority (chained multi) consensus engine; returns
    the same ``PriorityConsensus`` the Python engine produces."""
    from waffle_con_tpu.models.consensus import Consensus, EngineError
    from waffle_con_tpu.models.priority_consensus import PriorityConsensus

    cfg = config if config is not None else CdwfaConfig()
    if not chains:
        raise EngineError("Must provide a non-empty sequences Vec")
    n_levels = len(chains[0])
    if n_levels == 0:
        raise EngineError("Must provide a non-empty sequences Vec")
    for chain in chains:
        if len(chain) != n_levels:
            raise EngineError(
                f"Expected sequences Vec of length {n_levels}, "
                f"but got one of length {len(chain)}"
            )
    if offsets is None:
        offsets = [[None] * n_levels for _ in chains]
    if seed_groups is None:
        seed_groups = [None] * len(chains)
    _check_offsets(offsets, len(chains), "offset chains")
    for offset_chain in offsets:
        _check_offsets(offset_chain, n_levels, "offset chain levels")
    _check_offsets(seed_groups, len(chains), "seed_groups")

    flat = b"".join(bytes(s) for chain in chains for s in chain)
    lens = np.array(
        [len(s) for chain in chains for s in chain], dtype=np.int64
    )
    offs = np.array(
        [
            -1 if o is None else o
            for offset_chain in offsets
            for o in offset_chain
        ],
        dtype=np.int64,
    )
    seeds = np.array(
        [-1 if s is None else s for s in seed_groups], dtype=np.int64
    )
    int_cfg = _int_cfg_dual(cfg)

    raw = _call_blob(
        lambda lib, *a: lib.wn_priority_consensus(*a),
        _bytes_ptr(flat), lens.ctypes.data_as(_I64P), len(chains), n_levels,
        offs.ctypes.data_as(_I64P), seeds.ctypes.data_as(_I64P),
        int_cfg.ctypes.data_as(_I64P), cfg.min_af,
    )
    reader = _BlobReader(raw)
    out_chains = []
    for _ in range(reader.i64()):
        chain = []
        for _ in range(reader.i64()):
            seq = reader.data()
            scores = reader.vec()
            chain.append(Consensus(seq, cfg.consensus_cost, scores))
        out_chains.append(chain)
    indices = reader.vec()
    return PriorityConsensus(out_chains, indices)


def native_consensus(
    reads: Sequence[bytes],
    offsets: Optional[Sequence[Optional[int]]] = None,
    config: Optional[CdwfaConfig] = None,
) -> List[Tuple[bytes, List[int]]]:
    """Run the full C++ single-consensus engine; returns
    ``[(sequence, scores), ...]`` sorted lexicographically."""
    cfg = config if config is not None else CdwfaConfig()
    if offsets is None:
        offsets = [None] * len(reads)
    _check_offsets(offsets, len(reads))
    data_ptr, lens_ptr, _keep = _pack_reads([bytes(r) for r in reads])
    offs = np.array(
        [-1 if o is None else o for o in offsets], dtype=np.int64
    )
    int_cfg = np.array(_int_cfg_base(cfg), dtype=np.int64)
    raw = _call_blob(
        lambda lib, *a: lib.wn_consensus(*a),
        data_ptr, lens_ptr, len(reads), offs.ctypes.data_as(_I64P),
        int_cfg.ctypes.data_as(_I64P), cfg.min_af,
    )

    reader = _BlobReader(raw)
    results = []
    for _ in range(reader.i64()):
        sequence = reader.data()
        scores = reader.vec()
        results.append((sequence, scores))
    return results
