"""ctypes bindings for the native (C++) kernels and engine.

Builds ``src/waffle_native.cpp`` with g++ on first use (cached shared
object next to the sources).  Provides:

* :class:`NativeScorer` — the C++ implementation of the
  :class:`~waffle_con_tpu.ops.scorer.WavefrontScorer` seam
  (``backend="native"``);
* :func:`native_consensus` — the complete C++ single-consensus engine,
  used as the CPU baseline by ``bench.py``;
* :func:`native_wfa_ed` — one-shot edit distance.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import struct
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from waffle_con_tpu.config import CdwfaConfig, ConsensusCost
from waffle_con_tpu.ops.scorer import BranchStats, WavefrontScorer

_HERE = pathlib.Path(__file__).resolve().parent
_SRC = _HERE / "src" / "waffle_native.cpp"
_LIB = _HERE / "_libwaffle.so"
_LOCK = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_I64 = ctypes.c_longlong
_I64P = ctypes.POINTER(_I64)
_U8P = ctypes.POINTER(ctypes.c_uint8)


class NativeBuildError(RuntimeError):
    pass


def _build() -> None:
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        str(_SRC),
        "-o",
        str(_LIB),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{proc.stderr[-4000:]}"
        )


def load_library() -> ctypes.CDLL:
    global _lib
    with _LOCK:
        if _lib is not None:
            return _lib
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            _build()
        lib = ctypes.CDLL(str(_LIB))

        lib.wn_scorer_new.restype = ctypes.c_void_p
        lib.wn_scorer_new.argtypes = [
            _U8P, _I64P, _I64, _U8P, _I64, ctypes.c_int, ctypes.c_int,
        ]
        lib.wn_scorer_free.argtypes = [ctypes.c_void_p]
        lib.wn_root.restype = _I64
        lib.wn_root.argtypes = [ctypes.c_void_p, _U8P]
        lib.wn_clone.restype = _I64
        lib.wn_clone.argtypes = [ctypes.c_void_p, _I64]
        lib.wn_free_branch.argtypes = [ctypes.c_void_p, _I64]
        lib.wn_push.argtypes = [
            ctypes.c_void_p, _I64, _U8P, _I64, _I64P, _I64P, _I64P, _U8P,
        ]
        lib.wn_stats.argtypes = lib.wn_push.argtypes
        lib.wn_activate.argtypes = [
            ctypes.c_void_p, _I64, _I64, _I64, _U8P, _I64,
        ]
        lib.wn_deactivate.argtypes = [ctypes.c_void_p, _I64, _I64]
        lib.wn_finalized_eds.argtypes = [
            ctypes.c_void_p, _I64, _U8P, _I64, _I64P,
        ]
        lib.wn_wfa_ed.restype = _I64
        lib.wn_wfa_ed.argtypes = [
            _U8P, _I64, _U8P, _I64, ctypes.c_int, ctypes.c_int,
        ]
        lib.wn_consensus.restype = ctypes.c_int
        lib.wn_consensus.argtypes = [
            _U8P, _I64P, _I64, _I64P, _I64P, ctypes.c_double,
            ctypes.POINTER(_U8P), _I64P,
        ]
        lib.wn_blob_free.argtypes = [_U8P]
        _lib = lib
        return lib


def _bytes_ptr(data: bytes):
    return ctypes.cast(ctypes.create_string_buffer(data, len(data)), _U8P)


def _pack_reads(reads: Sequence[bytes]):
    blob = b"".join(reads)
    lens = np.array([len(r) for r in reads], dtype=np.int64)
    return (
        _bytes_ptr(blob),
        lens.ctypes.data_as(_I64P),
        lens,  # keep alive
    )


class NativeScorer(WavefrontScorer):
    """C++ branch store behind the scorer seam."""

    def __init__(self, reads: Sequence[bytes], config: CdwfaConfig) -> None:
        super().__init__(reads, config)
        self._lib = load_library()
        data_ptr, lens_ptr, self._keep = _pack_reads(self.reads)
        symtab = np.asarray(self.symtab, dtype=np.uint8)
        self._ptr = self._lib.wn_scorer_new(
            data_ptr,
            lens_ptr,
            len(self.reads),
            symtab.ctypes.data_as(_U8P),
            len(symtab),
            -1 if config.wildcard is None else config.wildcard,
            1 if config.allow_early_termination else 0,
        )

    def __del__(self):
        try:
            if getattr(self, "_ptr", None):
                self._lib.wn_scorer_free(self._ptr)
                self._ptr = None
        except Exception:
            pass

    def _out_buffers(self):
        n, a = self.num_reads, self.num_symbols
        eds = np.zeros(n, dtype=np.int64)
        occ = np.zeros((n, a), dtype=np.int64)
        split = np.zeros(n, dtype=np.int64)
        reached = np.zeros(n, dtype=np.uint8)
        return eds, occ, split, reached

    def root(self, active: np.ndarray) -> int:
        act = np.ascontiguousarray(active, dtype=np.uint8)
        return self._lib.wn_root(self._ptr, act.ctypes.data_as(_U8P))

    def clone(self, h: int) -> int:
        return self._lib.wn_clone(self._ptr, h)

    def free(self, h: int) -> None:
        self._lib.wn_free_branch(self._ptr, h)

    def push(self, h: int, consensus: bytes) -> BranchStats:
        eds, occ, split, reached = self._out_buffers()
        self._lib.wn_push(
            self._ptr, h, _bytes_ptr(consensus), len(consensus),
            eds.ctypes.data_as(_I64P), occ.ctypes.data_as(_I64P),
            split.ctypes.data_as(_I64P), reached.ctypes.data_as(_U8P),
        )
        return BranchStats(eds, occ, split, reached.astype(bool))

    def stats(self, h: int, consensus: bytes) -> BranchStats:
        eds, occ, split, reached = self._out_buffers()
        self._lib.wn_stats(
            self._ptr, h, _bytes_ptr(consensus), len(consensus),
            eds.ctypes.data_as(_I64P), occ.ctypes.data_as(_I64P),
            split.ctypes.data_as(_I64P), reached.ctypes.data_as(_U8P),
        )
        return BranchStats(eds, occ, split, reached.astype(bool))

    def activate(self, h: int, read_index: int, offset: int, consensus: bytes) -> None:
        self._lib.wn_activate(
            self._ptr, h, read_index, offset, _bytes_ptr(consensus), len(consensus)
        )

    def deactivate(self, h: int, read_index: int) -> None:
        self._lib.wn_deactivate(self._ptr, h, read_index)

    def finalized_eds(self, h: int, consensus: bytes) -> np.ndarray:
        eds = np.zeros(self.num_reads, dtype=np.int64)
        self._lib.wn_finalized_eds(
            self._ptr, h, _bytes_ptr(consensus), len(consensus),
            eds.ctypes.data_as(_I64P),
        )
        return eds


def native_wfa_ed(
    v1: bytes, v2: bytes, require_both_end: bool = True,
    wildcard: Optional[int] = None,
) -> int:
    lib = load_library()
    return lib.wn_wfa_ed(
        _bytes_ptr(v1), len(v1), _bytes_ptr(v2), len(v2),
        1 if require_both_end else 0,
        -1 if wildcard is None else wildcard,
    )


_ENGINE_ERRORS = {
    1: "Must have at least one initial offset of None to see the consensus.",
    3: "Finalize called on DWFA that was never initialized.",
}


def native_consensus(
    reads: Sequence[bytes],
    offsets: Optional[Sequence[Optional[int]]] = None,
    config: Optional[CdwfaConfig] = None,
) -> List[Tuple[bytes, List[int]]]:
    """Run the full C++ single-consensus engine; returns
    ``[(sequence, scores), ...]`` sorted lexicographically."""
    from waffle_con_tpu.models.consensus import EngineError

    cfg = config if config is not None else CdwfaConfig()
    if offsets is None:
        offsets = [None] * len(reads)
    lib = load_library()
    data_ptr, lens_ptr, _keep = _pack_reads([bytes(r) for r in reads])
    offs = np.array(
        [-1 if o is None else o for o in offsets], dtype=np.int64
    )
    int_cfg = np.array(
        [
            1 if cfg.consensus_cost is ConsensusCost.L2_DISTANCE else 0,
            cfg.max_queue_size,
            cfg.max_capacity_per_size,
            cfg.max_return_size,
            cfg.max_nodes_wo_constraint,
            cfg.min_count,
            -1 if cfg.wildcard is None else cfg.wildcard,
            1 if cfg.allow_early_termination else 0,
            1 if cfg.auto_shift_offsets else 0,
            cfg.offset_window,
            cfg.offset_compare_length,
        ],
        dtype=np.int64,
    )
    blob = _U8P()
    size = _I64(0)
    rc = lib.wn_consensus(
        data_ptr, lens_ptr, len(reads), offs.ctypes.data_as(_I64P),
        int_cfg.ctypes.data_as(_I64P), cfg.min_af,
        ctypes.byref(blob), ctypes.byref(size),
    )
    if rc != 0:
        if rc == 2:
            raise EngineError("Encountered coverage gap")
        raise EngineError(_ENGINE_ERRORS.get(rc, f"native engine error {rc}"))
    try:
        raw = ctypes.string_at(blob, size.value)
    finally:
        lib.wn_blob_free(blob)

    results = []
    pos = 0

    def read_i64():
        nonlocal pos
        (v,) = struct.unpack_from("<q", raw, pos)
        pos += 8
        return v

    n_results = read_i64()
    for _ in range(n_results):
        seq_len = read_i64()
        sequence = raw[pos : pos + seq_len]
        pos += seq_len
        n_scores = read_i64()
        scores = [read_i64() for _ in range(n_scores)]
        results.append((sequence, scores))
    return results
