// Native (C++) kernels and engine for waffle_con_tpu.
//
// Provides the serial-CPU implementation of the framework's two layers:
//   1. the incremental dynamic-WFA kernel + a WavefrontScorer-compatible
//      branch store (exact behavioral parity with ops/dwfa.py — the
//      executable spec — and transitively with the reference
//      /root/reference/src/dynamic_wfa.rs);
//   2. a complete single-consensus search engine (parity with
//      models/consensus.py, i.e. /root/reference/src/consensus.rs) used
//      as the CPU baseline in bench.py.
//
// Wavefronts use centered diagonal coordinates: diagonal k = (other
// consumed) - (baseline consumed) ranges over [-e, +e]; the stored value
// is bases consumed in `other` beyond `offset`; the baseline position of
// a lane is d - k.
//
// Exposed as a C ABI for ctypes (see ../__init__.py).

#include <algorithm>
#include <array>
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <map>
#include <memory>
#include <optional>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using std::size_t;
using i64 = long long;
using Bytes = std::vector<uint8_t>;

// ---------------------------------------------------------------------
// L0: incremental dynamic WFA (parity: ops/dwfa.py::DWFALite)

struct DWFA {
  i64 e = 0;
  std::vector<i64> wf{0};  // index i <-> diagonal k = i - e
  i64 offset = 0;

  void extend(const Bytes& baseline, const Bytes& other, int wildcard) {
    const i64 blen = (i64)baseline.size();
    const i64 olen = (i64)other.size();
    for (size_t i = 0; i < wf.size(); ++i) {
      i64 d = wf[i];
      const i64 k = (i64)i - e;
      i64 bo = d - k;
      i64 oo = d + offset;
      while (bo < blen && oo < olen) {
        const int b = baseline[(size_t)bo];
        if (b != other[(size_t)oo] && b != wildcard) break;
        ++d; ++bo; ++oo;
      }
      wf[i] = d;
    }
  }

  void escalate(const Bytes& baseline, const Bytes& other, int wildcard) {
    const size_t n = wf.size();
    ++e;
    std::vector<i64> nw(n + 2, 0);
    for (size_t i = 0; i < n; ++i) {
      const i64 d = wf[i];
      nw[i] = std::max(nw[i], d);          // baseline deletion
      nw[i + 1] = std::max(nw[i + 1], d + 1);  // mismatch
      nw[i + 2] = std::max(nw[i + 2], d + 1);  // insertion into baseline
    }
    wf.swap(nw);
    extend(baseline, other, wildcard);
  }

  i64 max_other() const {
    i64 m = std::numeric_limits<i64>::min();
    for (i64 d : wf) m = std::max(m, d);
    return offset + m;
  }

  i64 max_baseline() const {
    i64 m = std::numeric_limits<i64>::min();
    for (size_t i = 0; i < wf.size(); ++i) m = std::max(m, wf[i] - ((i64)i - e));
    return m;
  }

  bool reached_end(const Bytes& baseline) const {
    return max_baseline() == (i64)baseline.size();
  }

  void update(const Bytes& baseline, const Bytes& other, int wildcard,
              bool early_term) {
    extend(baseline, other, wildcard);
    const i64 target = (i64)other.size();
    while (max_other() < target && !(early_term && reached_end(baseline))) {
      escalate(baseline, other, wildcard);
    }
  }

  void finalize(const Bytes& baseline, const Bytes& other, int wildcard) {
    const i64 blen = (i64)baseline.size();
    while (max_baseline() < blen) escalate(baseline, other, wildcard);
  }

  // tip votes for the next consensus symbol: lanes that consumed all of
  // `other`, voting the baseline char they face
  void tips(const Bytes& baseline, const Bytes& other,
            std::map<int, i64>& votes) const {
    const i64 olen = (i64)other.size();
    const i64 blen = (i64)baseline.size();
    for (size_t i = 0; i < wf.size(); ++i) {
      const i64 d = wf[i];
      if (d + offset == olen) {
        const i64 bo = d - ((i64)i - e);
        if (bo < blen) votes[baseline[(size_t)bo]] += 1;
      }
    }
  }
};

// one-shot WFA edit distance (parity: ops/alignment.py::wfa_ed_config)
i64 wfa_ed_config(const uint8_t* v1, i64 l1, const uint8_t* v2, i64 l2,
                  bool require_both_end, int wildcard) {
  std::vector<std::pair<i64, i64>> curr{{0, 0}};
  i64 edits = 0;
  for (;;) {
    std::vector<std::pair<i64, i64>> next(2 * edits + 3, {0, 0});
    for (size_t w = 0; w < curr.size(); ++w) {
      i64 i = curr[w].first, j = curr[w].second;
      while (i < l1 && j < l2 &&
             (v1[i] == v2[j] || v1[i] == wildcard || v2[j] == wildcard)) {
        ++i; ++j;
      }
      if (j == l2 && (i == l1 || !require_both_end)) return edits;
      std::pair<i64, i64> a, b, c;
      if (i == l1) {
        a = {i, j}; b = {i, j + 1}; c = {i, j + 1};
      } else if (j == l2) {
        a = {i + 1, j}; b = {i + 1, j}; c = {i, j};
      } else {
        a = {i + 1, j}; b = {i + 1, j + 1}; c = {i, j + 1};
      }
      next[w] = std::max(next[w], a);
      next[w + 1] = std::max(next[w + 1], b);
      next[w + 2] = std::max(next[w + 2], c);
    }
    ++edits;
    curr.swap(next);
  }
}

// ---------------------------------------------------------------------
// scorer branch store (parity: ops/scorer.py::PythonScorer)

struct Scorer {
  std::vector<Bytes> reads;
  std::vector<int> symtab;              // dense id -> byte
  std::array<int, 256> sym_id;          // byte -> dense id (or -1)
  int wildcard = -1;                    // byte value or -1
  bool early_term = false;
  std::unordered_map<i64, std::vector<std::optional<DWFA>>> branches;
  i64 next_handle = 0;

  size_t R() const { return reads.size(); }
  size_t A() const { return symtab.size(); }
};

void scorer_snapshot(Scorer& s, const std::vector<std::optional<DWFA>>& dwfas,
                     const Bytes& cons, i64* eds, i64* occ, i64* split,
                     uint8_t* reached) {
  const size_t R = s.R(), A = s.A();
  std::fill(eds, eds + R, 0);
  std::fill(occ, occ + R * A, 0);
  std::fill(split, split + R, 0);
  std::fill(reached, reached + R, 0);
  std::map<int, i64> votes;
  for (size_t r = 0; r < R; ++r) {
    if (!dwfas[r]) continue;
    const DWFA& dw = *dwfas[r];
    eds[r] = dw.e;
    reached[r] = dw.reached_end(s.reads[r]) ? 1 : 0;
    votes.clear();
    dw.tips(s.reads[r], cons, votes);
    i64 total = 0;
    for (auto& [sym, count] : votes) {
      occ[r * A + s.sym_id[sym]] = count;
      total += count;
    }
    split[r] = total;
  }
}

// ---------------------------------------------------------------------
// single-consensus engine (parity: models/consensus.py::ConsensusDWFA)

struct EngineConfig {
  int cost_l2 = 0;                 // 0 = L1, 1 = L2
  i64 max_queue_size = 20;
  i64 max_capacity_per_size = 20;
  i64 max_return_size = 10;
  i64 max_nodes_wo_constraint = 1000;
  i64 min_count = 3;
  double min_af = 0.0;
  int wildcard = -1;
  int allow_early_termination = 0;
  int auto_shift_offsets = 1;
  i64 offset_window = 50;
  i64 offset_compare_length = 50;
};

struct Tracker {
  std::vector<i64> length_counts, processed_counts;
  i64 total = 0, thr = 0, cap = 0;
  explicit Tracker(size_t n, i64 capacity) : length_counts(n, 0), processed_counts(n, 0), cap(capacity) {}
  void ensure(std::vector<i64>& v, size_t n) { if (v.size() <= n) v.resize(n + 1, 0); }
  void insert(i64 v) { ensure(length_counts, v); length_counts[v]++; if (v >= thr) total++; }
  void remove(i64 v) { length_counts[v]--; if (v >= thr) total--; }
  void inc_threshold() { if ((size_t)thr < length_counts.size()) total -= length_counts[thr]; thr++; }
  bool process(i64 v) { ensure(processed_counts, v); if (processed_counts[v] >= cap) return false; processed_counts[v]++; return true; }
  bool at_capacity(i64 v) const {
    return (size_t)v < processed_counts.size() && processed_counts[v] >= cap;
  }
};

struct Node {
  Bytes consensus;
  std::vector<std::optional<DWFA>> dwfas;
  i64 cost = 0;

  i64 total_cost(bool l2) const {
    i64 t = 0;
    for (auto& d : dwfas)
      if (d) t += l2 ? d->e * d->e : d->e;
    return t;
  }
};

struct Result {
  Bytes sequence;
  std::vector<i64> scores;
};

i64 activation_offset(const Bytes& cons, const Bytes& seq, const EngineConfig& cfg) {
  const i64 cmp = std::min<i64>(cfg.offset_compare_length, (i64)seq.size());
  const i64 clen = (i64)cons.size();
  const i64 start = std::max<i64>(0, clen - (cfg.offset_window + cmp));
  const i64 end = std::max<i64>(0, clen - cmp);
  i64 best = std::max<i64>(0, clen - (cmp + cfg.offset_window / 2));
  i64 best_ed = wfa_ed_config(cons.data() + best, clen - best, seq.data(), cmp,
                              false, cfg.wildcard);
  for (i64 p = start; p < end; ++p) {
    i64 ed = wfa_ed_config(cons.data() + p, clen - p, seq.data(), cmp, false,
                           cfg.wildcard);
    if (ed < best_ed) { best_ed = ed; best = p; }
  }
  return best;
}

// error codes
constexpr int ERR_OK = 0;
constexpr int ERR_NO_INITIAL = 1;       // no initially active sequence
constexpr int ERR_COVERAGE_GAP = 2;     // coverage gap before activation
constexpr int ERR_UNINITIALIZED = 3;    // finalize on inactive DWFA

int run_consensus(const std::vector<Bytes>& reads,
                  const std::vector<i64>& in_offsets,  // -1 = none
                  const EngineConfig& cfg, std::vector<Result>& out) {
  const size_t R = reads.size();
  const bool l2 = cfg.cost_l2 != 0;
  const bool et = cfg.allow_early_termination != 0;

  std::vector<i64> offsets(in_offsets);
  if (cfg.auto_shift_offsets) {
    i64 mn = std::numeric_limits<i64>::max();
    bool have_start = false;
    for (i64 o : offsets) {
      if (o < 0) have_start = true; else mn = std::min(mn, o);
    }
    if (!have_start) {
      for (i64& o : offsets) o = (o == mn) ? -1 : o - mn;
    }
  }

  std::map<i64, std::vector<size_t>> activate_points;
  i64 max_activate = 0;
  size_t initially_active = 0;
  for (size_t i = 0; i < R; ++i) {
    if (offsets[i] >= 0) {
      i64 al = offsets[i] + cfg.offset_compare_length;
      activate_points[al].push_back(i);
      max_activate = std::max(max_activate, al);
    } else {
      ++initially_active;
    }
  }
  if (initially_active == 0) return ERR_NO_INITIAL;

  size_t max_len = 0;
  for (auto& r : reads) max_len = std::max(max_len, r.size());
  Tracker tracker(max_len, cfg.max_capacity_per_size);

  // max-priority: lowest cost, then longest consensus, then FIFO
  struct QKey {
    i64 cost; i64 len; i64 seq;
    bool operator<(const QKey& o) const {
      if (cost != o.cost) return cost < o.cost;
      if (len != o.len) return len > o.len;
      return seq < o.seq;
    }
  };
  std::map<QKey, std::unique_ptr<Node>> queue;
  i64 seq_counter = 0;

  auto root = std::make_unique<Node>();
  root->dwfas.resize(R);
  for (size_t i = 0; i < R; ++i)
    if (offsets[i] < 0) root->dwfas[i].emplace();
  root->cost = 0;
  tracker.insert(0);
  queue.emplace(QKey{0, 0, seq_counter++}, std::move(root));

  i64 maximum_error = std::numeric_limits<i64>::max();
  i64 farthest = 0, last_constraint = 0;
  out.clear();

  while (!queue.empty()) {
    while ((tracker.total > cfg.max_queue_size ||
            last_constraint >= cfg.max_nodes_wo_constraint) &&
           tracker.thr < farthest) {
      tracker.inc_threshold();
      last_constraint = 0;
    }

    auto it = queue.begin();
    std::unique_ptr<Node> node = std::move(it->second);
    const i64 top_cost = it->first.cost;
    queue.erase(it);
    const i64 top_len = (i64)node->consensus.size();
    tracker.remove(top_len);

    if (top_cost > maximum_error || top_len < tracker.thr ||
        tracker.at_capacity(top_len))
      continue;

    farthest = std::max(farthest, top_len);
    ++last_constraint;
    tracker.process(top_len);

    // completion check
    bool any_end = false, all_end = true;
    for (size_t r = 0; r < R; ++r) {
      const bool reached = node->dwfas[r] && node->dwfas[r]->reached_end(reads[r]);
      any_end |= reached;
      all_end &= reached;
    }
    if (et ? all_end : any_end) {
      for (size_t r = 0; r < R; ++r)
        if (!node->dwfas[r]) return ERR_UNINITIALIZED;
      // finalize a scratch copy
      std::vector<i64> fin(R);
      i64 fin_total = 0;
      for (size_t r = 0; r < R; ++r) {
        DWFA scratch = *node->dwfas[r];
        scratch.finalize(reads[r], node->consensus, cfg.wildcard);
        fin[r] = l2 ? scratch.e * scratch.e : scratch.e;
        fin_total += fin[r];
      }
      if (fin_total < maximum_error) {
        maximum_error = fin_total;
        out.clear();
      }
      if (fin_total <= maximum_error && (i64)out.size() < cfg.max_return_size) {
        out.push_back(Result{node->consensus, fin});
      }
    }

    // candidate nomination: fractional votes accumulated in read order
    std::map<int, double> candidates;
    std::map<int, i64> votes;
    for (size_t r = 0; r < R; ++r) {
      if (!node->dwfas[r]) continue;
      votes.clear();
      node->dwfas[r]->tips(reads[r], node->consensus, votes);
      i64 total = 0;
      for (auto& [sym, c] : votes) total += c;
      if (total == 0) continue;
      for (auto& [sym, c] : votes)
        candidates[sym] += (double)c / (double)total;
    }
    if (cfg.wildcard >= 0 && candidates.size() > 1)
      candidates.erase(cfg.wildcard);

    double max_observed = (double)cfg.min_count;
    if (!candidates.empty()) {
      max_observed = -1.0;
      for (auto& [sym, c] : candidates) max_observed = std::max(max_observed, c);
    }
    const double threshold = std::min((double)cfg.min_count, max_observed);

    std::vector<int> passing;
    for (auto& [sym, c] : candidates)
      if (c >= threshold) passing.push_back(sym);

    if (passing.empty()) {
      if (top_len < max_activate) return ERR_COVERAGE_GAP;
      continue;
    }

    for (size_t pi = 0; pi < passing.size(); ++pi) {
      std::unique_ptr<Node> child;
      if (pi + 1 == passing.size()) {
        child = std::move(node);  // move-in-place for the last child
      } else {
        child = std::make_unique<Node>(*node);
      }
      child->consensus.push_back((uint8_t)passing[pi]);
      for (size_t r = 0; r < R; ++r)
        if (child->dwfas[r])
          child->dwfas[r]->update(reads[r], child->consensus, cfg.wildcard, et);

      auto ap = activate_points.find((i64)child->consensus.size());
      if (ap != activate_points.end()) {
        for (size_t r : ap->second) {
          i64 off = activation_offset(child->consensus, reads[r], cfg);
          DWFA dw;
          dw.offset = off;
          dw.update(reads[r], child->consensus, cfg.wildcard, et);
          child->dwfas[r] = std::move(dw);
        }
      }
      const i64 c_cost = child->total_cost(l2);
      const i64 c_len = (i64)child->consensus.size();
      tracker.insert(c_len);
      queue.emplace(QKey{c_cost, c_len, seq_counter++}, std::move(child));
    }
  }

  std::sort(out.begin(), out.end(),
            [](const Result& a, const Result& b) { return a.sequence < b.sequence; });
  return ERR_OK;
}

Scorer* as_scorer(void* p) { return reinterpret_cast<Scorer*>(p); }

}  // namespace

// ---------------------------------------------------------------------
// C ABI

extern "C" {

void* wn_scorer_new(const uint8_t* read_data, const i64* read_lens, i64 n_reads,
                    const uint8_t* symtab, i64 n_symbols, int wildcard,
                    int early_term) {
  auto* s = new Scorer();
  i64 pos = 0;
  for (i64 i = 0; i < n_reads; ++i) {
    s->reads.emplace_back(read_data + pos, read_data + pos + read_lens[i]);
    pos += read_lens[i];
  }
  s->sym_id.fill(-1);
  for (i64 i = 0; i < n_symbols; ++i) {
    s->symtab.push_back(symtab[i]);
    s->sym_id[symtab[i]] = (int)i;
  }
  s->wildcard = wildcard;
  s->early_term = early_term != 0;
  return s;
}

void wn_scorer_free(void* p) { delete as_scorer(p); }

i64 wn_root(void* p, const uint8_t* active) {
  auto* s = as_scorer(p);
  std::vector<std::optional<DWFA>> dwfas(s->R());
  for (size_t r = 0; r < s->R(); ++r)
    if (active[r]) dwfas[r].emplace();
  const i64 h = s->next_handle++;
  s->branches.emplace(h, std::move(dwfas));
  return h;
}

i64 wn_clone(void* p, i64 h) {
  auto* s = as_scorer(p);
  const i64 nh = s->next_handle++;
  s->branches.emplace(nh, s->branches.at(h));
  return nh;
}

void wn_free_branch(void* p, i64 h) { as_scorer(p)->branches.erase(h); }

void wn_push(void* p, i64 h, const uint8_t* cons, i64 clen, i64* eds, i64* occ,
             i64* split, uint8_t* reached) {
  auto* s = as_scorer(p);
  auto& dwfas = s->branches.at(h);
  Bytes consensus(cons, cons + clen);
  for (size_t r = 0; r < s->R(); ++r)
    if (dwfas[r])
      dwfas[r]->update(s->reads[r], consensus, s->wildcard, s->early_term);
  scorer_snapshot(*s, dwfas, consensus, eds, occ, split, reached);
}

void wn_stats(void* p, i64 h, const uint8_t* cons, i64 clen, i64* eds, i64* occ,
              i64* split, uint8_t* reached) {
  auto* s = as_scorer(p);
  Bytes consensus(cons, cons + clen);
  scorer_snapshot(*s, s->branches.at(h), consensus, eds, occ, split, reached);
}

void wn_activate(void* p, i64 h, i64 read_index, i64 offset, const uint8_t* cons,
                 i64 clen) {
  auto* s = as_scorer(p);
  Bytes consensus(cons, cons + clen);
  DWFA dw;
  dw.offset = offset;
  dw.update(s->reads[(size_t)read_index], consensus, s->wildcard, s->early_term);
  s->branches.at(h)[(size_t)read_index] = std::move(dw);
}

void wn_deactivate(void* p, i64 h, i64 read_index) {
  as_scorer(p)->branches.at(h)[(size_t)read_index].reset();
}

void wn_finalized_eds(void* p, i64 h, const uint8_t* cons, i64 clen, i64* eds) {
  auto* s = as_scorer(p);
  Bytes consensus(cons, cons + clen);
  auto& dwfas = s->branches.at(h);
  for (size_t r = 0; r < s->R(); ++r) {
    if (dwfas[r]) {
      DWFA scratch = *dwfas[r];
      scratch.finalize(s->reads[r], consensus, s->wildcard);
      eds[r] = scratch.e;
    } else {
      eds[r] = 0;
    }
  }
}

i64 wn_wfa_ed(const uint8_t* v1, i64 l1, const uint8_t* v2, i64 l2,
              int require_both_end, int wildcard) {
  return wfa_ed_config(v1, l1, v2, l2, require_both_end != 0, wildcard);
}

// Full single-consensus engine.  Returns an error code; on success the
// result blob layout is:
//   i64 n_results; then per result: i64 seq_len, bytes, i64 n_scores,
//   i64 scores[]  (blob malloc'd; free with wn_blob_free)
int wn_consensus(const uint8_t* read_data, const i64* read_lens, i64 n_reads,
                 const i64* offsets,  // -1 = none
                 const i64* int_cfg,  // [cost_l2, max_queue, max_cap, max_ret,
                                      //  max_nodes, min_count, wildcard(-1),
                                      //  early_term, auto_shift, off_window,
                                      //  off_cmp_len]
                 double min_af, uint8_t** out_blob, i64* out_size) {
  std::vector<Bytes> reads;
  i64 pos = 0;
  for (i64 i = 0; i < n_reads; ++i) {
    reads.emplace_back(read_data + pos, read_data + pos + read_lens[i]);
    pos += read_lens[i];
  }
  EngineConfig cfg;
  cfg.cost_l2 = (int)int_cfg[0];
  cfg.max_queue_size = int_cfg[1];
  cfg.max_capacity_per_size = int_cfg[2];
  cfg.max_return_size = int_cfg[3];
  cfg.max_nodes_wo_constraint = int_cfg[4];
  cfg.min_count = int_cfg[5];
  cfg.wildcard = (int)int_cfg[6];
  cfg.allow_early_termination = (int)int_cfg[7];
  cfg.auto_shift_offsets = (int)int_cfg[8];
  cfg.offset_window = int_cfg[9];
  cfg.offset_compare_length = int_cfg[10];
  cfg.min_af = min_af;

  std::vector<i64> offs(offsets, offsets + n_reads);
  std::vector<Result> results;
  int rc = run_consensus(reads, offs, cfg, results);
  if (rc != ERR_OK) return rc;

  i64 size = sizeof(i64);
  for (auto& r : results)
    size += sizeof(i64) * 2 + (i64)r.sequence.size() + sizeof(i64) * (i64)r.scores.size();
  uint8_t* blob = (uint8_t*)malloc((size_t)size);
  uint8_t* w = blob;
  auto put_i64 = [&w](i64 v) { std::memcpy(w, &v, sizeof(i64)); w += sizeof(i64); };
  put_i64((i64)results.size());
  for (auto& r : results) {
    put_i64((i64)r.sequence.size());
    std::memcpy(w, r.sequence.data(), r.sequence.size());
    w += r.sequence.size();
    put_i64((i64)r.scores.size());
    for (i64 v : r.scores) put_i64(v);
  }
  *out_blob = blob;
  *out_size = size;
  return ERR_OK;
}

void wn_blob_free(uint8_t* blob) { free(blob); }

}  // extern "C"
