// Native (C++) kernels and engine for waffle_con_tpu.
//
// Provides the serial-CPU implementation of the framework's two layers:
//   1. the incremental dynamic-WFA kernel + a WavefrontScorer-compatible
//      branch store (exact behavioral parity with ops/dwfa.py — the
//      executable spec — and transitively with the reference
//      /root/reference/src/dynamic_wfa.rs);
//   2. a complete single-consensus search engine (parity with
//      models/consensus.py, i.e. /root/reference/src/consensus.rs) used
//      as the CPU baseline in bench.py.
//
// Wavefronts use centered diagonal coordinates: diagonal k = (other
// consumed) - (baseline consumed) ranges over [-e, +e]; the stored value
// is bases consumed in `other` beyond `offset`; the baseline position of
// a lane is d - k.
//
// Exposed as a C ABI for ctypes (see ../__init__.py).

#include <algorithm>
#include <array>
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <map>
#include <memory>
#include <optional>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

using std::size_t;
using i64 = long long;
using Bytes = std::vector<uint8_t>;

// ---------------------------------------------------------------------
// L0: incremental dynamic WFA (parity: ops/dwfa.py::DWFALite)

struct DWFA {
  i64 e = 0;
  std::vector<i64> wf{0};  // index i <-> diagonal k = i - e
  i64 offset = 0;

  void extend(const Bytes& baseline, const Bytes& other, int wildcard) {
    const i64 blen = (i64)baseline.size();
    const i64 olen = (i64)other.size();
    for (size_t i = 0; i < wf.size(); ++i) {
      i64 d = wf[i];
      const i64 k = (i64)i - e;
      i64 bo = d - k;
      i64 oo = d + offset;
      while (bo < blen && oo < olen) {
        const int b = baseline[(size_t)bo];
        if (b != other[(size_t)oo] && b != wildcard) break;
        ++d; ++bo; ++oo;
      }
      wf[i] = d;
    }
  }

  void escalate(const Bytes& baseline, const Bytes& other, int wildcard) {
    const size_t n = wf.size();
    ++e;
    std::vector<i64> nw(n + 2, 0);
    for (size_t i = 0; i < n; ++i) {
      const i64 d = wf[i];
      nw[i] = std::max(nw[i], d);          // baseline deletion
      nw[i + 1] = std::max(nw[i + 1], d + 1);  // mismatch
      nw[i + 2] = std::max(nw[i + 2], d + 1);  // insertion into baseline
    }
    wf.swap(nw);
    extend(baseline, other, wildcard);
  }

  i64 max_other() const {
    i64 m = std::numeric_limits<i64>::min();
    for (i64 d : wf) m = std::max(m, d);
    return offset + m;
  }

  i64 max_baseline() const {
    i64 m = std::numeric_limits<i64>::min();
    for (size_t i = 0; i < wf.size(); ++i) m = std::max(m, wf[i] - ((i64)i - e));
    return m;
  }

  bool reached_end(const Bytes& baseline) const {
    return max_baseline() == (i64)baseline.size();
  }

  void update(const Bytes& baseline, const Bytes& other, int wildcard,
              bool early_term) {
    extend(baseline, other, wildcard);
    const i64 target = (i64)other.size();
    while (max_other() < target && !(early_term && reached_end(baseline))) {
      escalate(baseline, other, wildcard);
    }
  }

  void finalize(const Bytes& baseline, const Bytes& other, int wildcard) {
    const i64 blen = (i64)baseline.size();
    while (max_baseline() < blen) escalate(baseline, other, wildcard);
  }

  // tip votes for the next consensus symbol: lanes that consumed all of
  // `other`, voting the baseline char they face
  void tips(const Bytes& baseline, const Bytes& other,
            std::map<int, i64>& votes) const {
    const i64 olen = (i64)other.size();
    const i64 blen = (i64)baseline.size();
    for (size_t i = 0; i < wf.size(); ++i) {
      const i64 d = wf[i];
      if (d + offset == olen) {
        const i64 bo = d - ((i64)i - e);
        if (bo < blen) votes[baseline[(size_t)bo]] += 1;
      }
    }
  }
};

// one-shot WFA edit distance (parity: ops/alignment.py::wfa_ed_config)
i64 wfa_ed_config(const uint8_t* v1, i64 l1, const uint8_t* v2, i64 l2,
                  bool require_both_end, int wildcard) {
  std::vector<std::pair<i64, i64>> curr{{0, 0}};
  i64 edits = 0;
  for (;;) {
    std::vector<std::pair<i64, i64>> next(2 * edits + 3, {0, 0});
    for (size_t w = 0; w < curr.size(); ++w) {
      i64 i = curr[w].first, j = curr[w].second;
      while (i < l1 && j < l2 &&
             (v1[i] == v2[j] || v1[i] == wildcard || v2[j] == wildcard)) {
        ++i; ++j;
      }
      if (j == l2 && (i == l1 || !require_both_end)) return edits;
      std::pair<i64, i64> a, b, c;
      if (i == l1) {
        a = {i, j}; b = {i, j + 1}; c = {i, j + 1};
      } else if (j == l2) {
        a = {i + 1, j}; b = {i + 1, j}; c = {i, j};
      } else {
        a = {i + 1, j}; b = {i + 1, j + 1}; c = {i, j + 1};
      }
      next[w] = std::max(next[w], a);
      next[w + 1] = std::max(next[w + 1], b);
      next[w + 2] = std::max(next[w + 2], c);
    }
    ++edits;
    curr.swap(next);
  }
}

// ---------------------------------------------------------------------
// scorer branch store (parity: ops/scorer.py::PythonScorer)

struct Scorer {
  std::vector<Bytes> reads;
  std::vector<int> symtab;              // dense id -> byte
  std::array<int, 256> sym_id;          // byte -> dense id (or -1)
  int wildcard = -1;                    // byte value or -1
  bool early_term = false;
  std::unordered_map<i64, std::vector<std::optional<DWFA>>> branches;
  i64 next_handle = 0;

  size_t R() const { return reads.size(); }
  size_t A() const { return symtab.size(); }
};

void scorer_snapshot(Scorer& s, const std::vector<std::optional<DWFA>>& dwfas,
                     const Bytes& cons, i64* eds, i64* occ, i64* split,
                     uint8_t* reached) {
  const size_t R = s.R(), A = s.A();
  std::fill(eds, eds + R, 0);
  std::fill(occ, occ + R * A, 0);
  std::fill(split, split + R, 0);
  std::fill(reached, reached + R, 0);
  std::map<int, i64> votes;
  for (size_t r = 0; r < R; ++r) {
    if (!dwfas[r]) continue;
    const DWFA& dw = *dwfas[r];
    eds[r] = dw.e;
    reached[r] = dw.reached_end(s.reads[r]) ? 1 : 0;
    votes.clear();
    dw.tips(s.reads[r], cons, votes);
    i64 total = 0;
    for (auto& [sym, count] : votes) {
      occ[r * A + s.sym_id[sym]] = count;
      total += count;
    }
    split[r] = total;
  }
}

// ---------------------------------------------------------------------
// single-consensus engine (parity: models/consensus.py::ConsensusDWFA)

struct EngineConfig {
  int cost_l2 = 0;                 // 0 = L1, 1 = L2
  i64 max_queue_size = 20;
  i64 max_capacity_per_size = 20;
  i64 max_return_size = 10;
  i64 max_nodes_wo_constraint = 1000;
  i64 min_count = 3;
  double min_af = 0.0;
  int wildcard = -1;
  int allow_early_termination = 0;
  int auto_shift_offsets = 1;
  i64 offset_window = 50;
  i64 offset_compare_length = 50;
};

struct Tracker {
  std::vector<i64> length_counts, processed_counts;
  i64 total = 0, thr = 0, cap = 0;
  explicit Tracker(size_t n, i64 capacity) : length_counts(n, 0), processed_counts(n, 0), cap(capacity) {}
  void ensure(std::vector<i64>& v, size_t n) { if (v.size() <= n) v.resize(n + 1, 0); }
  void insert(i64 v) { ensure(length_counts, v); length_counts[v]++; if (v >= thr) total++; }
  void remove(i64 v) { length_counts[v]--; if (v >= thr) total--; }
  void inc_threshold() { if ((size_t)thr < length_counts.size()) total -= length_counts[thr]; thr++; }
  bool process(i64 v) { ensure(processed_counts, v); if (processed_counts[v] >= cap) return false; processed_counts[v]++; return true; }
  bool at_capacity(i64 v) const {
    return (size_t)v < processed_counts.size() && processed_counts[v] >= cap;
  }
};

struct Node {
  Bytes consensus;
  std::vector<std::optional<DWFA>> dwfas;
  i64 cost = 0;

  i64 total_cost(bool l2) const {
    i64 t = 0;
    for (auto& d : dwfas)
      if (d) t += l2 ? d->e * d->e : d->e;
    return t;
  }
};

struct Result {
  Bytes sequence;
  std::vector<i64> scores;
};

i64 activation_offset(const Bytes& cons, const Bytes& seq, const EngineConfig& cfg) {
  const i64 cmp = std::min<i64>(cfg.offset_compare_length, (i64)seq.size());
  const i64 clen = (i64)cons.size();
  const i64 start = std::max<i64>(0, clen - (cfg.offset_window + cmp));
  const i64 end = std::max<i64>(0, clen - cmp);
  i64 best = std::max<i64>(0, clen - (cmp + cfg.offset_window / 2));
  i64 best_ed = wfa_ed_config(cons.data() + best, clen - best, seq.data(), cmp,
                              false, cfg.wildcard);
  for (i64 p = start; p < end; ++p) {
    i64 ed = wfa_ed_config(cons.data() + p, clen - p, seq.data(), cmp, false,
                           cfg.wildcard);
    if (ed < best_ed) { best_ed = ed; best = p; }
  }
  return best;
}

// error codes
constexpr int ERR_OK = 0;
constexpr int ERR_NO_INITIAL = 1;       // no initially active sequence
constexpr int ERR_COVERAGE_GAP = 2;     // coverage gap before activation
constexpr int ERR_UNINITIALIZED = 3;    // finalize on inactive DWFA
constexpr int ERR_REACTIVATION = 4;     // activating an already-active read

// queue priority shared by all engines: lowest cost, then longest
// consensus, then FIFO (matches SetPriorityQueue's (-cost, len) + seq)
struct QKey {
  i64 cost; i64 len; i64 seq;
  bool operator<(const QKey& o) const {
    if (cost != o.cost) return cost < o.cost;
    if (len != o.len) return len > o.len;
    return seq < o.seq;
  }
};

// offset auto-shift (parity: models/consensus.py::shift_offsets)
void shift_offsets_native(std::vector<i64>& offsets, bool auto_shift) {
  if (!auto_shift) return;
  i64 mn = std::numeric_limits<i64>::max();
  bool have_start = false;
  for (i64 o : offsets) {
    if (o < 0) have_start = true; else mn = std::min(mn, o);
  }
  if (!have_start)
    for (i64& o : offsets) o = (o == mn) ? -1 : o - mn;
}

// late-read activation points keyed by consensus length; returns the
// number of initially active reads
size_t build_activate_points(const std::vector<i64>& offsets,
                             i64 offset_compare_length,
                             std::map<i64, std::vector<size_t>>& points,
                             i64* max_activate = nullptr) {
  size_t initially_active = 0;
  for (size_t i = 0; i < offsets.size(); ++i) {
    if (offsets[i] >= 0) {
      const i64 al = offsets[i] + offset_compare_length;
      points[al].push_back(i);
      if (max_activate) *max_activate = std::max(*max_activate, al);
    } else {
      ++initially_active;
    }
  }
  return initially_active;
}

int run_consensus(const std::vector<Bytes>& reads,
                  const std::vector<i64>& in_offsets,  // -1 = none
                  const EngineConfig& cfg, std::vector<Result>& out,
                  i64* gap_info = nullptr) {  // [top_len, max_activate] on
                                              // ERR_COVERAGE_GAP (the
                                              // reference message carries
                                              // both, consensus.rs:305)
  const size_t R = reads.size();
  const bool l2 = cfg.cost_l2 != 0;
  const bool et = cfg.allow_early_termination != 0;

  std::vector<i64> offsets(in_offsets);
  shift_offsets_native(offsets, cfg.auto_shift_offsets != 0);

  std::map<i64, std::vector<size_t>> activate_points;
  i64 max_activate = 0;
  const size_t initially_active = build_activate_points(
      offsets, cfg.offset_compare_length, activate_points, &max_activate);
  if (initially_active == 0) return ERR_NO_INITIAL;

  size_t max_len = 0;
  for (auto& r : reads) max_len = std::max(max_len, r.size());
  Tracker tracker(max_len, cfg.max_capacity_per_size);

  std::map<QKey, std::unique_ptr<Node>> queue;
  i64 seq_counter = 0;

  auto root = std::make_unique<Node>();
  root->dwfas.resize(R);
  for (size_t i = 0; i < R; ++i)
    if (offsets[i] < 0) root->dwfas[i].emplace();
  root->cost = 0;
  tracker.insert(0);
  queue.emplace(QKey{0, 0, seq_counter++}, std::move(root));

  i64 maximum_error = std::numeric_limits<i64>::max();
  i64 farthest = 0, last_constraint = 0;
  out.clear();

  while (!queue.empty()) {
    while ((tracker.total > cfg.max_queue_size ||
            last_constraint >= cfg.max_nodes_wo_constraint) &&
           tracker.thr < farthest) {
      tracker.inc_threshold();
      last_constraint = 0;
    }

    auto it = queue.begin();
    std::unique_ptr<Node> node = std::move(it->second);
    const i64 top_cost = it->first.cost;
    queue.erase(it);
    const i64 top_len = (i64)node->consensus.size();
    tracker.remove(top_len);

    if (top_cost > maximum_error || top_len < tracker.thr ||
        tracker.at_capacity(top_len))
      continue;

    farthest = std::max(farthest, top_len);
    ++last_constraint;
    tracker.process(top_len);

    // completion check
    bool any_end = false, all_end = true;
    for (size_t r = 0; r < R; ++r) {
      const bool reached = node->dwfas[r] && node->dwfas[r]->reached_end(reads[r]);
      any_end |= reached;
      all_end &= reached;
    }
    if (et ? all_end : any_end) {
      for (size_t r = 0; r < R; ++r)
        if (!node->dwfas[r]) return ERR_UNINITIALIZED;
      // finalize a scratch copy
      std::vector<i64> fin(R);
      i64 fin_total = 0;
      for (size_t r = 0; r < R; ++r) {
        DWFA scratch = *node->dwfas[r];
        scratch.finalize(reads[r], node->consensus, cfg.wildcard);
        fin[r] = l2 ? scratch.e * scratch.e : scratch.e;
        fin_total += fin[r];
      }
      if (fin_total < maximum_error) {
        maximum_error = fin_total;
        out.clear();
      }
      if (fin_total <= maximum_error && (i64)out.size() < cfg.max_return_size) {
        out.push_back(Result{node->consensus, fin});
      }
    }

    // candidate nomination: fractional votes accumulated in read order
    std::map<int, double> candidates;
    std::map<int, i64> votes;
    for (size_t r = 0; r < R; ++r) {
      if (!node->dwfas[r]) continue;
      votes.clear();
      node->dwfas[r]->tips(reads[r], node->consensus, votes);
      i64 total = 0;
      for (auto& [sym, c] : votes) total += c;
      if (total == 0) continue;
      for (auto& [sym, c] : votes)
        candidates[sym] += (double)c / (double)total;
    }
    if (cfg.wildcard >= 0 && candidates.size() > 1)
      candidates.erase(cfg.wildcard);

    double max_observed = (double)cfg.min_count;
    if (!candidates.empty()) {
      max_observed = -1.0;
      for (auto& [sym, c] : candidates) max_observed = std::max(max_observed, c);
    }
    const double threshold = std::min((double)cfg.min_count, max_observed);

    std::vector<int> passing;
    for (auto& [sym, c] : candidates)
      if (c >= threshold) passing.push_back(sym);

    if (passing.empty()) {
      if (top_len < max_activate) {
        if (gap_info) {
          gap_info[0] = top_len;
          gap_info[1] = max_activate;
        }
        return ERR_COVERAGE_GAP;
      }
      continue;
    }

    for (size_t pi = 0; pi < passing.size(); ++pi) {
      std::unique_ptr<Node> child;
      if (pi + 1 == passing.size()) {
        child = std::move(node);  // move-in-place for the last child
      } else {
        child = std::make_unique<Node>(*node);
      }
      child->consensus.push_back((uint8_t)passing[pi]);
      for (size_t r = 0; r < R; ++r)
        if (child->dwfas[r])
          child->dwfas[r]->update(reads[r], child->consensus, cfg.wildcard, et);

      auto ap = activate_points.find((i64)child->consensus.size());
      if (ap != activate_points.end()) {
        for (size_t r : ap->second) {
          i64 off = activation_offset(child->consensus, reads[r], cfg);
          DWFA dw;
          dw.offset = off;
          dw.update(reads[r], child->consensus, cfg.wildcard, et);
          child->dwfas[r] = std::move(dw);
        }
      }
      const i64 c_cost = child->total_cost(l2);
      const i64 c_len = (i64)child->consensus.size();
      tracker.insert(c_len);
      queue.emplace(QKey{c_cost, c_len, seq_counter++}, std::move(child));
    }
  }

  std::sort(out.begin(), out.end(),
            [](const Result& a, const Result& b) { return a.sequence < b.sequence; });
  return ERR_OK;
}

// ---------------------------------------------------------------------
// dual-consensus engine (parity: models/dual_consensus.py, i.e.
// /root/reference/src/dual_consensus.rs:240-787)

struct DualEngineConfig : EngineConfig {
  int weighted_by_ed = 0;
  i64 dual_max_ed_delta = 20;
};

struct DualNode {
  bool is_dual = false, lock1 = false, lock2 = false;
  Bytes cons1, cons2;
  std::vector<std::optional<DWFA>> dw1, dw2;

  i64 max_len() const {
    return (i64)std::max(cons1.size(), cons2.size());
  }

  // full-identity key for set-semantics queue dedup (python _DualNode.key):
  // flags, both consensuses, and per-read (active, offset) on both sides
  std::string key() const {
    std::string k;
    k.reserve(cons1.size() + cons2.size() + dw1.size() * 10 + 8);
    k.push_back(is_dual ? '1' : '0');
    k.push_back(lock1 ? '1' : '0');
    k.push_back(lock2 ? '1' : '0');
    auto put = [&k](const Bytes& b) {
      i64 n = (i64)b.size();
      k.append(reinterpret_cast<const char*>(&n), sizeof(n));
      k.append(reinterpret_cast<const char*>(b.data()), b.size());
    };
    put(cons1);
    put(cons2);
    auto put_side = [&k](const std::vector<std::optional<DWFA>>& dws) {
      for (const auto& d : dws) {
        i64 o = d ? d->offset : -1;
        k.append(reinterpret_cast<const char*>(&o), sizeof(o));
        k.push_back(d ? '1' : '0');
      }
    };
    put_side(dw1);
    put_side(dw2);
    return k;
  }

  i64 total_cost(bool l2) const {
    i64 t = 0;
    for (size_t r = 0; r < dw1.size(); ++r) {
      i64 best = -1;
      if (dw1[r]) best = l2 ? dw1[r]->e * dw1[r]->e : dw1[r]->e;
      if (is_dual && dw2[r]) {
        const i64 s2 = l2 ? dw2[r]->e * dw2[r]->e : dw2[r]->e;
        if (best < 0 || s2 < best) best = s2;
      }
      if (best > 0) t += best;
    }
    return t;
  }

  bool is_dual_imbalanced(i64 min_count) const {
    if (!is_dual) return false;
    i64 a1 = 0, a2 = 0;
    for (const auto& d : dw1) a1 += d ? 1 : 0;
    for (const auto& d : dw2) a2 += d ? 1 : 0;
    return a1 < min_count || a2 < min_count;
  }

  bool reached_all_end(const std::vector<Bytes>& reads, bool require_all) const {
    bool any = false, all = true;
    for (size_t r = 0; r < dw1.size(); ++r) {
      const bool p1 = dw1[r] && dw1[r]->reached_end(reads[r]);
      const bool p2 = is_dual && dw2[r] && dw2[r]->reached_end(reads[r]);
      any |= p1 || p2;
      all &= p1 || p2;
    }
    return require_all ? all : any;
  }

  bool reached_consensus_end(const std::vector<Bytes>& reads, bool side1,
                             bool require_all) const {
    if (!side1 && !is_dual) return false;
    const auto& dws = side1 ? dw1 : dw2;
    bool any = false, all = true;
    for (size_t r = 0; r < dws.size(); ++r) {
      const bool f = dws[r] ? dws[r]->reached_end(reads[r]) : require_all;
      any |= f;
      all &= f;
    }
    return require_all ? all : any;
  }

  // fractional candidate votes for one side, reads accumulated in index
  // order (float summation order matches the python engine exactly)
  std::map<int, double> candidates(const std::vector<Bytes>& reads,
                                   int wildcard, bool side1,
                                   bool weighted) const {
    const auto& dws = side1 ? dw1 : dw2;
    const Bytes& cons = side1 ? cons1 : cons2;
    std::map<int, double> cand;
    std::map<int, i64> votes;
    for (size_t r = 0; r < dws.size(); ++r) {
      if (!dws[r]) continue;
      double w = 1.0;
      if (weighted && is_dual) {
        const double min_ed = 0.5;
        const bool h1 = (bool)dw1[r], h2 = (bool)dw2[r];
        if (h1 && h2) {
          const double c1 = std::max((double)dw1[r]->e, min_ed);
          const double c2 = std::max((double)dw2[r]->e, min_ed);
          const double numer = side1 ? c2 : c1;
          w = numer / (c1 + c2);
        } else if ((h1 && side1) || (h2 && !side1)) {
          w = 1.0;
        } else {
          w = 0.0;
        }
      }
      if (w <= 0.0) continue;
      votes.clear();
      dws[r]->tips(reads[r], cons, votes);
      i64 total = 0;
      for (auto& [sym, c] : votes) total += c;
      if (total == 0) continue;
      for (auto& [sym, c] : votes)
        cand[sym] += w * (double)c / (double)total;
    }
    if (wildcard >= 0 && cand.size() > 1) cand.erase(wildcard);
    return cand;
  }
};

struct DualResultC {
  Bytes cons1, cons2;
  bool has2 = false;
  std::vector<uint8_t> is_cons1;
  std::vector<i64> scores1, scores2;      // -1 = untracked (None)
  std::vector<i64> c1_scores, c2_scores;  // grouped per-assigned-read scores
};

// returns false on an attempt to activate an already-active read (the
// reference asserts/panics there: /root/reference/src/dual_consensus.rs:882)
bool dual_activate_sequence(DualNode& node, size_t seq_index,
                            const std::vector<Bytes>& reads,
                            const DualEngineConfig& cfg, bool et) {
  for (int side = 0; side < (node.is_dual ? 2 : 1); ++side) {
    const bool side1 = side == 0;
    const Bytes& cons = side1 ? node.cons1 : node.cons2;
    auto& dws = side1 ? node.dw1 : node.dw2;
    if (dws[seq_index]) return false;
    const i64 off = activation_offset(cons, reads[seq_index], cfg);
    DWFA dw;
    dw.offset = off;
    dw.update(reads[seq_index], cons, cfg.wildcard, et);
    dws[seq_index] = std::move(dw);
  }
  return true;
}

void dual_prune(DualNode& node, i64 ed_delta) {
  if (!node.is_dual) return;
  for (size_t r = 0; r < node.dw1.size(); ++r) {
    if (node.dw1[r] && node.dw2[r]) {
      const i64 e1 = node.dw1[r]->e, e2 = node.dw2[r]->e;
      if (e1 + ed_delta < e2) node.dw2[r].reset();
      else if (e2 + ed_delta < e1) node.dw1[r].reset();
    }
  }
}

// finalize a node into a result; returns false when some read was never
// tracked on either side (ERR_UNINITIALIZED)
bool dual_finalize(const DualNode& node, const std::vector<Bytes>& reads,
                   const DualEngineConfig& cfg, DualResultC& out,
                   i64& total) {
  const size_t R = reads.size();
  const bool l2 = cfg.cost_l2 != 0;
  for (size_t r = 0; r < R; ++r)
    if (!node.dw1[r] && !(node.is_dual && node.dw2[r])) return false;

  std::vector<i64> fin1(R, -1), fin2(R, -1);
  for (size_t r = 0; r < R; ++r) {
    if (node.dw1[r]) {
      DWFA scratch = *node.dw1[r];
      scratch.finalize(reads[r], node.cons1, cfg.wildcard);
      fin1[r] = l2 ? scratch.e * scratch.e : scratch.e;
    }
    if (node.is_dual && node.dw2[r]) {
      DWFA scratch = *node.dw2[r];
      scratch.finalize(reads[r], node.cons2, cfg.wildcard);
      fin2[r] = l2 ? scratch.e * scratch.e : scratch.e;
    }
  }

  std::vector<int> indices(R);
  std::vector<i64> best(R);
  total = 0;
  for (size_t r = 0; r < R; ++r) {
    const bool have1 = fin1[r] >= 0, have2 = fin2[r] >= 0;
    if (have1 && (!have2 || fin1[r] <= fin2[r])) {
      indices[r] = 0;
      best[r] = fin1[r];
    } else {
      indices[r] = 1;
      best[r] = fin2[r];
    }
    total += best[r];
  }

  const bool swap = node.is_dual && node.cons2 < node.cons1;
  out.is_cons1.resize(R);
  for (size_t r = 0; r < R; ++r)
    out.is_cons1[r] = ((indices[r] == 0) != swap) ? 1 : 0;
  out.c1_scores.clear();
  out.c2_scores.clear();
  for (size_t r = 0; r < R; ++r)
    (indices[r] == 0 ? out.c1_scores : out.c2_scores).push_back(best[r]);
  out.has2 = node.is_dual;
  if (swap) {
    out.cons1 = node.cons2;
    out.cons2 = node.cons1;
    out.scores1 = fin2;
    out.scores2 = fin1;
    out.c1_scores.swap(out.c2_scores);
  } else {
    out.cons1 = node.cons1;
    out.cons2 = node.cons2;
    out.scores1 = fin1;
    out.scores2 = fin2;
  }
  if (!node.is_dual) {
    out.cons2.clear();
    out.scores2.assign(R, -1);
  }
  return true;
}

int run_dual_consensus(const std::vector<Bytes>& reads,
                       const std::vector<i64>& in_offsets,  // -1 = none
                       const DualEngineConfig& cfg,
                       std::vector<DualResultC>& out) {
  const size_t R = reads.size();
  const bool l2 = cfg.cost_l2 != 0;
  const bool et = cfg.allow_early_termination != 0;

  std::vector<i64> offsets(in_offsets);
  shift_offsets_native(offsets, cfg.auto_shift_offsets != 0);

  std::map<i64, std::vector<size_t>> activate_points;
  const size_t initially_active = build_activate_points(
      offsets, cfg.offset_compare_length, activate_points);
  if (initially_active == 0) return ERR_NO_INITIAL;

  size_t max_len = 0;
  for (auto& r : reads) max_len = std::max(max_len, r.size());
  Tracker single_tracker(max_len, cfg.max_capacity_per_size);
  Tracker dual_tracker(max_len, cfg.max_capacity_per_size);

  std::map<QKey, std::unique_ptr<DualNode>> queue;
  std::set<std::string> live_keys;
  i64 seq_counter = 0;

  auto queue_child = [&](std::unique_ptr<DualNode> child, Tracker& tracker) {
    const i64 len = child->max_len();
    tracker.insert(len);
    std::string k = child->key();
    if (!live_keys.insert(std::move(k)).second) {
      tracker.remove(len);  // duplicate node: drop it
      return;
    }
    const i64 c = child->total_cost(l2);
    queue.emplace(QKey{c, len, seq_counter++}, std::move(child));
  };

  auto root = std::make_unique<DualNode>();
  root->dw1.resize(R);
  root->dw2.resize(R);
  for (size_t i = 0; i < R; ++i)
    if (offsets[i] < 0) root->dw1[i].emplace();
  queue_child(std::move(root), single_tracker);

  i64 maximum_error = std::numeric_limits<i64>::max();
  i64 farthest_single = 0, farthest_dual = 0;
  i64 single_last_constraint = 0, dual_last_constraint = 0;

  const i64 full_min_count = std::max<i64>(
      cfg.min_count, (i64)std::ceil(cfg.min_af * (double)R));
  std::vector<i64> total_active_count{(i64)initially_active};
  std::vector<i64> active_min_count{std::max<i64>(
      cfg.min_count,
      (i64)std::ceil(cfg.min_af * (double)initially_active))};

  std::vector<std::pair<DualResultC, i64>> results;  // result, total

  while (!queue.empty()) {
    while ((single_tracker.total > cfg.max_queue_size ||
            single_last_constraint >= cfg.max_nodes_wo_constraint) &&
           single_tracker.thr < farthest_single) {
      single_tracker.inc_threshold();
      single_last_constraint = 0;
    }
    while ((dual_tracker.total > cfg.max_queue_size ||
            dual_last_constraint >= cfg.max_nodes_wo_constraint) &&
           dual_tracker.thr < farthest_dual) {
      dual_tracker.inc_threshold();
      dual_last_constraint = 0;
    }

    auto it = queue.begin();
    std::unique_ptr<DualNode> node = std::move(it->second);
    const i64 top_cost = it->first.cost;
    queue.erase(it);
    live_keys.erase(node->key());
    const i64 top_len = node->max_len();

    Tracker& tracker = node->is_dual ? dual_tracker : single_tracker;
    tracker.remove(top_len);
    const i64 threshold_cutoff = tracker.thr;
    const bool at_capacity = tracker.at_capacity(top_len);

    if (top_cost > maximum_error || top_len < threshold_cutoff ||
        at_capacity ||
        node->is_dual_imbalanced(active_min_count[(size_t)top_len]))
      continue;

    if (node->is_dual) {
      farthest_dual = std::max(farthest_dual, top_len);
      ++dual_last_constraint;
      dual_tracker.process(top_len);
    } else {
      farthest_single = std::max(farthest_single, top_len);
      ++single_last_constraint;
      single_tracker.process(top_len);
    }

    // completion check
    if (node->reached_all_end(reads, et)) {
      DualResultC fin;
      i64 fin_total = 0;
      if (!dual_finalize(*node, reads, cfg, fin, fin_total))
        return ERR_UNINITIALIZED;
      bool imbalanced = false;
      if (node->is_dual) {
        i64 c1 = 0;
        for (uint8_t b : fin.is_cons1) c1 += b;
        const i64 c2 = (i64)fin.is_cons1.size() - c1;
        imbalanced = c1 < full_min_count || c2 < full_min_count;
      }
      if (!imbalanced) {
        if (fin_total < maximum_error) {
          maximum_error = fin_total;
          results.clear();
        }
        if (fin_total <= maximum_error &&
            (i64)results.size() < cfg.max_return_size)
          results.emplace_back(std::move(fin), fin_total);
      }
    }

    // dynamic active-count tables
    if ((i64)active_min_count.size() == top_len + 1) {
      i64 new_total = total_active_count[(size_t)top_len];
      auto ap = activate_points.find(top_len);
      if (ap != activate_points.end()) new_total += (i64)ap->second.size();
      total_active_count.push_back(new_total);
      active_min_count.push_back(std::max<i64>(
          cfg.min_count, (i64)std::ceil(cfg.min_af * (double)new_total)));
    }

    // -- expansion ---------------------------------------------------
    const bool weighted = cfg.weighted_by_ed != 0;
    auto ec1 = node->candidates(reads, cfg.wildcard, true, weighted);
    double sum1 = 0.0;
    for (auto& [s, c] : ec1) sum1 += c;
    const i64 min_count1 =
        std::max<i64>(cfg.min_count, (i64)std::ceil(cfg.min_af * sum1));
    double max_observed1 = (double)min_count1;
    if (!ec1.empty()) {
      max_observed1 = -1.0;
      for (auto& [s, c] : ec1) max_observed1 = std::max(max_observed1, c);
    }
    const double active_threshold1 =
        std::min((double)min_count1, max_observed1);

    auto maybe_activate = [&](DualNode& child) -> bool {
      auto ap = activate_points.find(child.max_len());
      if (ap != activate_points.end())
        for (size_t r : ap->second)
          if (!dual_activate_sequence(child, r, reads, cfg, et))
            return false;
      return true;
    };
    auto push_side = [&](DualNode& child, int sym, bool side1) {
      Bytes& cons = side1 ? child.cons1 : child.cons2;
      auto& dws = side1 ? child.dw1 : child.dw2;
      cons.push_back((uint8_t)sym);
      for (size_t r = 0; r < R; ++r)
        if (dws[r]) dws[r]->update(reads[r], cons, cfg.wildcard, et);
    };

    if (node->is_dual) {
      auto ec2 = node->candidates(reads, cfg.wildcard, false, weighted);
      double sum2 = 0.0;
      for (auto& [s, c] : ec2) sum2 += c;
      const i64 min_count2 =
          std::max<i64>(cfg.min_count, (i64)std::ceil(cfg.min_af * sum2));
      double max_observed2 = (double)min_count2;
      if (!ec2.empty()) {
        max_observed2 = -1.0;
        for (auto& [s, c] : ec2) max_observed2 = std::max(max_observed2, c);
      }
      const double active_threshold2 =
          std::min((double)min_count2, max_observed2);

      const bool fin1 = node->reached_consensus_end(reads, true, et);
      const bool fin2 = node->reached_consensus_end(reads, false, et);

      std::vector<int> opt1, opt2;  // -1 encodes None
      if (fin1 || ec1.empty() || node->lock1) opt1.push_back(-1);
      if (!node->lock1)
        for (auto& [sym, c] : ec1)
          if (c >= active_threshold1) opt1.push_back(sym);
      if (fin2 || ec2.empty() || node->lock2) opt2.push_back(-1);
      if (!node->lock2)
        for (auto& [sym, c] : ec2)
          if (c >= active_threshold2) opt2.push_back(sym);

      for (int can1 : opt1) {
        for (int can2 : opt2) {
          if (can1 < 0 && can2 < 0) continue;
          auto child = std::make_unique<DualNode>(*node);
          if (can1 >= 0) push_side(*child, can1, true);
          else child->lock1 = true;
          if (can2 >= 0) push_side(*child, can2, false);
          else child->lock2 = true;
          if (!maybe_activate(*child)) return ERR_REACTIVATION;
          dual_prune(*child, cfg.dual_max_ed_delta);
          queue_child(std::move(child), dual_tracker);
        }
      }
    } else {
      for (auto& [sym, c] : ec1) {
        if (c < active_threshold1) continue;
        auto child = std::make_unique<DualNode>(*node);
        push_side(*child, sym, true);
        if (!maybe_activate(*child)) return ERR_REACTIVATION;
        queue_child(std::move(child), single_tracker);
      }

      // dual splits: unordered pairs of distinct non-wildcard candidates
      // ordered by (-count, sym), gated on two passing min_count1
      std::vector<std::pair<double, int>> sorted_candidates;
      for (auto& [sym, c] : ec1)
        if (sym != cfg.wildcard) sorted_candidates.emplace_back(-c, sym);
      std::sort(sorted_candidates.begin(), sorted_candidates.end());
      i64 num_passing = 0;
      for (auto& [negc, sym] : sorted_candidates)
        if (-negc >= (double)min_count1) ++num_passing;
      if (num_passing > 1) {
        for (size_t i = 0; i < sorted_candidates.size(); ++i) {
          for (size_t j = i + 1; j < sorted_candidates.size(); ++j) {
            auto child = std::make_unique<DualNode>(*node);
            child->is_dual = true;
            child->cons2 = child->cons1;
            child->dw2 = child->dw1;
            push_side(*child, sorted_candidates[i].second, true);
            push_side(*child, sorted_candidates[j].second, false);
            if (!maybe_activate(*child)) return ERR_REACTIVATION;
            dual_prune(*child, cfg.dual_max_ed_delta);
            queue_child(std::move(child), dual_tracker);
          }
        }
      }
    }
  }

  std::stable_sort(
      results.begin(), results.end(), [](const auto& a, const auto& b) {
        if (a.first.cons1 != b.first.cons1)
          return a.first.cons1 < b.first.cons1;
        return a.first.cons2 < b.first.cons2;
      });

  out.clear();
  for (auto& [res, _t] : results) out.push_back(std::move(res));
  if (out.empty()) {
    // empty-consensus fallback (reference warn! path)
    DualResultC fb;
    fb.has2 = false;
    fb.is_cons1.assign(R, 1);
    fb.scores1.assign(R, 0);
    fb.scores2.assign(R, -1);
    fb.c1_scores.assign(R, 0);
    out.push_back(std::move(fb));
  }
  return ERR_OK;
}

// ---------------------------------------------------------------------
// priority consensus: worklist of dual splits over sequence chains
// (parity: models/priority_consensus.py, i.e.
// /root/reference/src/priority_consensus.rs:172-341)

struct PriorityResultC {
  // per group: a chain of (sequence, grouped scores)
  std::vector<std::vector<std::pair<Bytes, std::vector<i64>>>> chains;
  std::vector<i64> indices;
};

int run_priority_consensus(
    const std::vector<std::vector<Bytes>>& chains,       // [read][level]
    const std::vector<std::vector<i64>>& chain_offsets,  // -1 = none
    const std::vector<i64>& seed_groups,                 // -1 = none
    const DualEngineConfig& cfg, PriorityResultC& out) {
  const size_t n_reads = chains.size();
  const size_t max_split_level = chains[0].size();

  std::vector<std::vector<uint8_t>> to_split;
  std::vector<size_t> split_levels;
  std::vector<std::vector<std::pair<Bytes, std::vector<i64>>>> chain_stack;

  std::set<i64> seeds(seed_groups.begin(), seed_groups.end());
  for (i64 seed : seeds) {  // -1 (unseeded) sorts first
    std::vector<uint8_t> inc(n_reads);
    for (size_t i = 0; i < n_reads; ++i) inc[i] = seed_groups[i] == seed;
    to_split.push_back(std::move(inc));
    split_levels.push_back(0);
    chain_stack.emplace_back();
  }

  std::vector<std::vector<std::pair<Bytes, std::vector<i64>>>> consensuses;
  std::vector<std::vector<uint8_t>> assignments;

  while (!to_split.empty()) {
    std::vector<uint8_t> include_set = std::move(to_split.back());
    to_split.pop_back();
    const size_t level = split_levels.back();
    split_levels.pop_back();
    auto chain = std::move(chain_stack.back());
    chain_stack.pop_back();

    std::vector<Bytes> sub_reads;
    std::vector<i64> sub_offsets;
    for (size_t i = 0; i < n_reads; ++i) {
      if (include_set[i]) {
        sub_reads.push_back(chains[i][level]);
        sub_offsets.push_back(chain_offsets[i][level]);
      }
    }
    std::vector<DualResultC> dc;
    const int rc = run_dual_consensus(sub_reads, sub_offsets, cfg, dc);
    if (rc != ERR_OK) return rc;
    DualResultC& chosen = dc[0];

    if (chosen.has2) {
      std::vector<uint8_t> assign1(n_reads, 0), assign2(n_reads, 0);
      size_t ic = 0;
      for (size_t i = 0; i < n_reads; ++i) {
        if (include_set[i]) {
          (chosen.is_cons1[ic] ? assign1 : assign2)[i] = 1;
          ++ic;
        }
      }
      to_split.push_back(std::move(assign1));
      split_levels.push_back(level);
      chain_stack.push_back(chain);  // copy for the first half
      to_split.push_back(std::move(assign2));
      split_levels.push_back(level);
      chain_stack.push_back(std::move(chain));
    } else {
      chain.emplace_back(chosen.cons1, chosen.c1_scores);
      if (level + 1 == max_split_level) {
        consensuses.push_back(std::move(chain));
        assignments.push_back(std::move(include_set));
      } else {
        to_split.push_back(std::move(include_set));
        split_levels.push_back(level + 1);
        chain_stack.push_back(std::move(chain));
      }
    }
  }

  out.chains.clear();
  out.indices.assign(n_reads, 0);
  if (consensuses.size() > 1) {
    std::vector<size_t> order(consensuses.size());
    for (size_t i = 0; i < order.size(); ++i) order[i] = i;
    std::stable_sort(order.begin(), order.end(), [&](size_t a, size_t b) {
      const auto& ca = consensuses[a];
      const auto& cb = consensuses[b];
      for (size_t l = 0; l < ca.size() && l < cb.size(); ++l) {
        if (ca[l].first != cb[l].first) return ca[l].first < cb[l].first;
      }
      return ca.size() < cb.size();
    });
    out.indices.assign(n_reads, -1);
    for (size_t new_index = 0; new_index < order.size(); ++new_index) {
      const size_t old_index = order[new_index];
      for (size_t i = 0; i < n_reads; ++i)
        if (assignments[old_index][i]) out.indices[i] = (i64)new_index;
      out.chains.push_back(std::move(consensuses[old_index]));
    }
  } else {
    out.chains = std::move(consensuses);
  }
  return ERR_OK;
}

Scorer* as_scorer(void* p) { return reinterpret_cast<Scorer*>(p); }

void parse_dual_config(const i64* int_cfg, double min_af,
                       DualEngineConfig& cfg) {
  cfg.cost_l2 = (int)int_cfg[0];
  cfg.max_queue_size = int_cfg[1];
  cfg.max_capacity_per_size = int_cfg[2];
  cfg.max_return_size = int_cfg[3];
  cfg.max_nodes_wo_constraint = int_cfg[4];
  cfg.min_count = int_cfg[5];
  cfg.wildcard = (int)int_cfg[6];
  cfg.allow_early_termination = (int)int_cfg[7];
  cfg.auto_shift_offsets = (int)int_cfg[8];
  cfg.offset_window = int_cfg[9];
  cfg.offset_compare_length = int_cfg[10];
  cfg.weighted_by_ed = (int)int_cfg[11];
  cfg.dual_max_ed_delta = int_cfg[12];
  cfg.min_af = min_af;
}

struct BlobWriter {
  std::vector<uint8_t> buf;
  void put_i64(i64 v) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
    buf.insert(buf.end(), p, p + sizeof(i64));
  }
  void put_bytes(const Bytes& b) {
    put_i64((i64)b.size());
    buf.insert(buf.end(), b.begin(), b.end());
  }
  void put_vec(const std::vector<i64>& v) {
    put_i64((i64)v.size());
    for (i64 x : v) put_i64(x);
  }
  uint8_t* release(i64* out_size) {
    uint8_t* blob = (uint8_t*)malloc(buf.size());
    std::memcpy(blob, buf.data(), buf.size());
    *out_size = (i64)buf.size();
    return blob;
  }
};

void write_dual_results(const std::vector<DualResultC>& results,
                        BlobWriter& w) {
  w.put_i64((i64)results.size());
  for (const auto& res : results) {
    w.put_bytes(res.cons1);
    w.put_i64(res.has2 ? 1 : 0);
    if (res.has2) w.put_bytes(res.cons2);
    w.put_i64((i64)res.is_cons1.size());
    for (uint8_t b : res.is_cons1) w.put_i64(b);
    w.put_vec(res.scores1);
    w.put_vec(res.scores2);
    w.put_vec(res.c1_scores);
    w.put_vec(res.c2_scores);
  }
}

}  // namespace

// ---------------------------------------------------------------------
// C ABI

extern "C" {

void* wn_scorer_new(const uint8_t* read_data, const i64* read_lens, i64 n_reads,
                    const uint8_t* symtab, i64 n_symbols, int wildcard,
                    int early_term) {
  auto* s = new Scorer();
  i64 pos = 0;
  for (i64 i = 0; i < n_reads; ++i) {
    s->reads.emplace_back(read_data + pos, read_data + pos + read_lens[i]);
    pos += read_lens[i];
  }
  s->sym_id.fill(-1);
  for (i64 i = 0; i < n_symbols; ++i) {
    s->symtab.push_back(symtab[i]);
    s->sym_id[symtab[i]] = (int)i;
  }
  s->wildcard = wildcard;
  s->early_term = early_term != 0;
  return s;
}

void wn_scorer_free(void* p) { delete as_scorer(p); }

i64 wn_root(void* p, const uint8_t* active) {
  auto* s = as_scorer(p);
  std::vector<std::optional<DWFA>> dwfas(s->R());
  for (size_t r = 0; r < s->R(); ++r)
    if (active[r]) dwfas[r].emplace();
  const i64 h = s->next_handle++;
  s->branches.emplace(h, std::move(dwfas));
  return h;
}

i64 wn_clone(void* p, i64 h) {
  auto* s = as_scorer(p);
  const i64 nh = s->next_handle++;
  s->branches.emplace(nh, s->branches.at(h));
  return nh;
}

void wn_free_branch(void* p, i64 h) { as_scorer(p)->branches.erase(h); }

void wn_push(void* p, i64 h, const uint8_t* cons, i64 clen, i64* eds, i64* occ,
             i64* split, uint8_t* reached) {
  auto* s = as_scorer(p);
  auto& dwfas = s->branches.at(h);
  Bytes consensus(cons, cons + clen);
  for (size_t r = 0; r < s->R(); ++r)
    if (dwfas[r])
      dwfas[r]->update(s->reads[r], consensus, s->wildcard, s->early_term);
  scorer_snapshot(*s, dwfas, consensus, eds, occ, split, reached);
}

void wn_stats(void* p, i64 h, const uint8_t* cons, i64 clen, i64* eds, i64* occ,
              i64* split, uint8_t* reached) {
  auto* s = as_scorer(p);
  Bytes consensus(cons, cons + clen);
  scorer_snapshot(*s, s->branches.at(h), consensus, eds, occ, split, reached);
}

void wn_activate(void* p, i64 h, i64 read_index, i64 offset, const uint8_t* cons,
                 i64 clen) {
  auto* s = as_scorer(p);
  Bytes consensus(cons, cons + clen);
  DWFA dw;
  dw.offset = offset;
  dw.update(s->reads[(size_t)read_index], consensus, s->wildcard, s->early_term);
  s->branches.at(h)[(size_t)read_index] = std::move(dw);
}

void wn_deactivate(void* p, i64 h, i64 read_index) {
  as_scorer(p)->branches.at(h)[(size_t)read_index].reset();
}

void wn_finalized_eds(void* p, i64 h, const uint8_t* cons, i64 clen, i64* eds) {
  auto* s = as_scorer(p);
  Bytes consensus(cons, cons + clen);
  auto& dwfas = s->branches.at(h);
  for (size_t r = 0; r < s->R(); ++r) {
    if (dwfas[r]) {
      DWFA scratch = *dwfas[r];
      scratch.finalize(s->reads[r], consensus, s->wildcard);
      eds[r] = scratch.e;
    } else {
      eds[r] = 0;
    }
  }
}

i64 wn_wfa_ed(const uint8_t* v1, i64 l1, const uint8_t* v2, i64 l2,
              int require_both_end, int wildcard) {
  return wfa_ed_config(v1, l1, v2, l2, require_both_end != 0, wildcard);
}

// Full single-consensus engine.  Returns an error code; on success the
// result blob layout is:
//   i64 n_results; then per result: i64 seq_len, bytes, i64 n_scores,
//   i64 scores[]  (blob malloc'd; free with wn_blob_free)
int wn_consensus(const uint8_t* read_data, const i64* read_lens, i64 n_reads,
                 const i64* offsets,  // -1 = none
                 const i64* int_cfg,  // [cost_l2, max_queue, max_cap, max_ret,
                                      //  max_nodes, min_count, wildcard(-1),
                                      //  early_term, auto_shift, off_window,
                                      //  off_cmp_len]
                 double min_af, uint8_t** out_blob, i64* out_size) {
  std::vector<Bytes> reads;
  i64 pos = 0;
  for (i64 i = 0; i < n_reads; ++i) {
    reads.emplace_back(read_data + pos, read_data + pos + read_lens[i]);
    pos += read_lens[i];
  }
  EngineConfig cfg;
  cfg.cost_l2 = (int)int_cfg[0];
  cfg.max_queue_size = int_cfg[1];
  cfg.max_capacity_per_size = int_cfg[2];
  cfg.max_return_size = int_cfg[3];
  cfg.max_nodes_wo_constraint = int_cfg[4];
  cfg.min_count = int_cfg[5];
  cfg.wildcard = (int)int_cfg[6];
  cfg.allow_early_termination = (int)int_cfg[7];
  cfg.auto_shift_offsets = (int)int_cfg[8];
  cfg.offset_window = int_cfg[9];
  cfg.offset_compare_length = int_cfg[10];
  cfg.min_af = min_af;

  std::vector<i64> offs(offsets, offsets + n_reads);
  std::vector<Result> results;
  i64 gap[2] = {0, 0};
  int rc = run_consensus(reads, offs, cfg, results, gap);
  if (rc != ERR_OK) {
    if (rc == ERR_COVERAGE_GAP && out_blob != nullptr) {
      // error-detail blob: the two i64s the reference interpolates into
      // its coverage-gap message (consensus.rs:305)
      uint8_t* detail = (uint8_t*)malloc(2 * sizeof(i64));
      std::memcpy(detail, gap, 2 * sizeof(i64));
      *out_blob = detail;
      *out_size = 2 * sizeof(i64);
    }
    return rc;
  }

  i64 size = sizeof(i64);
  for (auto& r : results)
    size += sizeof(i64) * 2 + (i64)r.sequence.size() + sizeof(i64) * (i64)r.scores.size();
  uint8_t* blob = (uint8_t*)malloc((size_t)size);
  uint8_t* w = blob;
  auto put_i64 = [&w](i64 v) { std::memcpy(w, &v, sizeof(i64)); w += sizeof(i64); };
  put_i64((i64)results.size());
  for (auto& r : results) {
    put_i64((i64)r.sequence.size());
    std::memcpy(w, r.sequence.data(), r.sequence.size());
    w += r.sequence.size();
    put_i64((i64)r.scores.size());
    for (i64 v : r.scores) put_i64(v);
  }
  *out_blob = blob;
  *out_size = size;
  return ERR_OK;
}

// Full dual-consensus engine.  int_cfg layout: [cost_l2, max_queue,
// max_cap, max_ret, max_nodes, min_count, wildcard(-1), early_term,
// auto_shift, off_window, off_cmp_len, weighted_by_ed, dual_max_ed_delta].
// Result blob: i64 n_results; per result: bytes cons1, i64 has2,
// [bytes cons2], i64 n, i64 is_cons1[n], vec scores1, vec scores2,
// vec c1_scores, vec c2_scores (vec = i64 len + payload; bytes = i64 len
// + raw).  Scores use -1 for "untracked".
int wn_dual_consensus(const uint8_t* read_data, const i64* read_lens,
                      i64 n_reads, const i64* offsets, const i64* int_cfg,
                      double min_af, uint8_t** out_blob, i64* out_size) {
  std::vector<Bytes> reads;
  i64 pos = 0;
  for (i64 i = 0; i < n_reads; ++i) {
    reads.emplace_back(read_data + pos, read_data + pos + read_lens[i]);
    pos += read_lens[i];
  }
  DualEngineConfig cfg;
  parse_dual_config(int_cfg, min_af, cfg);
  std::vector<i64> offs(offsets, offsets + n_reads);
  std::vector<DualResultC> results;
  const int rc = run_dual_consensus(reads, offs, cfg, results);
  if (rc != ERR_OK) return rc;
  BlobWriter w;
  write_dual_results(results, w);
  *out_blob = w.release(out_size);
  return ERR_OK;
}

// Full priority (chained multi) consensus engine over the dual engine.
// Chains arrive flattened read-major: chain_lens has n_reads * n_levels
// entries.  Result blob: i64 n_groups; per group: i64 n_levels, per
// level: bytes sequence + vec scores; then vec sequence_indices.
int wn_priority_consensus(const uint8_t* chain_data, const i64* chain_lens,
                          i64 n_reads, i64 n_levels, const i64* offsets,
                          const i64* seed_groups, const i64* int_cfg,
                          double min_af, uint8_t** out_blob, i64* out_size) {
  std::vector<std::vector<Bytes>> chains((size_t)n_reads);
  std::vector<std::vector<i64>> chain_offsets((size_t)n_reads);
  i64 pos = 0;
  for (i64 i = 0; i < n_reads; ++i) {
    for (i64 l = 0; l < n_levels; ++l) {
      const i64 len = chain_lens[i * n_levels + l];
      chains[(size_t)i].emplace_back(chain_data + pos, chain_data + pos + len);
      chain_offsets[(size_t)i].push_back(offsets[i * n_levels + l]);
      pos += len;
    }
  }
  std::vector<i64> seeds(seed_groups, seed_groups + n_reads);
  DualEngineConfig cfg;
  parse_dual_config(int_cfg, min_af, cfg);
  PriorityResultC res;
  const int rc = run_priority_consensus(chains, chain_offsets, seeds, cfg, res);
  if (rc != ERR_OK) return rc;
  BlobWriter w;
  w.put_i64((i64)res.chains.size());
  for (const auto& chain : res.chains) {
    w.put_i64((i64)chain.size());
    for (const auto& [seq, scores] : chain) {
      w.put_bytes(seq);
      w.put_vec(scores);
    }
  }
  w.put_vec(res.indices);
  *out_blob = w.release(out_size);
  return ERR_OK;
}

void wn_blob_free(uint8_t* blob) { free(blob); }

}  // extern "C"
