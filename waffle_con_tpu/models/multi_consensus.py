"""Multi-consensus result type (the algorithm it once belonged to is
superseded by the priority engine; parity with
``/root/reference/src/multi_consensus.rs:11-65``)."""

from __future__ import annotations

from typing import List

from waffle_con_tpu.models.consensus import Consensus


class MultiConsensus:
    """A set of consensuses plus, per input read, the index of the
    consensus it was assigned to.  Construction sorts the consensuses
    lexicographically and remaps the indices to match."""

    __slots__ = ("consensuses", "sequence_indices")

    def __init__(
        self, consensuses: List[Consensus], sequence_indices: List[int]
    ) -> None:
        order = sorted(range(len(consensuses)), key=lambda i: consensuses[i].sequence)
        reverse_lookup = [0] * len(consensuses)
        for new_index, old_index in enumerate(order):
            reverse_lookup[old_index] = new_index
        self.consensuses = [consensuses[i] for i in order]
        self.sequence_indices = [reverse_lookup[i] for i in sequence_indices]

    def __eq__(self, rhs) -> bool:
        return (
            isinstance(rhs, MultiConsensus)
            and self.consensuses == rhs.consensuses
            and self.sequence_indices == rhs.sequence_indices
        )

    def __repr__(self) -> str:
        return (
            f"MultiConsensus(consensuses={self.consensuses!r}, "
            f"sequence_indices={self.sequence_indices})"
        )
